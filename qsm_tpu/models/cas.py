"""CAS register — milestone config #3 (BASELINE.json:9).

A register with read / write / compare-and-swap.  CAS packs ``(old, new)``
into one integer argument (``old * n_values + new``) so the spec stays inside
the framework's integer command encoding (SURVEY.md §7 design stance).  The
bug this config exists to catch is the non-atomic CAS (read, compare on the
client, then write) — the classic lost-update race.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

READ = 0
WRITE = 1
CAS = 2


class CasSpec(Spec):
    """Atomic register with compare-and-swap over values [0, n_values).

    Model state: ``[value]``.  CAS(old, new): responds 1 and sets ``new``
    iff ``value == old``, else responds 0 and leaves the value unchanged.
    """

    name = "cas"
    STATE_DIM = 1

    def __init__(self, n_values: int = 5):
        self.n_values = n_values
        self.CMDS = (
            CmdSig("read", n_args=1, n_resps=n_values),
            CmdSig("write", n_args=n_values, n_resps=1),
            CmdSig("cas", n_args=n_values * n_values, n_resps=2),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(1, np.int32)

    def cas_arg(self, old: int, new: int) -> int:
        return old * self.n_values + new

    def scalar_state_bound(self, n_ops):
        return self.n_values  # state is always a stored value

    def spec_kwargs(self):
        return {"n_values": self.n_values}

    def step_py(self, state, cmd, arg, resp):
        value = state[0]
        if cmd == READ:
            return [value], resp == value
        if cmd == WRITE:
            return [arg], resp == 0
        old, new = divmod(arg, self.n_values)
        if value == old:
            return [new], resp == 1
        return [value], resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        value = state[0]
        old = arg // self.n_values
        new = arg % self.n_values
        succ = value == old
        ok = jnp.where(
            cmd == READ, resp == value,
            jnp.where(cmd == WRITE, resp == 0,
                      resp == succ.astype(resp.dtype)))
        new_value = jnp.where(
            cmd == WRITE, arg,
            jnp.where((cmd == CAS) & succ, new, value))
        return jnp.stack([new_value.astype(state.dtype)]), ok

    def gen_cmd(self, rng, state=None):
        """Bias CAS's expected value toward the (approximate) current model
        value half the time, so generated CASes actually succeed often enough
        to exercise the lost-update race."""
        cmd = rng.randrange(len(self.CMDS))
        if cmd == CAS:
            new = rng.randrange(self.n_values)
            if state is not None and rng.random() < 0.5:
                old = int(state[0])
            else:
                old = rng.randrange(self.n_values)
            return CAS, self.cas_arg(old, new)
        return cmd, rng.randrange(self.CMDS[cmd].n_args)


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _cas_server(store: dict):
    """Server applying read/write/cas atomically per message."""
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        if kind == "read":
            yield Send(msg.src, store["value"])
        elif kind == "write":
            store["value"] = rest[0]
            yield Send(msg.src, 0)
        elif kind == "cas":
            old, new = rest
            if store["value"] == old:
                store["value"] = new
                yield Send(msg.src, 1)
            else:
                yield Send(msg.src, 0)


class AtomicCasSUT:
    """Correct: CAS is one server message, applied atomically.
    Expected to PASS prop_concurrent."""

    def setup(self, sched: Scheduler) -> None:
        self.store = {"value": 0}
        sched.spawn("server", _cas_server(self.store), daemon=True)

    def __init__(self, spec: CasSpec):
        self.spec = spec

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == READ:
            yield Send("server", ("read",))
        elif cmd == WRITE:
            yield Send("server", ("write", arg))
        else:
            old, new = divmod(arg, self.spec.n_values)
            yield Send("server", ("cas", old, new))
        msg = yield Recv()
        return msg.payload


class RacyCasSUT:
    """Racy: CAS is read-compare-write as separate round trips; a concurrent
    write between the read and the write is silently clobbered (lost update)
    and the CAS still reports success.  Expected to FAIL."""

    def setup(self, sched: Scheduler) -> None:
        self.store = {"value": 0}
        sched.spawn("server", _cas_server(self.store), daemon=True)

    def __init__(self, spec: CasSpec):
        self.spec = spec

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == READ:
            yield Send("server", ("read",))
            msg = yield Recv()
            return msg.payload
        if cmd == WRITE:
            yield Send("server", ("write", arg))
            msg = yield Recv()
            return msg.payload
        old, new = divmod(arg, self.spec.n_values)
        yield Send("server", ("read",))
        msg = yield Recv()
        if msg.payload != old:
            return 0
        # non-atomic: the compare happened client-side; another pid's write
        # can land before this write does
        yield Send("server", ("write", new))
        yield Recv()
        return 1
