"""Multi-key transactions — the DELIBERATELY non-decomposable family
(ISSUE 17; P-compositionality refusal exercised as a feature).

``TxnRegisterSpec`` looks exactly like ``MultiRegisterSpec`` — per-cell
reads and writes with the same declarative :class:`~qsm_tpu.core.spec.
KeyProj` tags — plus one multi-key op: ``copy(src, dst)`` reads cell
``src`` and writes its value into cell ``dst`` in one atomic step.  The
copy also DECLARES a KeyProj (keyed by ``src``), so on paper the spec
advertises per-key decomposability; in truth a copy couples two keys —
its write to ``dst`` is a change outside its declared key's component,
and the value it writes depends on state the projected register can
never see.

That makes this family the compile-time validator's showcase:
``projection_report`` (core/spec.py) fails it on the independence probe
("step leaks into keys […]") and every decomposition consumer refuses
with that report as the why stamp — ``PComp`` raises
``NotDecomposableError``, the planner stamps ``decompose_keys=off
(refused: …)``, the serve plane stamps ``pcomp=off (refused: …)``
(pinned in tests/test_models_gen.py).  Whole-history checking remains
fully sound — refusal costs speed, never verdicts.  The deliberate
QSM-SPEC-PCOMP finding is whitelisted in ``.qsmlint`` with this
rationale.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, KeyProj, Spec
from ..sched.scheduler import Recv, Scheduler, Send

READ = 0
WRITE = 1
COPY = 2


class TxnRegisterSpec(Spec):
    """``n_cells`` registers over values [0, n_values) with a cross-cell
    copy.

    READ(cell) responds the cell's value; WRITE packs ``cell * n_values
    + v`` and responds 0; COPY packs the ``src != dst`` pair as
    ``src * (n_cells - 1) + off`` (``off`` skipping the diagonal), sets
    ``dst := value(src)`` and responds 0.  ``2 <= n_cells <= n_values``
    is required so the copy's (bogus) projection passes the DOMAIN
    checks and the refusal stamp is the interesting one — the
    independence failure ("step leaks into keys […]"), not a packing
    arithmetic error.
    """

    name = "txn"

    def __init__(self, n_cells: int = 4, n_values: int = 4):
        if not 2 <= n_cells <= n_values:
            raise ValueError(
                "need 2 <= n_cells <= n_values (see docstring)")
        self.n_cells = n_cells
        self.n_values = n_values
        self.STATE_DIM = n_cells
        self.CMDS = (
            CmdSig("read", n_args=n_cells, n_resps=n_values,
                   proj=KeyProj(pcmd=READ, stride=1)),
            CmdSig("write", n_args=n_cells * n_values, n_resps=1,
                   proj=KeyProj(pcmd=WRITE, stride=n_values)),
            # the lie: copy claims to be a per-src-key op projecting
            # onto a register write, but its step mutates dst — the
            # validator's independence probe catches exactly this
            CmdSig("copy", n_args=n_cells * (n_cells - 1), n_resps=1,
                   proj=KeyProj(pcmd=WRITE, stride=n_cells - 1)),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(self.n_cells, np.int32)

    def write_arg(self, cell: int, value: int) -> int:
        return cell * self.n_values + value

    def copy_arg(self, src: int, dst: int) -> int:
        off = dst - 1 if dst > src else dst  # diagonal excluded
        return src * (self.n_cells - 1) + off

    def copy_pair(self, arg: int):
        src, off = divmod(arg, self.n_cells - 1)
        return src, off + 1 if off >= src else off

    def spec_kwargs(self):
        return {"n_cells": self.n_cells, "n_values": self.n_values}

    def state_elem_bounds(self):
        return [self.n_values] * self.n_cells

    def projected_spec(self):
        from .register import RegisterSpec

        return RegisterSpec(n_values=self.n_values)

    def step_py(self, state, cmd, arg, resp):
        state = list(state)
        if cmd == READ:
            return state, resp == state[arg]
        if cmd == WRITE:
            cell, value = divmod(arg, self.n_values)
            state[cell] = value
            return state, resp == 0
        src, dst = self.copy_pair(arg)
        state[dst] = state[src]
        return state, resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        iota = jnp.arange(self.n_cells)
        is_read = cmd == READ
        is_write = cmd == WRITE
        w_cell = arg // self.n_values
        w_val = arg % self.n_values
        src = arg // (self.n_cells - 1)
        off = arg % (self.n_cells - 1)
        dst = jnp.where(off >= src, off + 1, off)
        cell = jnp.where(is_read, arg, jnp.where(is_write, w_cell, dst))
        value = jnp.where(is_write, w_val, state[src])
        ok = jnp.where(is_read, resp == state[arg], resp == 0)
        new_state = jnp.where(~is_read & (iota == cell), value, state)
        return new_state.astype(state.dtype), ok


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _txn_server(store: dict):
    """One server applying read/write/copy per message, atomically."""
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        if kind == "read":
            yield Send(msg.src, store.get(rest[0], 0))
        elif kind == "write":
            cell, value = rest
            store[cell] = value
            yield Send(msg.src, 0)
        else:  # copy, atomic server-side
            src, dst = rest
            store[dst] = store.get(src, 0)
            yield Send(msg.src, 0)


class AtomicTxnSUT:
    """Correct: the copy is one server message — read-then-write applied
    atomically.  Expected to PASS prop_concurrent."""

    def __init__(self, spec: TxnRegisterSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        sched.spawn("server", _txn_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == READ:
            yield Send("server", ("read", arg))
        elif cmd == WRITE:
            cell, value = divmod(arg, self.spec.n_values)
            yield Send("server", ("write", cell, value))
        else:
            src, dst = self.spec.copy_pair(arg)
            yield Send("server", ("copy", src, dst))
        msg = yield Recv()
        return msg.payload


class TornCopyTxnSUT:
    """Racy: copy is read-src-then-write-dst as separate round trips —
    a write to ``src`` that lands in between makes the copy install a
    value no atomic copy could have observed at any single point
    (stale-read torn transaction).  Expected to FAIL."""

    def __init__(self, spec: TxnRegisterSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        sched.spawn("server", _txn_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == READ:
            yield Send("server", ("read", arg))
            msg = yield Recv()
            return msg.payload
        if cmd == WRITE:
            cell, value = divmod(arg, self.spec.n_values)
            yield Send("server", ("write", cell, value))
            msg = yield Recv()
            return msg.payload
        src, dst = self.spec.copy_pair(arg)
        yield Send("server", ("read", src))
        msg = yield Recv()
        # non-atomic: the source read happened in its own round trip;
        # a write to src can land before this dst write does
        yield Send("server", ("write", dst, msg.payload))
        yield Recv()
        return 0
