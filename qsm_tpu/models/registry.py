"""Model registry — names → (spec factory, SUT implementations).

The CLI and regression files refer to specs/SUTs by name; everything needed
to reproduce a run is then (model, impl, seed, config) — the reference's
"every artifact derivable from (seed, config)" philosophy (SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict

from .cas import AtomicCasSUT, CasSpec, RacyCasSUT
from .counter import AtomicTicketSUT, RacyTicketSUT, TicketSpec
from .kv import AtomicKvSUT, KvSpec, StaleCacheKvSUT
from .queue import AtomicQueueSUT, QueueSpec, RacyTwoPhaseQueueSUT
from .register import (AtomicRegisterSUT, RacyCachedRegisterSUT,
                       RegisterSpec, ReplicatedRegisterSUT)
from .failover import AsyncReplFailoverSUT, SyncReplFailoverSUT
from .multi import (AtomicMultiCasSUT, AtomicMultiRegisterSUT,
                    MultiCasSpec, MultiRegisterSpec, RacyMultiCasSUT,
                    ShardedStaleMultiRegisterSUT)
from .lock import (AtomicSemaphoreSUT, RacyCheckThenActSemaphoreSUT,
                   SemaphoreSpec)
from .rangeset import (AtomicRangeSetSUT, RangeSetSpec,
                       ScanningRangeSetSUT)
from .set import AtomicSetSUT, RacyCheckThenActSetSUT, SetSpec
from .stack import AtomicStackSUT, RacyTwoPhaseStackSUT, StackSpec
from .txn import AtomicTxnSUT, TornCopyTxnSUT, TxnRegisterSpec


@dataclasses.dataclass(frozen=True)
class ModelEntry:
    make_spec: Callable[[], object]
    impls: Dict[str, Callable]  # impl name -> SUT factory (takes spec)
    default_pids: int
    default_ops: int


def _no_spec(cls):
    return lambda spec: cls()


MODELS: Dict[str, ModelEntry] = {
    "register": ModelEntry(
        make_spec=RegisterSpec,
        impls={"atomic": _no_spec(AtomicRegisterSUT),
               "racy": _no_spec(RacyCachedRegisterSUT),
               "replicated": _no_spec(ReplicatedRegisterSUT)},
        default_pids=2, default_ops=12),
    "ticket": ModelEntry(
        make_spec=TicketSpec,
        impls={"atomic": _no_spec(AtomicTicketSUT),
               "racy": _no_spec(RacyTicketSUT)},
        default_pids=4, default_ops=24),
    "cas": ModelEntry(
        make_spec=CasSpec,
        impls={"atomic": AtomicCasSUT, "racy": RacyCasSUT},
        default_pids=8, default_ops=32),
    "queue": ModelEntry(
        make_spec=QueueSpec,
        impls={"atomic": AtomicQueueSUT, "racy": RacyTwoPhaseQueueSUT},
        default_pids=8, default_ops=48),
    "kv": ModelEntry(
        make_spec=KvSpec,
        impls={"atomic": AtomicKvSUT, "racy": StaleCacheKvSUT},
        default_pids=16, default_ops=64),
    # composed multi-cell families (P-compositional, ops/pcomp.py):
    # per-cell sub-histories project onto register/cas, so long-history
    # corpora decompose onto the single-object engines
    "multireg": ModelEntry(
        make_spec=MultiRegisterSpec,
        impls={"atomic": AtomicMultiRegisterSUT,
               "racy": ShardedStaleMultiRegisterSUT},
        default_pids=16, default_ops=64),
    "multicas": ModelEntry(
        make_spec=MultiCasSpec,
        impls={"atomic": AtomicMultiCasSUT, "racy": RacyMultiCasSUT},
        default_pids=16, default_ops=64),
    # extra model families beyond the five milestone configs
    "set": ModelEntry(
        make_spec=SetSpec,
        impls={"atomic": AtomicSetSUT, "racy": RacyCheckThenActSetSUT},
        default_pids=4, default_ops=24),
    "stack": ModelEntry(
        make_spec=StackSpec,
        impls={"atomic": AtomicStackSUT, "racy": RacyTwoPhaseStackSUT},
        default_pids=8, default_ops=32),
    # generation-plane families (ISSUE 17): a range-query set, a lock/
    # semaphore cross-checking the race-lint fixtures, and the
    # deliberately non-decomposable multi-key transaction family whose
    # projection every consumer must REFUSE (models/txn.py docstring)
    "rangeset": ModelEntry(
        make_spec=RangeSetSpec,
        impls={"atomic": AtomicRangeSetSUT, "racy": ScanningRangeSetSUT},
        default_pids=4, default_ops=24),
    "semaphore": ModelEntry(
        make_spec=SemaphoreSpec,
        impls={"atomic": AtomicSemaphoreSUT,
               "racy": RacyCheckThenActSemaphoreSUT},
        default_pids=4, default_ops=24),
    "txn": ModelEntry(
        make_spec=TxnRegisterSpec,
        impls={"atomic": AtomicTxnSUT, "racy": TornCopyTxnSUT},
        default_pids=8, default_ops=32),
    # failover register: atomic = synchronous replication, racy = async
    # (the lost-acked-write bug).  Discriminated under a CRASH schedule
    # (e.g. --crash-at primary:6); without one both behave like a plain
    # register
    "failover": ModelEntry(
        make_spec=RegisterSpec,
        impls={"atomic": SyncReplFailoverSUT,
               "racy": AsyncReplFailoverSUT},
        default_pids=3, default_ops=10),
}


def make(model: str, impl: str, spec_kwargs: dict = None):
    """(spec, sut) for a registry entry.

    ``spec_kwargs`` reproduces a non-default spec (regression replay must
    not silently rebuild registry defaults — ADVICE.md round 1)."""
    entry = MODELS[model]
    spec = entry.make_spec(**(spec_kwargs or {}))
    return spec, entry.impls[impl](spec)


class SutFactory:
    """Picklable zero-arg SUT constructor for the parallel execution plane
    (sched/pool.py): spawn-started worker processes rebuild the SUT from
    registry names — lambdas/closures don't survive pickling."""

    def __init__(self, model: str, impl: str, spec_kwargs: dict = None):
        self.model = model
        self.impl = impl
        self.spec_kwargs = spec_kwargs

    def __call__(self):
        return make(self.model, self.impl, self.spec_kwargs)[1]
