"""Bitmask set — an additional model family beyond the five milestone
configs (SURVEY.md §2 Examples: the reference family's test suite IS its
examples; more executable specs widen the regression surface).

The set holds keys from [0, n_keys).  Because membership packs into one
bitmask integer, the model state is SCALAR with bound ``2**n_keys`` — so
this spec rides every fast path in the framework at once: the compiled
domain step table (core/spec.py), the native C++ table kernel (wg.cpp
kind 0), and the device kernel's per-history step-table gather
(ops/jax_kernel.py).

The racy implementation's add is check-then-act (contains round trip,
then an unconditional insert round trip): two concurrent adds of the same
key can both observe it absent and both report "inserted" — but the model
says the second linearized add must return 0.  The classic TOCTOU race.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

ADD = 0
REMOVE = 1
CONTAINS = 2


class SetSpec(Spec):
    """Set over keys [0, n_keys), model state = membership bitmask.

    ADD(k) responds 1 iff k was absent (and inserts it), else 0.
    REMOVE(k) responds 1 iff k was present (and removes it), else 0.
    CONTAINS(k) responds the membership bit; never mutates.
    """

    name = "set"
    STATE_DIM = 1

    def __init__(self, n_keys: int = 4):
        if not 1 <= n_keys <= 16:
            raise ValueError(f"n_keys must be in [1, 16], got {n_keys}")
        self.n_keys = n_keys
        self.CMDS = (
            CmdSig("add", n_args=n_keys, n_resps=2),
            CmdSig("remove", n_args=n_keys, n_resps=2),
            CmdSig("contains", n_args=n_keys, n_resps=2),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(1, np.int32)

    def scalar_state_bound(self, n_ops):
        return 1 << self.n_keys  # state is always a membership mask

    def spec_kwargs(self):
        return {"n_keys": self.n_keys}

    def step_py(self, state, cmd, arg, resp):
        mask = state[0]
        present = (mask >> arg) & 1
        if cmd == ADD:
            return [mask | (1 << arg)], resp == 1 - present
        if cmd == REMOVE:
            return [mask & ~(1 << arg)], resp == present
        return [mask], resp == present

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        mask = state[0]
        bit = jnp.int32(1) << arg
        present = (mask >> arg) & 1
        ok = jnp.where(cmd == ADD, resp == 1 - present, resp == present)
        new_mask = jnp.where(
            cmd == ADD, mask | bit,
            jnp.where(cmd == REMOVE, mask & ~bit, mask))
        return jnp.stack([new_mask.astype(state.dtype)]), ok


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _set_server(store: dict):
    """Server applying add/remove/contains atomically per message; also
    answers the racy SUT's unconditional-insert protocol."""
    while True:
        msg = yield Recv()
        kind, key = msg.payload
        items = store["items"]
        if kind == "add":
            if key in items:
                yield Send(msg.src, 0)
            else:
                items.add(key)
                yield Send(msg.src, 1)
        elif kind == "remove":
            if key in items:
                items.discard(key)
                yield Send(msg.src, 1)
            else:
                yield Send(msg.src, 0)
        elif kind == "contains":
            yield Send(msg.src, 1 if key in items else 0)
        elif kind == "insert":
            items.add(key)
            yield Send(msg.src, 0)


class AtomicSetSUT:
    """Correct: each op is one atomically-applied server message.
    Expected to PASS prop_concurrent."""

    def __init__(self, spec: SetSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {"items": set()}
        sched.spawn("server", _set_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        kind = ("add", "remove", "contains")[cmd]
        yield Send("server", (kind, arg))
        msg = yield Recv()
        return msg.payload


class RacyCheckThenActSetSUT:
    """Racy: add is contains-then-insert as separate round trips; two
    concurrent adds of the same key both observe it absent and both claim
    the insertion (resp 1), but only one can linearize first.  Expected
    to FAIL."""

    def __init__(self, spec: SetSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {"items": set()}
        sched.spawn("server", _set_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd != ADD:
            kind = ("add", "remove", "contains")[cmd]
            yield Send("server", (kind, arg))
            msg = yield Recv()
            return msg.payload
        yield Send("server", ("contains", arg))
        msg = yield Recv()
        if msg.payload == 1:
            return 0  # observed present
        # non-atomic: the membership check happened in a separate round
        # trip; another pid's add can land before this insert does
        yield Send("server", ("insert", arg))
        yield Recv()
        return 1
