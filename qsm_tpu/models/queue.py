"""Bounded FIFO queue — milestone config #4 (BASELINE.json:10).

The first spec whose state space is too big to tabulate: the model state is
the *queue contents*, kept as a packed int32 vector ``[length, slot0..slotC-1]``
with ``transition`` a branchless jitted function rather than a step table —
exactly the representation SURVEY.md §7 hard-parts #2 prescribes.

The racy implementation splits dequeue into front-read + pop round trips, so
two concurrent dequeues can both observe (and both "remove") the same head —
the classic duplicate-dequeue race a FIFO linearizability checker must catch.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

ENQ = 0
DEQ = 1

OK = 0
FULL = 1


class QueueSpec(Spec):
    """Bounded FIFO queue of capacity ``capacity`` over values [0, n_values).

    ENQ(v) responds OK(0) and appends, or FULL(1) when at capacity.
    DEQ responds the head value, or the sentinel ``n_values`` when empty.
    Model state: ``[length, slot0, ..., slot_{capacity-1}]`` with slot0 the
    head; vacated slots are zeroed so equal queue contents always pack to the
    same state vector (canonical form matters for memoised oracles).
    """

    name = "queue"

    def __init__(self, capacity: int = 4, n_values: int = 4):
        self.capacity = capacity
        self.n_values = n_values
        self.STATE_DIM = 1 + capacity
        self.EMPTY = n_values  # DEQ-on-empty response sentinel
        self.CMDS = (
            CmdSig("enq", n_args=n_values, n_resps=2),
            CmdSig("deq", n_args=1, n_resps=n_values + 1),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(self.STATE_DIM, np.int32)

    def spec_kwargs(self):
        return {"capacity": self.capacity, "n_values": self.n_values}

    def native_kernel(self):
        return (1, self.capacity, self.n_values)  # wg.cpp kind 1

    def state_elem_bounds(self):
        # length in [0, cap]; slots in [0, n_values) with vacated slots
        # zeroed (canonical form keeps every element in its domain)
        return [self.capacity + 1] + [self.n_values] * self.capacity

    def step_py(self, state, cmd, arg, resp):
        length = state[0]
        slots = list(state[1:])
        if cmd == ENQ:
            if length == self.capacity:
                return [length] + slots, resp == FULL
            new = slots.copy()
            new[length] = arg
            return [length + 1] + new, resp == OK
        if length == 0:
            return [0] + slots, resp == self.EMPTY
        head = slots[0]
        new = slots[1:] + [0]
        return [length - 1] + new, resp == head

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        length = state[0]
        slots = state[1:]
        iota = jnp.arange(self.capacity)

        is_enq = cmd == ENQ
        full = length == self.capacity
        empty = length == 0
        head = slots[0]

        enq_ok = jnp.where(full, resp == FULL, resp == OK)
        deq_ok = jnp.where(empty, resp == self.EMPTY, resp == head)
        ok = jnp.where(is_enq, enq_ok, deq_ok)

        enq_slots = jnp.where((iota == length) & ~full, arg, slots)
        # dequeue: shift left one, zero the vacated tail slot
        deq_slots = jnp.where(empty, slots,
                              jnp.where(iota == self.capacity - 1, 0,
                                        jnp.roll(slots, -1)))
        new_slots = jnp.where(is_enq, enq_slots, deq_slots)
        new_len = jnp.where(is_enq,
                            length + (~full).astype(length.dtype),
                            length - (~empty).astype(length.dtype))
        new_state = jnp.concatenate(
            [new_len[None], new_slots]).astype(state.dtype)
        return new_state, ok


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _queue_server(q: dict, capacity: int, n_values: int):
    """Atomic per-message queue server; also answers the racy SUT's
    two-phase ('front', 'pop') protocol."""
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        items = q["items"]
        if kind == "enq":
            if len(items) >= capacity:
                yield Send(msg.src, FULL)
            else:
                items.append(rest[0])
                yield Send(msg.src, OK)
        elif kind == "deq":
            yield Send(msg.src, items.pop(0) if items else n_values)
        elif kind == "front":
            yield Send(msg.src, items[0] if items else n_values)
        elif kind == "pop":
            if items:
                items.pop(0)
            yield Send(msg.src, OK)


class AtomicQueueSUT:
    """Correct: enq/deq each a single atomically-applied server message.
    Expected to PASS prop_concurrent."""

    def __init__(self, spec: QueueSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.q = {"items": []}
        sched.spawn("server",
                    _queue_server(self.q, self.spec.capacity,
                                  self.spec.n_values), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        yield Send("server", ("enq", arg) if cmd == ENQ else ("deq",))
        msg = yield Recv()
        return msg.payload


class RacyTwoPhaseQueueSUT:
    """Racy: dequeue is front-read then pop as separate round trips; two
    concurrent dequeues can both return the same head (duplicate delivery)
    while two elements get popped.  Expected to FAIL."""

    def __init__(self, spec: QueueSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.q = {"items": []}
        sched.spawn("server",
                    _queue_server(self.q, self.spec.capacity,
                                  self.spec.n_values), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == ENQ:
            yield Send("server", ("enq", arg))
            msg = yield Recv()
            return msg.payload
        yield Send("server", ("front",))
        msg = yield Recv()
        head = msg.payload
        if head == self.spec.n_values:
            return head  # observed empty
        yield Send("server", ("pop",))
        yield Recv()
        return head
