"""Ticket dispenser / atomic counter — milestone config #2 (BASELINE.json:8).

The ticket dispenser is the qsm family's classic example (SURVEY.md §2
Examples): ``take`` hands out the next ticket number, ``reset`` restarts the
sequence.  The linearizability bug it exists to catch is the non-atomic
read-then-increment: two pids read the same counter value and both get the
same ticket.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

TAKE = 0
RESET = 1


class TicketSpec(Spec):
    """Atomic ticket dispenser.

    Model state: ``[next]``.  TAKE must return the current ``next`` and
    advance it; RESET returns 0 and sets ``next`` to 0.  ``n_tickets`` bounds
    the response domain; keep it above the history length so TAKE always has
    a valid response (preconditions are generation-time only).
    """

    name = "ticket"
    STATE_DIM = 1

    def __init__(self, n_tickets: int = 25):
        self.n_tickets = n_tickets
        self.CMDS = (
            CmdSig("take", n_args=1, n_resps=n_tickets),
            CmdSig("reset", n_args=1, n_resps=1),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(1, np.int32)

    def spec_kwargs(self):
        return {"n_tickets": self.n_tickets}

    def precondition(self, state, cmd, arg) -> bool:
        return cmd != TAKE or state[0] < self.n_tickets

    def scalar_state_bound(self, n_ops):
        # Every ok TAKE requires resp == state and moves state up by one;
        # RESET moves it to 0.  A chain of ok steps in an n_ops history can
        # therefore never push the state past n_ops, REGARDLESS of what
        # response values the SUT actually produced (a buggy SUT may hand
        # out tickets beyond n_tickets; the oracle accepts resp == state
        # with no cap, so the table must cover those states too — bounding
        # by n_tickets here was unsound).
        return n_ops + 1

    def step_py(self, state, cmd, arg, resp):
        nxt = state[0]
        if cmd == TAKE:
            return [nxt + 1], resp == nxt
        return [0], resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        nxt = state[0]
        is_take = cmd == TAKE
        ok = jnp.where(is_take, resp == nxt, resp == 0)
        new = jnp.where(is_take, nxt + 1, 0)
        return jnp.stack([new.astype(state.dtype)]), ok


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _atomic_server(store: dict):
    """Server applying take/reset atomically per message."""
    while True:
        msg = yield Recv()
        kind = msg.payload[0]
        if kind == "take":
            yield Send(msg.src, store["next"])
            store["next"] += 1
        elif kind == "reset":
            store["next"] = 0
            yield Send(msg.src, 0)
        elif kind == "read":
            yield Send(msg.src, store["next"])
        elif kind == "incr":
            store["next"] += 1
            yield Send(msg.src, 0)


class AtomicTicketSUT:
    """Correct: one server message per TAKE — read+increment is atomic.
    Expected to PASS prop_concurrent."""

    def setup(self, sched: Scheduler) -> None:
        self.store = {"next": 0}
        sched.spawn("server", _atomic_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        yield Send("server", ("take",) if cmd == TAKE else ("reset",))
        msg = yield Recv()
        return msg.payload


class RacyTicketSUT:
    """Racy: TAKE is read-then-increment as TWO server round-trips; two pids
    can read the same counter and hand out duplicate tickets — the classic
    dispenser bug (SURVEY.md §2 Examples).  Expected to FAIL."""

    def setup(self, sched: Scheduler) -> None:
        self.store = {"next": 0}
        sched.spawn("server", _atomic_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == TAKE:
            yield Send("server", ("read",))
            msg = yield Recv()
            ticket = msg.payload
            yield Send("server", ("incr",))
            yield Recv()
            return ticket
        yield Send("server", ("reset",))
        msg = yield Recv()
        return msg.payload
