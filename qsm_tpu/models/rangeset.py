"""Range-query set — the set/map family the generation plane stresses
(ISSUE 17; ROADMAP item 3).

``RangeSetSpec`` extends the bitmask-set shape (models/set.py) with an
order-statistics RANGE op: ``count_below(k)`` answers how many members
are strictly below ``k``.  The response is a function of MANY keys at
once, which is exactly what kv/cas histories cannot express — and what
makes the racy implementation's bug shape new: a range scan that reads
per-key membership in separate round trips observes a *snapshot no
linearization point produces* when adds/removes land mid-scan.

State stays one membership bitmask (scalar, bound ``2**n_keys``), so
the family rides every fast path at once — the compiled domain step
table, the native C++ table kernel, and the device kernel's per-history
step-table gather — while its histories are adversarial for the search
(a count response constrains the whole mask, not one bit).
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

ADD = 0
REMOVE = 1
CONTAINS = 2
COUNT_BELOW = 3


class RangeSetSpec(Spec):
    """Set over keys [0, n_keys) with an order-statistics range query.

    ADD(k) responds 1 iff k was absent (and inserts it), else 0.
    REMOVE(k) responds 1 iff k was present (and removes it), else 0.
    CONTAINS(k) responds the membership bit; never mutates.
    COUNT_BELOW(k) responds ``popcount(mask & ((1 << k) - 1))`` — the
    number of members strictly below k; never mutates.  ``k`` ranges
    over [0, n_keys]: ``COUNT_BELOW(n_keys)`` is the full cardinality.
    """

    name = "rangeset"
    STATE_DIM = 1

    def __init__(self, n_keys: int = 4):
        if not 1 <= n_keys <= 16:
            raise ValueError(f"n_keys must be in [1, 16], got {n_keys}")
        self.n_keys = n_keys
        self.CMDS = (
            CmdSig("add", n_args=n_keys, n_resps=2),
            CmdSig("remove", n_args=n_keys, n_resps=2),
            CmdSig("contains", n_args=n_keys, n_resps=2),
            # arg domain includes n_keys (count the whole set); response
            # domain is a COUNT in [0, n_keys]
            CmdSig("count_below", n_args=n_keys + 1, n_resps=n_keys + 1),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(1, np.int32)

    def scalar_state_bound(self, n_ops):
        return 1 << self.n_keys  # state is always a membership mask

    def spec_kwargs(self):
        return {"n_keys": self.n_keys}

    def step_py(self, state, cmd, arg, resp):
        mask = state[0]
        if cmd == COUNT_BELOW:
            below = int(mask) & ((1 << arg) - 1)
            return [mask], resp == bin(below).count("1")
        present = (mask >> arg) & 1
        if cmd == ADD:
            return [mask | (1 << arg)], resp == 1 - present
        if cmd == REMOVE:
            return [mask & ~(1 << arg)], resp == present
        return [mask], resp == present

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        mask = state[0]
        bit = jnp.int32(1) << arg
        present = (mask >> arg) & 1
        # branchless popcount of the below-arg prefix: sum the masked
        # bits across the (static) key domain
        iota = jnp.arange(self.n_keys, dtype=jnp.int32)
        below = jnp.sum(((mask >> iota) & 1) * (iota < arg))
        ok = jnp.where(
            cmd == COUNT_BELOW, resp == below,
            jnp.where(cmd == ADD, resp == 1 - present, resp == present))
        new_mask = jnp.where(
            cmd == ADD, mask | bit,
            jnp.where(cmd == REMOVE, mask & ~bit, mask))
        return jnp.stack([new_mask.astype(state.dtype)]), ok


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _rangeset_server(store: dict):
    """Server applying add/remove/contains/count atomically per message;
    also answers the racy SUT's per-key probe protocol."""
    while True:
        msg = yield Recv()
        kind, key = msg.payload
        items = store["items"]
        if kind == "add":
            if key in items:
                yield Send(msg.src, 0)
            else:
                items.add(key)
                yield Send(msg.src, 1)
        elif kind == "remove":
            if key in items:
                items.discard(key)
                yield Send(msg.src, 1)
            else:
                yield Send(msg.src, 0)
        elif kind == "contains":
            yield Send(msg.src, 1 if key in items else 0)
        elif kind == "count_below":
            yield Send(msg.src, sum(1 for k in items if k < key))


class AtomicRangeSetSUT:
    """Correct: each op — the range query included — is one atomically
    applied server message.  Expected to PASS prop_concurrent."""

    def __init__(self, spec: RangeSetSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {"items": set()}
        sched.spawn("server", _rangeset_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        kind = ("add", "remove", "contains", "count_below")[cmd]
        yield Send("server", (kind, arg))
        msg = yield Recv()
        return msg.payload


class ScanningRangeSetSUT:
    """Racy: COUNT_BELOW is a per-key contains SCAN — one round trip per
    key below the bound — so adds/removes that land mid-scan yield a
    count no single linearization point produces (a key counted before
    its removal plus one added behind the cursor).  Point ops are
    atomic; only the range op torn.  Expected to FAIL."""

    def __init__(self, spec: RangeSetSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {"items": set()}
        sched.spawn("server", _rangeset_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd != COUNT_BELOW:
            kind = ("add", "remove", "contains")[cmd]
            yield Send("server", (kind, arg))
            msg = yield Recv()
            return msg.payload
        # non-atomic: each membership probe is its own round trip; the
        # set can change between probes, so the sum is a torn snapshot
        count = 0
        for key in range(arg):
            yield Send("server", ("contains", key))
            msg = yield Recv()
            count += msg.payload
        return count
