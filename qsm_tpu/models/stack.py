"""Bounded LIFO stack — an additional vector-state model family beyond
the five milestone configs (SURVEY.md §2 Examples).

Mirrors the FIFO queue config's representation choices (models/queue.py,
SURVEY.md §7 hard-parts #2): model state is the packed int32 vector
``[length, slot0..slotC-1]`` with a branchless jitted transition — but the
LIFO discipline makes the top of the stack a *dynamic* slot
(``slots[length-1]``), so the jax step exercises a dynamic gather where
the queue's head was a static index.  A native C++ step kernel (wg.cpp
kind 3) gives the host checker plane the same fast path the queue has.

The racy implementation's pop is top-read + drop as separate round trips:
two concurrent pops can both observe (and both "remove") the same top —
the LIFO twin of the queue's duplicate-dequeue race.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

PUSH = 0
POP = 1

OK = 0
FULL = 1


class StackSpec(Spec):
    """Bounded LIFO stack of capacity ``capacity`` over values [0, n_values).

    PUSH(v) responds OK(0) and appends, or FULL(1) when at capacity.
    POP responds the top value, or the sentinel ``n_values`` when empty.
    Model state: ``[length, slot0, ..., slot_{capacity-1}]`` with
    ``slot_{length-1}`` the top; vacated slots are zeroed so equal stack
    contents always pack to the same state vector (canonical form matters
    for memoised oracles).
    """

    name = "stack"

    def __init__(self, capacity: int = 4, n_values: int = 4):
        self.capacity = capacity
        self.n_values = n_values
        self.STATE_DIM = 1 + capacity
        self.EMPTY = n_values  # POP-on-empty response sentinel
        self.CMDS = (
            CmdSig("push", n_args=n_values, n_resps=2),
            CmdSig("pop", n_args=1, n_resps=n_values + 1),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(self.STATE_DIM, np.int32)

    def spec_kwargs(self):
        return {"capacity": self.capacity, "n_values": self.n_values}

    def native_kernel(self):
        return (3, self.capacity, self.n_values)  # wg.cpp kind 3

    def state_elem_bounds(self):
        # length in [0, cap]; slots in [0, n_values), vacated top zeroed
        return [self.capacity + 1] + [self.n_values] * self.capacity

    def step_py(self, state, cmd, arg, resp):
        length = state[0]
        slots = list(state[1:])
        if cmd == PUSH:
            if length == self.capacity:
                return [length] + slots, resp == FULL
            new = slots.copy()
            new[length] = arg
            return [length + 1] + new, resp == OK
        if length == 0:
            return [0] + slots, resp == self.EMPTY
        top = slots[length - 1]
        new = slots.copy()
        new[length - 1] = 0  # canonical form: vacated top zeroed
        return [length - 1] + new, resp == top

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        length = state[0]
        slots = state[1:]
        iota = jnp.arange(self.capacity)

        is_push = cmd == PUSH
        full = length == self.capacity
        empty = length == 0
        top = slots[jnp.maximum(length - 1, 0)]  # dynamic gather

        push_ok = jnp.where(full, resp == FULL, resp == OK)
        pop_ok = jnp.where(empty, resp == self.EMPTY, resp == top)
        ok = jnp.where(is_push, push_ok, pop_ok)

        push_slots = jnp.where((iota == length) & ~full, arg, slots)
        pop_slots = jnp.where((iota == length - 1) & ~empty, 0, slots)
        new_slots = jnp.where(is_push, push_slots, pop_slots)
        new_len = jnp.where(is_push,
                            length + (~full).astype(length.dtype),
                            length - (~empty).astype(length.dtype))
        new_state = jnp.concatenate(
            [new_len[None], new_slots]).astype(state.dtype)
        return new_state, ok


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _stack_server(st: dict, capacity: int, n_values: int):
    """Atomic per-message stack server; also answers the racy SUT's
    two-phase ('top', 'drop') protocol."""
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        items = st["items"]
        if kind == "push":
            if len(items) >= capacity:
                yield Send(msg.src, FULL)
            else:
                items.append(rest[0])
                yield Send(msg.src, OK)
        elif kind == "pop":
            yield Send(msg.src, items.pop() if items else n_values)
        elif kind == "top":
            yield Send(msg.src, items[-1] if items else n_values)
        elif kind == "drop":
            if items:
                items.pop()
            yield Send(msg.src, OK)


class AtomicStackSUT:
    """Correct: push/pop each a single atomically-applied server message.
    Expected to PASS prop_concurrent."""

    def __init__(self, spec: StackSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.st = {"items": []}
        sched.spawn("server",
                    _stack_server(self.st, self.spec.capacity,
                                  self.spec.n_values), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        yield Send("server", ("push", arg) if cmd == PUSH else ("pop",))
        msg = yield Recv()
        return msg.payload


class RacyTwoPhaseStackSUT:
    """Racy: pop is top-read then drop as separate round trips; two
    concurrent pops can both return the same top (duplicate delivery)
    while two elements get dropped.  Expected to FAIL."""

    def __init__(self, spec: StackSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.st = {"items": []}
        sched.spawn("server",
                    _stack_server(self.st, self.spec.capacity,
                                  self.spec.n_values), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == PUSH:
            yield Send("server", ("push", arg))
            msg = yield Recv()
            return msg.payload
        yield Send("server", ("top",))
        msg = yield Recv()
        top = msg.payload
        if top == self.spec.n_values:
            return top  # observed empty
        yield Send("server", ("drop",))
        yield Recv()
        return top
