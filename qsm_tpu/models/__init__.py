"""The five milestone specs + correct/racy SUT pairs (BASELINE.json:7-11;
SURVEY.md §2 Examples — the reference's test suite IS its examples)."""

from .register import (AtomicRegisterSUT, RacyCachedRegisterSUT,
                       RegisterSpec, ReplicatedRegisterSUT)
from .counter import AtomicTicketSUT, RacyTicketSUT, TicketSpec
from .cas import AtomicCasSUT, CasSpec, RacyCasSUT
from .queue import AtomicQueueSUT, QueueSpec, RacyTwoPhaseQueueSUT
from .kv import AtomicKvSUT, KvSpec, StaleCacheKvSUT
