"""The five milestone specs (BASELINE.json:7-11) plus extra model
families (set, stack), each a correct/racy SUT pair (SURVEY.md §2
Examples — the reference's test suite IS its examples)."""

from .register import (AtomicRegisterSUT, RacyCachedRegisterSUT,
                       RegisterSpec, ReplicatedRegisterSUT)
from .counter import AtomicTicketSUT, RacyTicketSUT, TicketSpec
from .cas import AtomicCasSUT, CasSpec, RacyCasSUT
from .queue import AtomicQueueSUT, QueueSpec, RacyTwoPhaseQueueSUT
from .kv import AtomicKvSUT, KvSpec, StaleCacheKvSUT
from .multi import (AtomicMultiCasSUT, AtomicMultiRegisterSUT,
                    MultiCasSpec, MultiRegisterSpec, RacyMultiCasSUT,
                    ShardedStaleMultiRegisterSUT)
from .set import AtomicSetSUT, RacyCheckThenActSetSUT, SetSpec
from .stack import AtomicStackSUT, RacyTwoPhaseStackSUT, StackSpec
