"""Primary/backup register failover — the monitors/links showcase.

The reference builds on distributed-process, whose monitors/links are the
failure-detection primitive (SURVEY.md §5); this model family exercises
the framework's equivalent end to end: a ROUTER process `Monitor`s the
primary replica and fails over to the backup when the deterministic crash
schedule kills it (`FaultPlan.crash_at` — replayable from the seed like
everything else).

Two implementations against the plain ``RegisterSpec``:

* ``SyncReplFailoverSUT`` — a write is acked to the client only after the
  backup acknowledged its replication.  Every acknowledged write is on
  the backup at failover, so histories stay linearizable through the
  crash.  Expected to PASS.
* ``AsyncReplFailoverSUT`` — the write is acked as soon as the primary
  applied it; replication trails behind.  A crash in that window loses
  an acknowledged write: the promoted backup serves the OLD value after
  a newer one was acknowledged — the classic failover lost-update.
  Expected to FAIL under a crash schedule.

Correctness subtleties the sync design must (and does) handle — each one
is a real distributed-systems failover bug the checker caught during
development of this very module:

* replication carries the primary's APPLY-ORDER sequence number, and a
  replica ignores stale sequences — the delivery pool is not FIFO, so
  two in-flight replications can arrive reordered;
* a replica stops accepting replication the moment it serves its first
  direct client operation (it is the leader now) — otherwise a stale
  in-flight replication arriving after failover would overwrite a write
  the new leader already acknowledged.

Reference citation: SURVEY.md §5 failure-detection row (the mount at
/root/reference is empty; monitors/links are distributed-process public
API knowledge anchored there).
"""

from __future__ import annotations

from ..sched.scheduler import Monitor, Recv, Scheduler, Send

READ = 0
WRITE = 1


def _replica(store: dict, me: str):
    """One register replica.

    Protocol: ("read", tag) / ("write", tag, v) from the router —
    responds ("resp", tag, value-or-0, seq); ("repl", v, seq, tag) —
    applies iff newer and not yet leader, always acks ("repl-ack", tag).
    """
    seq = 0          # local apply order; stamps write responses
    applied = 0      # highest replicated seq applied
    leader = False   # set on first direct client op: replication ends
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        if kind == "read":
            leader = leader or me == "backup"
            yield Send(msg.src, ("resp", rest[0], store[me], seq))
        elif kind == "write":
            leader = leader or me == "backup"
            tag, value = rest
            seq += 1
            store[me] = value
            yield Send(msg.src, ("resp", tag, 0, seq))
        elif kind == "repl":
            value, rseq, tag = rest
            if leader:
                # A leader acking a replication it IGNORED would let the
                # router acknowledge a write that is not durable — the
                # lost-acked-write bug.  Stay silent: the writer stays
                # un-acked (a pending op the checker completes/prunes).
                continue
            if rseq > applied:
                applied = rseq
                store[me] = value
            yield Send(msg.src, ("repl-ack", tag))


def _router(sync: bool):
    """Client-facing front: forwards ops to the current leader; fails
    over to the backup on the primary's DOWN notification; owns the
    replication step so the replicas stay one simple state machine."""
    leader = "primary"
    yield Monitor("primary")
    pending = {}  # tag -> (client, kind, value)
    tag = 0
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        if kind == "DOWN":
            leader = "backup"
        elif kind == "read":
            tag += 1
            pending[tag] = (msg.src, "r", None)
            yield Send(leader, ("read", tag))
        elif kind == "write":
            tag += 1
            pending[tag] = (msg.src, "w", rest[0])
            yield Send(leader, ("write", tag, rest[0]))
        elif kind == "resp":
            t, value, seq = rest[0], rest[1], rest[2]
            if t not in pending:
                continue  # duplicated response (fault): already served
            client, op_kind, wvalue = pending[t]
            if op_kind == "r":
                del pending[t]
                yield Send(client, value)
            elif msg.src == "primary" and sync:
                # replicate BEFORE acking: the ack waits on repl-ack
                yield Send("backup", ("repl", wvalue, seq, t))
            else:
                # async mode acks here (the bug: replication trails the
                # ack); post-failover single-replica writes ack here too
                del pending[t]
                yield Send(client, 0)
                if msg.src == "primary":
                    yield Send("backup", ("repl", wvalue, seq, t))
        elif kind == "repl-ack":
            t = rest[0]
            if t in pending:  # sync write completing; async already acked
                client, _, _ = pending.pop(t)
                yield Send(client, 0)


class _FailoverBase:
    sync = True

    def __init__(self, spec=None):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {"primary": 0, "backup": 0}
        sched.spawn("primary", _replica(self.store, "primary"),
                    daemon=True)
        sched.spawn("backup", _replica(self.store, "backup"), daemon=True)
        sched.spawn("router", _router(self.sync), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        yield Send("router", ("read",) if cmd == READ
                   else ("write", arg))
        msg = yield Recv()
        return msg.payload


class SyncReplFailoverSUT(_FailoverBase):
    """Synchronous replication: acked writes survive failover.
    Expected to PASS prop_concurrent under crash schedules."""

    sync = True


class AsyncReplFailoverSUT(_FailoverBase):
    """Asynchronous replication: a crash between client-ack and
    replication loses an acknowledged write.  Expected to FAIL under
    crash schedules."""

    sync = False
