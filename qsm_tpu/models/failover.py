"""Primary/backup register failover — the monitors/links showcase.

The reference builds on distributed-process, whose monitors/links are the
failure-detection primitive (SURVEY.md §5); this model family exercises
the framework's equivalent end to end: a ROUTER process `Monitor`s the
primary replica and fails over to the backup when the deterministic crash
schedule kills it (`FaultPlan.crash_at` — replayable from the seed like
everything else).

Two implementations against the plain ``RegisterSpec``:

* ``SyncReplFailoverSUT`` — a write is acked only after the backup
  acknowledged its replication, and reads serve only the COMMITTED
  (replication-acked) value.  Expected to PASS under crash schedules.
* ``AsyncReplFailoverSUT`` — writes ack immediately and reads serve the
  primary's freshly-applied state.  A crash loses acknowledged writes
  AND rolls back values reads already observed.  Expected to FAIL.

Every rule in the sync design exists because this framework's own
checker caught its absence as a real linearizability violation during
development — the tool debugging its author's distributed systems:

* *acked writes must be durable*: ack only after the backup's repl-ack
  (else: crash loses an acked write — the async impl's bug #1);
* *reads must not observe unreplicated state*: the primary serves the
  last COMMITTED value, not its latest applied one (else: a read
  returns v, the primary crashes before v replicates, and a
  post-failover read returns the older value — observations went back
  in time; caught by the 400-trial burn-in as read(1) ... read(0));
* *replication must be ordered*: sequence numbers assigned in the
  primary's apply order, stale ones ignored (the delivery pool is not
  FIFO);
* *a promoted replica must refuse stale replication silently*: acking
  a repl it ignored would let an un-durable write ack (lost-ack bug),
  and applying it would overwrite post-failover writes.

Reference citation: SURVEY.md §5 failure-detection row (the mount at
/root/reference is empty; monitors/links are distributed-process public
API knowledge anchored there).
"""

from __future__ import annotations

from ..sched.scheduler import Monitor, Recv, Scheduler, Send

READ = 0
WRITE = 1


def _primary(sync: bool, backup: str = "backup"):
    """The primary replica: applies writes, replicates to the backup.

    sync mode: stage the write, replicate, ack the router only on the
    backup's repl-ack; reads serve the committed value.  async mode:
    ack immediately, replicate behind, serve applied state (two bugs).
    """
    committed = 0       # last replication-acked value — what reads see
    committed_seq = 0
    applied = 0         # latest applied value incl. unreplicated staging
    seq = 0             # apply-order sequence, stamped into replication
    staged = {}         # seq -> (router_tag, value) awaiting repl-ack
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        if kind == "read":
            value = committed if sync else applied
            yield Send(msg.src, ("resp", rest[0], value))
        elif kind == "write":
            tag, value = rest
            seq += 1
            if sync:
                staged[seq] = (tag, value)
                yield Send(backup, ("repl", value, seq))
            else:
                # acked before durable (bug #1), and reads serve this
                # un-replicated state (bug #2, via `applied` above)
                applied = value
                yield Send(msg.src, ("resp", tag, 0))
                yield Send(backup, ("repl", value, seq))
        elif kind == "repl-ack":
            aseq = rest[0]
            if aseq in staged:  # duplication faults may re-deliver acks
                tag, value = staged.pop(aseq)
                if aseq > committed_seq:
                    committed_seq = aseq
                    committed = value
                yield Send("router", ("resp", tag, 0))


def _backup():
    """The backup: applies ordered replication until promoted (acks go
    to ``msg.src``, so no wiring to the primary's name); serves clients
    directly afterwards (its first direct op IS the promotion — the
    router only routes here after the primary's DOWN)."""
    value = 0
    applied = 0     # highest replicated seq applied; continues as the
    leader = False  # local write order after promotion
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        if kind == "repl":
            v, rseq = rest
            if leader:
                # silence, not an ack: acking an IGNORED replication
                # would let an un-durable write ack (lost-ack bug), and
                # applying it would overwrite post-failover writes
                continue
            if rseq > applied:  # stale out-of-order replication ignored
                applied = rseq
                value = v
            yield Send(msg.src, ("repl-ack", rseq))
        elif kind == "read":
            leader = True
            yield Send(msg.src, ("resp", rest[0], value))
        elif kind == "write":
            leader = True
            tag, v = rest
            applied += 1
            value = v
            yield Send(msg.src, ("resp", tag, 0))


def _router():
    """Client-facing front: forwards ops to the current leader, fails
    over to the backup on the primary's DOWN notification."""
    leader = "primary"
    yield Monitor("primary")
    pending = {}  # tag -> client
    tag = 0
    while True:
        msg = yield Recv()
        kind, *rest = msg.payload
        if kind == "DOWN":
            leader = "backup"
        elif kind == "read":
            tag += 1
            pending[tag] = msg.src
            yield Send(leader, ("read", tag))
        elif kind == "write":
            tag += 1
            pending[tag] = msg.src
            yield Send(leader, ("write", tag, rest[0]))
        elif kind == "resp":
            t, value = rest
            if t in pending:  # duplication faults: already served
                yield Send(pending.pop(t), value)


class _FailoverBase:
    sync = True

    def __init__(self, spec=None):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        sched.spawn("primary", _primary(self.sync), daemon=True)
        sched.spawn("backup", _backup(), daemon=True)
        sched.spawn("router", _router(), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        yield Send("router", ("read",) if cmd == READ
                   else ("write", arg))
        msg = yield Recv()
        return msg.payload


class SyncReplFailoverSUT(_FailoverBase):
    """Synchronous replication + committed reads: linearizable through
    crashes.  Expected to PASS prop_concurrent under crash schedules."""

    sync = True


class AsyncReplFailoverSUT(_FailoverBase):
    """Asynchronous replication + uncommitted reads: a crash loses acked
    writes and rolls back observed values.  Expected to FAIL."""

    sync = False
