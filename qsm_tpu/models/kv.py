"""Multi-key KV map — milestone config #5 (BASELINE.json:11).

16 pids, histories up to 64 ops — far past direct Wing–Gong range in the
worst case.  The spec declares a partition key, so the checker may apply the
P-compositionality split (Horn & Kroening, PAPERS.md:5): a history is
linearizable iff every per-key sub-history is, and each sub-history projects
onto a plain atomic register — many small, batchable problems instead of one
exponential one (SURVEY.md §2b "per-key decomposition").
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, KeyProj, Spec
from ..sched.scheduler import Recv, Scheduler, Send
from .register import READ, WRITE, RegisterSpec

GET = 0
PUT = 1


class KvSpec(Spec):
    """Atomic map over ``n_keys`` keys with values [0, n_values).

    GET(k) returns the key's value; PUT packs ``k * n_values + v`` into its
    integer argument and responds 0.  Model state: one value per key.
    """

    name = "kv"

    def __init__(self, n_keys: int = 4, n_values: int = 4):
        self.n_keys = n_keys
        self.n_values = n_values
        self.STATE_DIM = n_keys
        # the per-key projection is DECLARED next to the alphabet (and
        # validated once by core.spec.projection_report): GET's arg IS
        # the key (stride 1, projected arg 0 = READ's no-arg), PUT packs
        # key * n_values + value (stride n_values → projected WRITE(v))
        self.CMDS = (
            CmdSig("get", n_args=n_keys, n_resps=n_values,
                   proj=KeyProj(pcmd=READ, stride=1)),
            CmdSig("put", n_args=n_keys * n_values, n_resps=1,
                   proj=KeyProj(pcmd=WRITE, stride=n_values)),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(self.n_keys, np.int32)

    def put_arg(self, key: int, value: int) -> int:
        return key * self.n_values + value

    def spec_kwargs(self):
        return {"n_keys": self.n_keys, "n_values": self.n_values}

    def native_kernel(self):
        return (2, self.n_keys, self.n_values)  # wg.cpp kind 2

    def state_elem_bounds(self):
        return [self.n_values] * self.n_keys  # one value per key

    def step_py(self, state, cmd, arg, resp):
        state = list(state)
        if cmd == GET:
            return state, resp == state[arg]
        key, value = divmod(arg, self.n_values)
        state[key] = value
        return state, resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        iota = jnp.arange(self.n_keys)
        is_get = cmd == GET
        key = jnp.where(is_get, arg, arg // self.n_values)
        value = arg % self.n_values
        ok = jnp.where(is_get, resp == state[key], resp == 0)
        new_state = jnp.where(~is_get & (iota == key), value, state)
        return new_state.astype(state.dtype), ok

    # -- P-compositionality (PAPERS.md:5) ------------------------------
    # partition_key / project_op are DERIVED from the KeyProj
    # declarations above (core/spec.py); only the projected spec's
    # identity needs stating.
    def projected_spec(self) -> RegisterSpec:
        """Each per-key sub-history is a history of a plain register."""
        return RegisterSpec(n_values=self.n_values)


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _kv_server(store: dict):
    while True:
        msg = yield Recv()
        kind, key, *rest = msg.payload
        if kind == "get":
            yield Send(msg.src, store.get(key, 0))
        else:
            store[key] = rest[0]
            yield Send(msg.src, 0)


class AtomicKvSUT:
    """Correct: single server, one atomically-applied message per op.
    Expected to PASS prop_concurrent."""

    def __init__(self, spec: KvSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        sched.spawn("server", _kv_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == GET:
            yield Send("server", ("get", arg))
        else:
            key, value = divmod(arg, self.spec.n_values)
            yield Send("server", ("put", key, value))
        msg = yield Recv()
        return msg.payload


class StaleCacheKvSUT:
    """Racy: each client caches GET results per key and never revalidates;
    other pids' PUTs are invisible to it — stale reads violate per-key
    linearizability.  Expected to FAIL."""

    def __init__(self, spec: KvSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        self.cache = {}  # (pid, key) -> value
        sched.spawn("server", _kv_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == GET:
            if (pid, arg) in self.cache:
                return self.cache[(pid, arg)]
            yield Send("server", ("get", arg))
            msg = yield Recv()
            self.cache[(pid, arg)] = msg.payload
            return msg.payload
        key, value = divmod(arg, self.spec.n_values)
        yield Send("server", ("put", key, value))
        msg = yield Recv()
        self.cache[(pid, key)] = value
        return 0
