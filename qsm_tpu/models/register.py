"""Shared read/write register — milestone config #1 (BASELINE.json:7).

The reference's in-tree example is a 2-pid shared register with a correct
implementation expected to pass ``prop_concurrent`` and a racy one expected to
fail (SURVEY.md §4).  This module provides the model spec; the matching
correct/racy SUT implementations live in ``qsm_tpu.models.suts``.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

READ = 0
WRITE = 1


class RegisterSpec(Spec):
    """Atomic register over values [0, n_values).

    Model state: ``[value]``.  READ must return the current value; WRITE
    always succeeds (resp 0) and sets it.
    """

    name = "register"
    STATE_DIM = 1

    def __init__(self, n_values: int = 5):
        self.n_values = n_values
        self.CMDS = (
            CmdSig("read", n_args=1, n_resps=n_values),
            CmdSig("write", n_args=n_values, n_resps=1),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(1, np.int32)

    def scalar_state_bound(self, n_ops):
        return self.n_values  # state is always a stored value

    def spec_kwargs(self):
        return {"n_values": self.n_values}

    def step_py(self, state, cmd, arg, resp):
        value = state[0]
        if cmd == READ:
            return [value], resp == value
        return [arg], resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        value = state[0]
        is_read = cmd == READ
        ok = jnp.where(is_read, resp == value, resp == 0)
        new_value = jnp.where(is_read, value, arg)
        return jnp.stack([new_value.astype(state.dtype)]), ok


# ---------------------------------------------------------------------------
# SUT implementations (the reference's correct-vs-racy example pair)
# ---------------------------------------------------------------------------

def register_server(store: dict, key: str):
    """Shared server loop: handles ('read', _) / ('write', arg) messages
    against ``store[key]`` atomically.  All three SUTs differ only in how
    their ``perform`` talks to instances of this loop."""
    while True:
        msg = yield Recv()
        kind, arg = msg.payload
        if kind == "read":
            yield Send(msg.src, store[key])
        else:
            store[key] = arg
            yield Send(msg.src, 0)


class AtomicRegisterSUT:
    """Correct implementation: one server process applies each message
    atomically.  Expected to PASS prop_concurrent."""

    def setup(self, sched: Scheduler) -> None:
        self.store = {"server": 0}
        sched.spawn("server", register_server(self.store, "server"),
                    daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        yield Send("server", ("read" if cmd == READ else "write", arg))
        msg = yield Recv()
        return msg.payload


class RacyCachedRegisterSUT:
    """Racy implementation: each client caches the value on first read and
    serves later reads from the cache; writes update the server and the
    writer's own cache only.  Cross-pid stale reads violate linearizability
    — expected to FAIL prop_concurrent (the reference family's racy-register
    pattern, SURVEY.md §4)."""

    def setup(self, sched: Scheduler) -> None:
        self.store = {"server": 0}
        self.cache = {}
        sched.spawn("server", register_server(self.store, "server"),
                    daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == READ:
            if pid in self.cache:
                return self.cache[pid]  # stale: never revalidated
            yield Send("server", ("read", arg))
            msg = yield Recv()
            self.cache[pid] = msg.payload
            return msg.payload
        yield Send("server", ("write", arg))
        msg = yield Recv()
        self.cache[pid] = arg
        return 0


class ReplicatedRegisterSUT:
    """Racy implementation: two replicas, writes propagate as two separate
    messages, reads go to the pid's home replica.  Concurrent writes can
    apply in different orders at the two replicas, leaving them divergent
    — a subtler ordering bug only some interleavings expose."""

    def setup(self, sched: Scheduler) -> None:
        self.store = {"replica:0": 0, "replica:1": 0}
        for name in self.store:
            sched.spawn(name, register_server(self.store, name), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        home = f"replica:{pid % 2}"
        if cmd == READ:
            yield Send(home, ("read", arg))
            msg = yield Recv()
            return msg.payload
        # write to both replicas; delivery order at each is the scheduler's
        # choice, so concurrent writes may land in opposite orders
        yield Send("replica:0", ("write", arg))
        yield Send("replica:1", ("write", arg))
        yield Recv()
        yield Recv()
        return 0
