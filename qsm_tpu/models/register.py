"""Shared read/write register — milestone config #1 (BASELINE.json:7).

The reference's in-tree example is a 2-pid shared register with a correct
implementation expected to pass ``prop_concurrent`` and a racy one expected to
fail (SURVEY.md §4).  This module provides the model spec; the matching
correct/racy SUT implementations live in ``qsm_tpu.models.suts``.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec

READ = 0
WRITE = 1


class RegisterSpec(Spec):
    """Atomic register over values [0, n_values).

    Model state: ``[value]``.  READ must return the current value; WRITE
    always succeeds (resp 0) and sets it.
    """

    name = "register"
    STATE_DIM = 1

    def __init__(self, n_values: int = 5):
        self.n_values = n_values
        self.CMDS = (
            CmdSig("read", n_args=1, n_resps=n_values),
            CmdSig("write", n_args=n_values, n_resps=1),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(1, np.int32)

    def step_py(self, state, cmd, arg, resp):
        value = state[0]
        if cmd == READ:
            return [value], resp == value
        return [arg], resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        value = state[0]
        is_read = cmd == READ
        ok = jnp.where(is_read, resp == value, resp == 0)
        new_value = jnp.where(is_read, value, arg)
        return jnp.stack([new_value.astype(state.dtype)]), ok
