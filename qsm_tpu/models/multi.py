"""Multi-cell composed objects — the second and third P-compositional
spec families (ROADMAP item 3; PAPERS.md:5).

``MultiRegisterSpec`` is an array of independent atomic registers
addressed by cell (read/write), and ``MultiCasSpec`` generalises it to an
array of CAS registers (read/write/compare-and-swap per cell) — the
composed shape real sharded stores have, where the lost-update race lives
*inside one cell* while the history interleaves every cell.  Both declare
their per-key projection DECLARATIVELY on the alphabet (``CmdSig.proj``,
core/spec.py) and project onto the existing single-object specs
(``RegisterSpec`` / ``CasSpec``), so the compile-time validator
(``projection_report``) pins totality + faithfulness and the decomposed
checkers reuse the single-object engines' native kernels and selectivity
tables unchanged.

Arg packing (the ``KeyProj`` strides): read's arg IS the cell; write
packs ``cell * n_values + v``; cas packs ``cell * n_values² + old *
n_values + new`` — projected args are exactly the single-object specs'
own encodings.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, KeyProj, Spec
from ..sched.scheduler import Recv, Scheduler, Send

READ = 0
WRITE = 1
CAS = 2


class MultiRegisterSpec(Spec):
    """``n_cells`` independent atomic registers over values [0, n_values).

    Model state: one value per cell.  READ(cell) returns the cell's
    value; WRITE packs ``cell * n_values + v`` and responds 0.
    """

    name = "multireg"

    def __init__(self, n_cells: int = 4, n_values: int = 4):
        self.n_cells = n_cells
        self.n_values = n_values
        self.STATE_DIM = n_cells
        self.CMDS = (
            CmdSig("read", n_args=n_cells, n_resps=n_values,
                   proj=KeyProj(pcmd=READ, stride=1)),
            CmdSig("write", n_args=n_cells * n_values, n_resps=1,
                   proj=KeyProj(pcmd=WRITE, stride=n_values)),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(self.n_cells, np.int32)

    def write_arg(self, cell: int, value: int) -> int:
        return cell * self.n_values + value

    def spec_kwargs(self):
        return {"n_cells": self.n_cells, "n_values": self.n_values}

    def state_elem_bounds(self):
        return [self.n_values] * self.n_cells

    def step_py(self, state, cmd, arg, resp):
        state = list(state)
        if cmd == READ:
            return state, resp == state[arg]
        cell, value = divmod(arg, self.n_values)
        state[cell] = value
        return state, resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        iota = jnp.arange(self.n_cells)
        is_read = cmd == READ
        cell = jnp.where(is_read, arg, arg // self.n_values)
        value = arg % self.n_values
        ok = jnp.where(is_read, resp == state[cell], resp == 0)
        new_state = jnp.where(~is_read & (iota == cell), value, state)
        return new_state.astype(state.dtype), ok

    def projected_spec(self):
        from .register import RegisterSpec

        return RegisterSpec(n_values=self.n_values)


class MultiCasSpec(Spec):
    """``n_cells`` independent CAS registers over values [0, n_values).

    Per cell: READ returns the value; WRITE sets it (resp 0);
    CAS(old, new) responds 1 and sets ``new`` iff the cell holds ``old``,
    else responds 0.  The projection target is :class:`~qsm_tpu.models.
    cas.CasSpec` — per-cell sub-histories ride its native kernel and
    selectivity table.
    """

    name = "multicas"

    def __init__(self, n_cells: int = 4, n_values: int = 4):
        self.n_cells = n_cells
        self.n_values = n_values
        self.STATE_DIM = n_cells
        self.CMDS = (
            CmdSig("read", n_args=n_cells, n_resps=n_values,
                   proj=KeyProj(pcmd=READ, stride=1)),
            CmdSig("write", n_args=n_cells * n_values, n_resps=1,
                   proj=KeyProj(pcmd=WRITE, stride=n_values)),
            CmdSig("cas", n_args=n_cells * n_values * n_values, n_resps=2,
                   proj=KeyProj(pcmd=CAS, stride=n_values * n_values)),
        )

    def initial_state(self) -> np.ndarray:
        return np.zeros(self.n_cells, np.int32)

    def write_arg(self, cell: int, value: int) -> int:
        return cell * self.n_values + value

    def cas_arg(self, cell: int, old: int, new: int) -> int:
        return (cell * self.n_values + old) * self.n_values + new

    def spec_kwargs(self):
        return {"n_cells": self.n_cells, "n_values": self.n_values}

    def state_elem_bounds(self):
        return [self.n_values] * self.n_cells

    def step_py(self, state, cmd, arg, resp):
        state = list(state)
        if cmd == READ:
            return state, resp == state[arg]
        if cmd == WRITE:
            cell, value = divmod(arg, self.n_values)
            state[cell] = value
            return state, resp == 0
        cell, rest = divmod(arg, self.n_values * self.n_values)
        old, new = divmod(rest, self.n_values)
        if state[cell] == old:
            state[cell] = new
            return state, resp == 1
        return state, resp == 0

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        iota = jnp.arange(self.n_cells)
        nv = self.n_values
        is_read = cmd == READ
        is_write = cmd == WRITE
        cell = jnp.where(is_read, arg,
                         jnp.where(is_write, arg // nv, arg // (nv * nv)))
        w_val = arg % nv
        old = (arg // nv) % nv
        new = arg % nv
        cur = state[cell]
        succ = cur == old
        ok = jnp.where(is_read, resp == cur,
                       jnp.where(is_write, resp == 0,
                                 resp == succ.astype(resp.dtype)))
        target = jnp.where(is_write, w_val,
                           jnp.where(succ, new, cur))
        write_it = ~is_read & (is_write | succ)
        new_state = jnp.where(write_it & (iota == cell), target, state)
        return new_state.astype(state.dtype), ok

    def gen_cmd(self, rng, state=None):
        """Like CasSpec: bias CAS's expected value toward the cell's
        (approximate) current value half the time so generated CASes
        succeed often enough to exercise the per-cell lost-update race."""
        cmd = rng.randrange(len(self.CMDS))
        if cmd == CAS:
            cell = rng.randrange(self.n_cells)
            new = rng.randrange(self.n_values)
            if state is not None and rng.random() < 0.5:
                old = int(state[cell])
            else:
                old = rng.randrange(self.n_values)
            return CAS, self.cas_arg(cell, old, new)
        return cmd, rng.randrange(self.CMDS[cmd].n_args)

    def projected_spec(self):
        from .cas import CasSpec

        return CasSpec(n_values=self.n_values)


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _cell_server(store: dict):
    """One server applying read/write/cas per message, atomically, across
    all cells (payload carries the cell)."""
    while True:
        msg = yield Recv()
        kind, cell, *rest = msg.payload
        if kind == "read":
            yield Send(msg.src, store.get(cell, 0))
        elif kind == "write":
            store[cell] = rest[0]
            yield Send(msg.src, 0)
        else:  # cas
            old, new = rest
            if store.get(cell, 0) == old:
                store[cell] = new
                yield Send(msg.src, 1)
            else:
                yield Send(msg.src, 0)


class AtomicMultiRegisterSUT:
    """Correct: one server, one atomically-applied message per op.
    Expected to PASS prop_concurrent."""

    def __init__(self, spec: MultiRegisterSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        sched.spawn("server", _cell_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == READ:
            yield Send("server", ("read", arg))
        else:
            cell, value = divmod(arg, self.spec.n_values)
            yield Send("server", ("write", cell, value))
        msg = yield Recv()
        return msg.payload


class ShardedStaleMultiRegisterSUT:
    """Racy: reads are served from a per-pid shard cache that is never
    invalidated by other pids' writes — stale reads violate per-cell
    linearizability (the sharded-store analogue of the kv stale-cache
    bug).  Expected to FAIL."""

    def __init__(self, spec: MultiRegisterSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        self.cache = {}  # (pid, cell) -> value
        sched.spawn("server", _cell_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd == READ:
            if (pid, arg) in self.cache:
                return self.cache[(pid, arg)]
            yield Send("server", ("read", arg))
            msg = yield Recv()
            self.cache[(pid, arg)] = msg.payload
            return msg.payload
        cell, value = divmod(arg, self.spec.n_values)
        yield Send("server", ("write", cell, value))
        msg = yield Recv()
        self.cache[(pid, cell)] = value
        return 0


class AtomicMultiCasSUT:
    """Correct: each op (CAS included) is one server message, applied
    atomically.  Expected to PASS prop_concurrent."""

    def __init__(self, spec: MultiCasSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        sched.spawn("server", _cell_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        nv = self.spec.n_values
        if cmd == READ:
            yield Send("server", ("read", arg))
        elif cmd == WRITE:
            cell, value = divmod(arg, nv)
            yield Send("server", ("write", cell, value))
        else:
            cell, rest = divmod(arg, nv * nv)
            old, new = divmod(rest, nv)
            yield Send("server", ("cas", cell, old, new))
        msg = yield Recv()
        return msg.payload


class RacyMultiCasSUT:
    """Racy: CAS is read-compare-write as separate round trips; a
    concurrent write to the SAME cell between the read and the write is
    silently clobbered (lost update inside one cell) while the CAS still
    reports success.  Expected to FAIL — and only the decomposed checker
    can afford to catch it on long histories."""

    def __init__(self, spec: MultiCasSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {}
        sched.spawn("server", _cell_server(self.store), daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        nv = self.spec.n_values
        if cmd == READ:
            yield Send("server", ("read", arg))
            msg = yield Recv()
            return msg.payload
        if cmd == WRITE:
            cell, value = divmod(arg, nv)
            yield Send("server", ("write", cell, value))
            msg = yield Recv()
            return msg.payload
        cell, rest = divmod(arg, nv * nv)
        old, new = divmod(rest, nv)
        yield Send("server", ("read", cell))
        msg = yield Recv()
        if msg.payload != old:
            return 0
        # non-atomic: the compare happened client-side; another pid's
        # write to this cell can land before this write does
        yield Send("server", ("write", cell, new))
        yield Recv()
        return 1
