"""Counting semaphore (mutex at ``permits=1``) — the lock family the
generation plane stresses (ISSUE 17; ROADMAP item 3).

The model state is the number of permits currently AVAILABLE — scalar
with bound ``permits + 1`` — so the family rides the domain-table fast
paths like set/rangeset.  What makes it worth having next to them is
the bug shape: the racy implementation's ``try_acquire`` is the
check-then-act race the whole analysis plane revolves around — a load
of the permit count and a decrement in separate round trips, the exact
interprocedural pattern the race-lint fixtures seed
(``analysis/fixtures.py`` check-then-act stubs, family g) and the
QSM-RACE passes hunt statically.  Here the SAME pattern is caught
*dynamically*: two concurrent acquires of the last permit both observe
1 and both report success, and no linearization order admits two
acquires from one available permit.  The fixture stubs and this SUT
cross-check each other — one pins the analyzer, one pins the checker.
"""

from __future__ import annotations

import numpy as np

from ..core.spec import CmdSig, Spec
from ..sched.scheduler import Recv, Scheduler, Send

ACQUIRE = 0
RELEASE = 1
AVAILABLE = 2


class SemaphoreSpec(Spec):
    """Counting semaphore over ``permits`` permits.

    ACQUIRE responds 1 and takes a permit iff one is available, else 0
    (a non-blocking try-acquire: blocking would make every history with
    contention pending-only).  RELEASE responds 1 and returns a permit
    iff one is held, else 0 (over-release refused, so the count stays
    in domain).  AVAILABLE responds the current count; never mutates.
    """

    name = "semaphore"
    STATE_DIM = 1

    def __init__(self, permits: int = 2):
        if not 1 <= permits <= 8:
            raise ValueError(f"permits must be in [1, 8], got {permits}")
        self.permits = permits
        self.CMDS = (
            CmdSig("acquire", n_args=1, n_resps=2),
            CmdSig("release", n_args=1, n_resps=2),
            CmdSig("available", n_args=1, n_resps=permits + 1),
        )

    def initial_state(self) -> np.ndarray:
        return np.full(1, self.permits, np.int32)

    def scalar_state_bound(self, n_ops):
        return self.permits + 1  # available count stays in [0, permits]

    def spec_kwargs(self):
        return {"permits": self.permits}

    def step_py(self, state, cmd, arg, resp):
        avail = state[0]
        if cmd == ACQUIRE:
            if avail > 0:
                return [avail - 1], resp == 1
            return [avail], resp == 0
        if cmd == RELEASE:
            if avail < self.permits:
                return [avail + 1], resp == 1
            return [avail], resp == 0
        return [avail], resp == avail

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        avail = state[0]
        can_take = avail > 0
        can_give = avail < self.permits
        ok = jnp.where(
            cmd == ACQUIRE, resp == can_take.astype(resp.dtype),
            jnp.where(cmd == RELEASE, resp == can_give.astype(resp.dtype),
                      resp == avail))
        new_avail = jnp.where(
            cmd == ACQUIRE, jnp.where(can_take, avail - 1, avail),
            jnp.where(cmd == RELEASE,
                      jnp.where(can_give, avail + 1, avail), avail))
        return jnp.stack([new_avail.astype(state.dtype)]), ok


# ---------------------------------------------------------------------------
# SUT implementations
# ---------------------------------------------------------------------------

def _sem_server(store: dict, permits: int):
    """Server applying acquire/release/available atomically per message;
    also answers the racy SUT's load/decrement protocol."""
    while True:
        msg = yield Recv()
        kind = msg.payload
        if kind == "acquire":
            if store["avail"] > 0:
                store["avail"] -= 1
                yield Send(msg.src, 1)
            else:
                yield Send(msg.src, 0)
        elif kind == "release":
            if store["avail"] < permits:
                store["avail"] += 1
                yield Send(msg.src, 1)
            else:
                yield Send(msg.src, 0)
        elif kind == "available":
            yield Send(msg.src, store["avail"])
        elif kind == "take":
            # unconditional decrement — the racy client's second half.
            # Clamped at 0 so later ``available`` replies stay in the
            # spec's response domain (resp -1 is the history encoding's
            # pending sentinel); the violation lives in the two resp-1
            # acquires of one permit, not in a negative count.
            store["avail"] = max(0, store["avail"] - 1)
            yield Send(msg.src, 0)


class AtomicSemaphoreSUT:
    """Correct: acquire is one atomically-applied server message.
    Expected to PASS prop_concurrent."""

    def __init__(self, spec: SemaphoreSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {"avail": self.spec.permits}
        sched.spawn("server", _sem_server(self.store, self.spec.permits),
                    daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        kind = ("acquire", "release", "available")[cmd]
        yield Send("server", kind)
        msg = yield Recv()
        return msg.payload


class RacyCheckThenActSemaphoreSUT:
    """Racy: acquire loads the available count and decrements in
    SEPARATE round trips — the check-then-act shape the race-lint
    fixtures seed statically.  Two concurrent acquires of the last
    permit both observe 1 and both claim it (resp 1); the model says
    the second linearized acquire must respond 0.  Expected to FAIL."""

    def __init__(self, spec: SemaphoreSpec):
        self.spec = spec

    def setup(self, sched: Scheduler) -> None:
        self.store = {"avail": self.spec.permits}
        sched.spawn("server", _sem_server(self.store, self.spec.permits),
                    daemon=True)

    def perform(self, pid: int, cmd: int, arg: int):
        if cmd != ACQUIRE:
            kind = ("acquire", "release", "available")[cmd]
            yield Send("server", kind)
            msg = yield Recv()
            return msg.payload
        yield Send("server", "available")
        msg = yield Recv()
        if msg.payload <= 0:
            return 0
        # non-atomic: the availability check happened in a separate
        # round trip; another pid's take can land before this one does
        yield Send("server", "take")
        yield Recv()
        return 1
