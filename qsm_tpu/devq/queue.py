"""The persistent device-work queue: bank device-worthy work off-window.

Probe reality (r5: 9 device hits in 717 probes) makes device time the
scarcest resource in the system, yet until this module every plane only
ever checked on the host off-window.  The queue turns that around:
planes BANK device-worthy work continuously at their natural seams —

* ``check``   — serve admission banks oversize corpora (the largest
  compile buckets, where sharded dispatch pays most);
* ``pcomp``   — the per-key split banks validated sub-lane groups;
* ``shrink``  — round boundaries bank the still-undecided frontier;
* ``monitor`` — deciding appends bank the session's prefix re-check;
* ``warmup``  — the planner banks ``@meshN`` bucket-ladder warm
  compiles whenever a plan says the device pays,

and the window drain scheduler (:mod:`.drain`) spends a whole seized
window on the backlog, banking every verdict back under the EXACT
``serve.cache.fingerprint_key`` the originating plane will hit on its
next request.

Persistence is a second replog row domain: the queue owns its own
:class:`~qsm_tpu.fleet.replog.SegmentedLog` (``devq/`` under the
node's state dir), with two row shapes keyed by the item fingerprint —

    {"key": K, "plane": P, "item": {…}}      # banked work
    {"key": K, "done": 1}                    # drained (tombstone)

``done`` is ABSORBING (a tombstone), never ordered against the put row:
whichever of the two a node sees first, the item converges to done —
which is what makes anti-entropy order-free.  Any fleet node can bank;
gossip converges the queue fleet-wide (fleet/gossip.py grows a devq
exchange leg); the node that wins a window drains for everyone.

The in-memory index is CAPPED (``cap``, lowest-score eviction) — the
discipline lint family (o) ``QSM-DEVQ-UNBOUNDED`` gates, because a
fleet-fed queue with no bound is an OOM of the window host the first
time a busy fleet out-banks rare windows.  docs/WINDOWS.md is the
prose contract.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

#: Work planes, in starvation-accounting order.  ``warmup`` is the only
#: plane whose items carry no lanes (the work is the compile itself).
PLANES = ("check", "pcomp", "shrink", "monitor", "warmup")

#: In-memory pending cap.  Disk rows are unbounded-by-design (the replog
#: seals and gossips them); the cap bounds what one window host indexes.
DEFAULT_CAP = 512

# the done-tombstone index keeps this many keys beyond the pending cap;
# older tombstones fall back to the disk rows (re-adopting one costs a
# redundant re-check, never a wrong verdict)
_DONE_FACTOR = 4


def _stable_sha(doc) -> str:
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, default=list).encode()
    ).hexdigest()


def item_fingerprint(plane: str, model: str, spec_kwargs: dict,
                     lane_keys: Sequence[str]) -> str:
    """The queue row identity: plane + spec identity + the exact verdict
    row keys the drain will bank.  Two nodes banking the same corpus
    derive the same key — anti-entropy dedupes instead of double-work."""
    return _stable_sha([plane, model, spec_kwargs or {}, list(lane_keys)])


@dataclass
class WorkItem:
    """One banked unit of device-worthy work.

    ``lanes`` are wire-format history rows (serve/protocol.py
    ``history_to_rows``) and ``lane_keys[i]`` is the
    ``fingerprint_key`` of lane ``i`` — computed by the ORIGINATING
    plane, so the drain banks back under identities the plane's next
    request will actually hit (drain.py re-derives and refuses on
    mismatch rather than banking under a guessed key)."""

    key: str
    plane: str
    model: str
    spec_kwargs: dict = field(default_factory=dict)
    lanes: List[list] = field(default_factory=list)
    lane_keys: List[str] = field(default_factory=list)
    bucket: int = 1            # compile-bucket size proxy (score input)
    enq_ts: float = 0.0        # bank time (staleness input)
    node: str = "n0"           # originating fleet node

    def to_doc(self) -> dict:
        return {"key": self.key, "plane": self.plane,
                "model": self.model, "spec_kwargs": self.spec_kwargs,
                "lanes": self.lanes, "lane_keys": self.lane_keys,
                "bucket": self.bucket, "enq_ts": self.enq_ts,
                "node": self.node}

    @classmethod
    def from_doc(cls, doc: dict) -> "WorkItem":
        if doc.get("plane") not in PLANES:
            raise ValueError(f"devq item plane {doc.get('plane')!r} "
                             f"not in {PLANES}")
        return cls(key=str(doc["key"]), plane=doc["plane"],
                   model=str(doc.get("model", "")),
                   spec_kwargs=dict(doc.get("spec_kwargs") or {}),
                   lanes=[list(r) for r in doc.get("lanes") or []],
                   lane_keys=[str(k) for k in doc.get("lane_keys") or []],
                   bucket=int(doc.get("bucket", 1)),
                   enq_ts=float(doc.get("enq_ts", 0.0)),
                   node=str(doc.get("node", "n0")))


class DeviceWorkQueue:
    """Fingerprint-keyed, priority-scored, capped, optionally persistent.

    ``dir`` is the devq replog directory (None = memory-only, which the
    in-process seams use under tests); ``drained_planes`` feeds the
    starvation term of :meth:`score` and is updated by the drain
    scheduler via :meth:`note_drained`.
    """

    def __init__(self, dir: Optional[str] = None, *, node_id: str = "n0",
                 cap: int = DEFAULT_CAP, seal_rows: int = 64,
                 now=time.time):
        self.node_id = node_id
        self.cap = int(cap)
        self._now = now
        self._lock = threading.RLock()
        self._pending: Dict[str, WorkItem] = {}
        self._done: "OrderedDict[str, None]" = OrderedDict()
        self._drained_planes: Dict[str, int] = {p: 0 for p in PLANES}
        self.banked = 0       # puts accepted (fresh keys)
        self.evicted = 0      # cap evictions (lowest score first)
        self.log = None
        if dir is not None:
            from ..fleet.replog import SegmentedLog

            self.log = SegmentedLog(dir, node_id=node_id,
                                    seal_rows=seal_rows)
            self._fold_rows(self.log.load(), persist=False)

    # -- banking ----------------------------------------------------------
    def put(self, item: WorkItem, persist: bool = True) -> bool:
        """Bank one item; False when its key is already pending or
        drained (idempotent — the wire op and gossip both re-deliver)."""
        with self._lock:
            if item.key in self._done or item.key in self._pending:
                return False
            if not item.enq_ts:
                item.enq_ts = float(self._now())
            self._pending[item.key] = item
            self.banked += 1
            if persist and self.log is not None:
                self.log.append([json.dumps(
                    {"key": item.key, "plane": item.plane,
                     "item": item.to_doc()}, sort_keys=True)])
            self._evict_over_cap()
            return True

    def put_doc(self, doc: dict) -> bool:
        return self.put(WorkItem.from_doc(doc))

    def mark_done(self, key: str, persist: bool = True) -> bool:
        """Absorbing tombstone: the item never re-dispatches here, and
        the row gossips so it never re-dispatches ANYWHERE."""
        with self._lock:
            item = self._pending.pop(key, None)
            fresh = key not in self._done
            self._done[key] = None
            self._trim_done()
            if item is not None:
                self._drained_planes[item.plane] = (
                    self._drained_planes.get(item.plane, 0) + 1)
            if fresh and persist and self.log is not None:
                self.log.append([json.dumps(
                    {"key": key, "done": 1}, sort_keys=True)])
            return fresh

    def note_drained(self, plane: str, n: int = 1) -> None:
        with self._lock:
            self._drained_planes[plane] = (
                self._drained_planes.get(plane, 0) + int(n))

    def _evict_over_cap(self) -> None:
        # lowest score goes first: the cap sheds the work a window would
        # drain LAST anyway.  Never evicts below cap; lint family (o)
        # pins that this comparison + pop exist (QSM-DEVQ-UNBOUNDED).
        while len(self._pending) > self.cap:
            now = float(self._now())
            worst = min(self._pending,
                        key=lambda k: self.score(self._pending[k], now))
            self._pending.pop(worst)
            self.evicted += 1

    def _trim_done(self) -> None:
        limit = self.cap * _DONE_FACTOR
        while len(self._done) > limit:
            self._done.popitem(last=False)

    # -- scoring / draining ----------------------------------------------
    def score(self, item: WorkItem, now: Optional[float] = None) -> float:
        """bucket × staleness × plane starvation (ISSUE 20 drain order):
        big compile buckets amortize the window best, old items first
        within a bucket class, and a plane nothing has drained yet beats
        one already served this window."""
        if now is None:
            now = float(self._now())
        staleness = 1.0 + max(0.0, now - item.enq_ts) / 60.0
        starvation = 1.0 / (1.0 + self._drained_planes.get(item.plane, 0))
        return float(max(1, item.bucket)) * staleness * starvation

    def pending_items(self) -> List[WorkItem]:
        """Snapshot in drain order (score descending, key tiebreak)."""
        with self._lock:
            now = float(self._now())
            return sorted(self._pending.values(),
                          key=lambda it: (-self.score(it, now), it.key))

    def get(self, key: str) -> Optional[WorkItem]:
        with self._lock:
            return self._pending.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- anti-entropy surface ---------------------------------------------
    # Delegates to the underlying SegmentedLog; gossip treats the devq
    # log exactly like the verdict replog (digest → missing → pull →
    # adopt), then folds adopted rows into the live index here.
    def digests(self) -> Dict[str, str]:
        return self.log.digests() if self.log is not None else {}

    def missing(self, remote: Dict[str, str]) -> List[str]:
        return self.log.missing(remote) if self.log is not None else []

    def read_segment(self, name: str):
        if self.log is None:
            raise KeyError(name)
        return self.log.read_segment(name)

    def adopt(self, name: str, fingerprint: str,
              lines: Sequence[str]) -> int:
        """Adopt a remote devq segment: verify + persist via the log,
        then fold the rows into the live index (done rows ABSORB —
        arrival order across segments does not matter)."""
        if self.log is None:
            return 0
        rows = self.log.adopt(name, fingerprint, lines)
        return self._fold_rows(rows, persist=False)

    def _fold_rows(self, rows: Sequence[dict], persist: bool) -> int:
        folded = 0
        for row in rows:
            key = str(row.get("key"))
            if row.get("done"):
                if self.mark_done(key, persist=persist):
                    folded += 1
            elif isinstance(row.get("item"), dict):
                try:
                    item = WorkItem.from_doc(row["item"])
                except (KeyError, ValueError, TypeError):
                    continue  # foreign/corrupt row: skip, never wedge
                if self.put(item, persist=persist):
                    folded += 1
        return folded

    # -- accounting --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            by_plane: Dict[str, int] = {}
            for it in self._pending.values():
                by_plane[it.plane] = by_plane.get(it.plane, 0) + 1
            return {"pending": len(self._pending),
                    "pending_by_plane": by_plane,
                    "done": len(self._done),
                    "banked": self.banked, "evicted": self.evicted,
                    "drained_by_plane": dict(self._drained_planes),
                    "cap": self.cap,
                    "persistent": self.log is not None}


# ---------------------------------------------------------------------------
# The process-global queue: how in-engine seams (planner build_backend,
# shrink rounds, monitor appends) reach a queue the serve layer owns —
# the same set_global pattern obs uses for its recorder.
_GLOBAL: Optional[DeviceWorkQueue] = None
_GLOBAL_LOCK = threading.Lock()


def set_global_devq(queue: Optional[DeviceWorkQueue]) -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = queue


def global_devq() -> Optional[DeviceWorkQueue]:
    return _GLOBAL


def bank_histories(spec, histories, *, plane: str,
                   queue: Optional[DeviceWorkQueue] = None,
                   bucket: Optional[int] = None,
                   node: Optional[str] = None) -> Optional[str]:
    """Bank a (spec, histories) corpus as ONE item; the convenience every
    plane seam calls.  No-ops (None) when no queue is configured — the
    seams must cost nothing on the ordinary host path."""
    q = queue if queue is not None else global_devq()
    if q is None or not histories:
        return None
    from ..serve.cache import fingerprint_key
    from ..serve.protocol import history_to_rows

    lane_keys = [fingerprint_key(spec, h) for h in histories]
    kwargs = spec.spec_kwargs()
    key = item_fingerprint(plane, spec.name, kwargs, lane_keys)
    item = WorkItem(
        key=key, plane=plane, model=spec.name, spec_kwargs=kwargs,
        lanes=[history_to_rows(h) for h in histories],
        lane_keys=lane_keys,
        bucket=bucket if bucket is not None
        else max(len(h.ops) for h in histories),
        node=node or q.node_id)
    q.put(item)
    return key


def note_device_plan(spec, plan) -> Optional[str]:
    """Planner seam (``build_backend``): when a plan is mesh-sized the
    window wants its ``@meshN`` bucket ladder already compiled — bank a
    ``warmup`` item (no lanes; the drain compiles the ladder and checks
    a deterministic smoke corpus through it)."""
    q = global_devq()
    if q is None or getattr(plan, "mesh_devices", 1) <= 1:
        return None
    kwargs = spec.spec_kwargs()
    key = item_fingerprint("warmup", spec.name, kwargs,
                           [plan.name, str(plan.mesh_devices)])
    q.put(WorkItem(key=key, plane="warmup", model=spec.name,
                   spec_kwargs=kwargs,
                   bucket=max(plan.batch_buckets or (1,)),
                   node=q.node_id))
    return key
