"""Window arbitrage: a persistent device-work queue + drain scheduler.

Device windows are rare (probe reality: 9 hits in 717 probes) while
device-worthy work is continuous — so the two are decoupled, the way
OmniLink-style trace validation decouples capture from checking.
Planes BANK work into a fleet-replicated :class:`.queue.DeviceWorkQueue`
at their natural seams; when a window lands, :class:`.drain
.DrainScheduler` spends ALL of it on the backlog and banks every
oracle-re-proved verdict under the exact fingerprint the originating
plane will hit next.  docs/WINDOWS.md is the contract; wire ops
``devq.put/digests/pull/drain_report`` extend PROTOCOL.json.
"""

from .drain import DEFAULT_WINDOW_S, DrainScheduler
from .queue import (DEFAULT_CAP, PLANES, DeviceWorkQueue, WorkItem,
                    bank_histories, global_devq, item_fingerprint,
                    note_device_plan, set_global_devq)

__all__ = [
    "DEFAULT_CAP",
    "DEFAULT_WINDOW_S",
    "DeviceWorkQueue",
    "DrainScheduler",
    "PLANES",
    "WorkItem",
    "bank_histories",
    "global_devq",
    "item_fingerprint",
    "note_device_plan",
    "set_global_devq",
]
