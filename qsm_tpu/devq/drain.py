"""The window drain scheduler: spend a whole seized window on the queue.

When ``tools/probe_watcher.py`` seizes a device window it hands this
scheduler the window's PROBED device set and a deadline; the scheduler
then drains the banked :class:`~qsm_tpu.devq.queue.DeviceWorkQueue` in
score order until the window closes.  The contract, clause by clause
(ISSUE 20 / docs/WINDOWS.md):

* **Mesh from the window, not a count** — the mesh is built from the
  exact devices that answered the probe (:func:`qsm_tpu.mesh.topology
  .mesh_from_devices`); a forced ``make_mesh(n)`` would happily include
  a chip the window never offered.  Batches ride
  ``mesh/dispatch.sharded_backend`` like every other plane.
* **Soundness: the device never gets the last word.**  Every drained
  verdict is re-proved by a FRESH host memo oracle
  (``WingGongCPU(memo=True)``) before banking; the banked verdict IS
  the oracle's, under the exact ``fingerprint_key`` the originating
  plane computed at bank time (re-derived here and refused on
  mismatch).  A device/oracle disagreement increments
  ``wrong_verdicts`` — the bench gate pins it at zero — and banks the
  oracle's answer.  The window can therefore only ever make the system
  FASTER, never wrong.
* **A snatched-away chip degrades, never wedges** — every loop
  iteration consults the remaining window time (the QSM-DEVQ-DRAIN
  lint discipline), and a device dispatch that raises or runs past the
  deadline drops that item (and the rest of the window) to the host
  ladder instead of blocking on a dead chip.
* **Kill-mid-drain resumes exactly-once** — each item is a
  :class:`~qsm_tpu.resilience.checkpoint.CellJournal` cell keyed by
  its queue fingerprint; a SIGKILLed drainer's successor replays
  completions from the journal and re-dispatches ZERO completed items.
* **Accounting** — the report records per-plane device-vs-host ratios
  (the host re-proof doubles as the matched-budget host baseline) and
  ``window_utilization`` (fraction of the drain wall-clock spent in
  engine dispatch), which the serve ``health`` verb reports as an SLO.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .queue import PLANES, DeviceWorkQueue, WorkItem

#: Below this many seconds of window left, stop starting new items —
#: a half-dispatched batch at window close costs more than it pays.
DEFAULT_MIN_ITEM_S = 0.05

#: Simulated/default window length when the caller gives no deadline.
DEFAULT_WINDOW_S = 30.0

#: Lanes for the deterministic warmup smoke corpus (small: the point of
#: a warmup item is the compile, the lanes just prove the executable).
_WARMUP_LANES = 4
_WARMUP_SEED = 20_000_20


class DrainScheduler:
    """One window: drain the queue, bank oracle-proved verdicts, report.

    ``devices`` is the window's probed device list (jax Device objects);
    ``mesh`` may be passed pre-built instead.  ``cache`` is the verdict
    bank (:class:`~qsm_tpu.serve.cache.VerdictCache` or anything with
    its ``put_many``); None still drains and re-proves, it just cannot
    bank.  ``journal_path`` enables kill-mid-drain resume."""

    def __init__(self, queue: DeviceWorkQueue, *, cache=None,
                 devices: Optional[list] = None, mesh=None,
                 window_s: Optional[float] = None,
                 window_end: Optional[float] = None,
                 journal_path: Optional[str] = None,
                 window_id: str = "w0", resume: bool = False,
                 budget: int = 2_000,
                 min_item_s: float = DEFAULT_MIN_ITEM_S,
                 device_dispatch: bool = True,
                 now=time.monotonic):
        self.queue = queue
        self.cache = cache
        self.budget = int(budget)
        self.min_item_s = float(min_item_s)
        self.window_id = window_id
        self._now = now
        if mesh is None:
            if devices is None:
                import jax

                devices = list(jax.devices())
            from ..mesh.topology import mesh_from_devices

            mesh = mesh_from_devices(devices)
        self.mesh = mesh
        self.n_devices = int(getattr(mesh, "size", 1))
        if window_end is None:
            window_end = float(now()) + float(window_s if window_s
                                              is not None
                                              else DEFAULT_WINDOW_S)
        self.window_end = float(window_end)
        # flips False the first time the device path raises: the rest of
        # the window degrades to the host ladder instead of retrying a
        # chip the scheduler no longer owns
        self._device_ok = bool(device_dispatch)
        # ONE spec instance per (model, kwargs): the cached backends are
        # spec-BOUND (CppOracle asserts identity), so every item of the
        # same shape must hand them the same instance back
        self._specs: Dict[str, object] = {}
        self._backends: Dict[str, object] = {}   # spec_key -> device be
        self._host: Dict[str, object] = {}       # spec_key -> host ladder
        self.journal = None
        if journal_path is not None:
            from ..resilience.checkpoint import CellJournal

            self.journal = CellJournal(
                journal_path,
                {"artifact": "qsm_tpu_devq_drain",
                 "device_fallback": None, "window_id": window_id},
                resume=resume,
                match_keys=("artifact", "window_id"))

    # ------------------------------------------------------------------
    def _remaining_s(self) -> float:
        return self.window_end - float(self._now())

    @staticmethod
    def _spec_key(item: WorkItem) -> str:
        import json as _json

        return _json.dumps([item.model, item.spec_kwargs or {}],
                           sort_keys=True)

    def _spec_for(self, item: WorkItem):
        key = self._spec_key(item)
        spec = self._specs.get(key)
        if spec is None:
            from ..models.registry import make

            spec, _ = make(item.model, "atomic", item.spec_kwargs or None)
            self._specs[key] = spec
        return spec

    def _device_backend(self, item: WorkItem, spec):
        key = self._spec_key(item)
        be = self._backends.get(key)
        if be is None:
            from ..mesh.dispatch import sharded_backend

            be = sharded_backend(spec, mesh=self.mesh,
                                 budget=self.budget)
            self._backends[key] = be
        return be

    def _host_backend(self, item: WorkItem, spec):
        key = self._spec_key(item)
        be = self._host.get(key)
        if be is None:
            from ..search.planner import build_host_backend, plan_search

            be = build_host_backend(spec, plan_search(spec))
            self._host[key] = be
        return be

    @staticmethod
    def _lanes_of(item: WorkItem, spec):
        """Reconstruct the item's histories.  Warmup items carry none;
        their smoke corpus is rebuilt deterministically (same seeds →
        same histories → same fingerprints on every node)."""
        from ..serve.protocol import rows_to_history

        if item.plane == "warmup" and not item.lanes:
            from ..models.registry import MODELS
            from ..utils.corpus import build_corpus

            entry = MODELS[item.model]
            return build_corpus(
                spec, [entry.impls["atomic"]], _WARMUP_LANES,
                n_pids=entry.default_pids, max_ops=entry.default_ops,
                seed_base=_WARMUP_SEED, seed_prefix="devq-warmup")
        return [rows_to_history(rows) for rows in item.lanes]

    # ------------------------------------------------------------------
    def drain(self) -> dict:
        """Drain until the queue or the window is exhausted; return the
        window report (the artifact ``tools/window_drain.py`` commits)."""
        t0 = float(self._now())
        started = self.queue.snapshot()
        per_plane = {p: {"items": 0, "lanes": 0, "device_items": 0,
                         "host_items": 0, "device_s": 0.0,
                         "host_s": 0.0} for p in PLANES}
        dispatched: List[str] = []
        resumed: List[str] = []
        busy_s = 0.0
        wrong = key_mismatch = banked = 0
        deadline_stopped = False
        while True:
            remaining = self._remaining_s()
            if remaining <= self.min_item_s:
                deadline_stopped = len(self.queue) > 0
                break
            # re-rank every iteration: draining a plane feeds its own
            # starvation term, so the order interleaves planes instead
            # of burning the window on whichever banked the most
            items = self.queue.pending_items()
            if not items:
                break
            item = items[0]
            if self.journal is not None:
                prior = self.journal.complete(item.key)
                if prior is not None:
                    # a predecessor drained this before it was killed:
                    # fold the completion, re-dispatch NOTHING
                    self.queue.mark_done(item.key)
                    resumed.append(item.key)
                    continue
            row, item_busy = self._run_item(item, remaining)
            busy_s += item_busy
            stats = per_plane[item.plane]
            stats["items"] += 1
            stats["lanes"] += row["lanes"]
            stats[f"{row['path']}_items"] += 1
            stats["device_s"] += row["device_s"]
            stats["host_s"] += row["host_s"]
            wrong += row["wrong"]
            key_mismatch += row["key_mismatch"]
            banked += row["banked"]
            dispatched.append(item.key)
            if self.journal is not None:
                self.journal.emit(item.key, row)
            self.queue.mark_done(item.key)
        elapsed = max(1e-9, float(self._now()) - t0)
        for stats in per_plane.values():
            # host_s is the fresh-oracle re-proof of the SAME lanes: a
            # matched-budget host baseline, so the ratio is (host
            # seconds per lane) / (device seconds per lane)
            stats["device_vs_host_ratio"] = (
                round(stats["host_s"] / stats["device_s"], 6)
                if stats["device_s"] > 0 else None)
            stats["device_s"] = round(stats["device_s"], 6)
            stats["host_s"] = round(stats["host_s"], 6)
        return {
            "window_id": self.window_id,
            "devices": self.n_devices,
            "mesh_axes": list(getattr(self.mesh, "axis_names", ())),
            "pending_at_open": started["pending"],
            "drained": len(dispatched),
            "dispatched": dispatched,
            "resumed": resumed,
            "deadline_stopped": deadline_stopped,
            "wrong_verdicts": wrong,
            "key_mismatches": key_mismatch,
            "banked_rows": banked,
            "per_plane": per_plane,
            "elapsed_s": round(elapsed, 3),
            "busy_s": round(busy_s, 3),
            "window_utilization": round(busy_s / elapsed, 3),
        }

    # ------------------------------------------------------------------
    def _run_item(self, item: WorkItem, remaining: float):
        """One item: device dispatch (host ladder when the window is too
        thin or the chip vanished), fresh-oracle re-proof, bank."""
        from ..ops.backend import Verdict
        from ..ops.wing_gong_cpu import WingGongCPU
        from ..serve.cache import fingerprint_key

        spec = self._spec_for(item)
        hists = self._lanes_of(item, spec)
        path, device_s = "host", 0.0
        verdicts = None
        if self._device_ok and remaining > self.min_item_s:
            t0 = float(self._now())
            try:
                be = self._device_backend(item, spec)
                verdicts = be.check_histories(spec, hists)
                path = "device"
            except Exception:
                # the chip was snatched away (or the build died):
                # degrade THIS window to the host ladder, keep draining
                self._device_ok = False
                verdicts = None
            device_s = float(self._now()) - t0
        if verdicts is None:
            t0 = float(self._now())
            be = self._host_backend(item, spec)
            verdicts = be.check_histories(spec, hists)
            device_s = float(self._now()) - t0
            path = "host"
        # fresh memo oracle per ITEM: no state shared with the engine
        # under test, so agreement actually proves something
        t0 = float(self._now())
        oracle = WingGongCPU(memo=True)
        proofs = oracle.check_histories(spec, hists)
        host_s = float(self._now()) - t0
        undecided = int(Verdict.BUDGET_EXCEEDED)
        wrong = sum(1 for v, p in zip(verdicts, proofs)
                    if int(v) != undecided and int(v) != int(p))
        rows, key_mismatch = [], 0
        lane_keys = item.lane_keys or [None] * len(hists)
        for h, stored, proof in zip(hists, lane_keys, proofs):
            true_key = fingerprint_key(spec, h)
            if stored is not None and stored != true_key:
                # a corrupted / foreign item must not poison the bank
                # under a key some other history owns
                key_mismatch += 1
                continue
            rows.append((true_key, int(proof), None))
        if self.cache is not None and rows:
            self.cache.put_many(rows)
        return ({"plane": item.plane, "path": path, "lanes": len(hists),
                 "device_s": round(device_s, 6),
                 "host_s": round(host_s, 6),
                 "wrong": wrong, "key_mismatch": key_mismatch,
                 "banked": len(rows),
                 "verdicts": [int(p) for p in proofs]},
                device_s + host_s)
