"""The closed loop — ``qsm-tpu fuzz --addr``: soak a live fleet with
generated work, trusting nothing it answers.

The steering loop runs client-side against a serve-plane shim: every
round's corpus goes up as an ordinary ``check`` request (witnesses
requested), and every returned verdict is **re-proved locally** before
it counts —

* a fresh memo oracle (``WingGongCPU(memo=True)``, built per batch so no
  cache state survives between rounds) re-checks every history; a
  decided fleet verdict that contradicts a decided oracle verdict is a
  ``wrong_verdict`` — the closed loop's only failure currency;
* every ``LINEARIZABLE`` with a witness is replayed search-free through
  ``verify_witness`` (ops/backend.py) — the fleet's proof obligation,
  not its word;
* a slice of generated histories is also STREAMED through monitor
  sessions (``session.open/append/close``) so the soak exercises the
  incremental frontier plane, not just the batch path.

The oracle re-check is not only audit: the shim absorbs the local
oracle's ``SearchStats``, so the steering loop's nodes-per-history
signal measures real search hardness even though the fleet's own
counters stay server-side.  The PR 15 SLO/health plane is the judge —
the report carries the fleet's ``health`` answer and the run maps it to
the same exit codes ``qsm-tpu health`` uses (obs/slo.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from ..obs.slo import HEALTH_EXIT_CODES, HEALTH_EXIT_UNREACHABLE
from ..ops.backend import Verdict, verify_witness
from ..ops.wing_gong_cpu import WingGongCPU
from ..search.stats import SearchStats, collect_search_stats
from ..serve.client import CheckClient, SessionHandle
from ..serve.protocol import VERDICT_NAMES, history_to_rows
from .steer import SteeringLoop

_NAME_TO_VERDICT = {n: i for i, n in enumerate(VERDICT_NAMES)}
# kept wrongness provenance: the COUNT is exact forever, the detail
# rows are capped (QSM-GEN-UNBOUNDED discipline — one wrong verdict is
# an incident, ten thousand identical ones are a counter)
_WRONG_KEEP = 32


class _FleetBackend:
    """``check_histories`` over a :class:`CheckClient`, oracle-audited.

    Looks like any other backend to the steering loop; every answer is
    cross-examined (module docstring) and the audit oracle's search
    counters become this backend's ``search_stats()``."""

    def __init__(self, client: CheckClient, model: str,
                 spec_kwargs: Optional[dict] = None,
                 deadline_s: Optional[float] = None):
        self.client = client
        self.model = model
        self.spec_kwargs = spec_kwargs
        self.deadline_s = deadline_s
        self.stats = SearchStats(engine="fleet-fuzz")
        self.wrong_verdicts = 0
        self.wrong: List[dict] = []       # provenance of each wrongness
        self.witnesses_verified = 0
        self.sheds = 0

    def check_histories(self, spec, histories):
        doc = self.client.check(self.model, list(histories),
                                spec_kwargs=self.spec_kwargs,
                                witness=True,
                                deadline_s=self.deadline_s)
        if not doc.get("ok"):
            # an honest shed/refusal is back-pressure, not wrongness:
            # surface it as undecided and let the loop keep breathing
            self.sheds += 1
            return [int(Verdict.BUDGET_EXCEEDED)] * len(histories)
        verdicts = [_NAME_TO_VERDICT[v] for v in doc["verdicts"]]
        witnesses = doc.get("witnesses") or [None] * len(verdicts)
        oracle = WingGongCPU(memo=True)  # fresh: no banked state
        truth = oracle.check_histories(spec, list(histories))
        self.stats.absorb(collect_search_stats(oracle))
        undecided = int(Verdict.BUDGET_EXCEEDED)
        for i, (h, v, w) in enumerate(zip(histories, verdicts,
                                          witnesses)):
            t = int(truth[i])
            if v != undecided and t != undecided and v != t:
                self._record_wrong({"index": i,
                                    "fleet": VERDICT_NAMES[v],
                                    "oracle": VERDICT_NAMES[t],
                                    "seed": h.seed,
                                    "program_id": h.program_id})
            if v == int(Verdict.LINEARIZABLE) and w is not None:
                if verify_witness(spec, h, [tuple(p) for p in w]):
                    self.witnesses_verified += 1
                else:
                    self._record_wrong({"index": i, "fleet": "witness",
                                        "oracle": "replay-failed",
                                        "seed": h.seed,
                                        "program_id": h.program_id})
        return verdicts

    def _record_wrong(self, row: dict) -> None:
        self.wrong_verdicts += 1
        if len(self.wrong) < _WRONG_KEEP:  # count exact, detail capped
            self.wrong.append(row)

    def search_stats(self) -> SearchStats:
        return dataclasses.replace(self.stats)


def _stream_session(client: CheckClient, model: str, history, *,
                    spec_kwargs: Optional[dict] = None,
                    deadline_s: Optional[float] = None,
                    chunk: int = 8) -> dict:
    """One generated history through the monitor plane, in invoke-order
    chunks (the live-wire-tap shape, docs/MONITOR.md)."""
    handle = SessionHandle(client, model, spec_kwargs=spec_kwargs,
                           deadline_s=deadline_s)
    rows = history_to_rows(history)
    for i in range(0, len(rows), chunk):
        handle.append(rows[i:i + chunk])
    handle.close()
    return {"verdict": handle.verdict, "flips": len(handle.flips)}


def fuzz_fleet(address: str, models: Sequence[str], *, rounds: int = 4,
               batch: int = 16, seed: int = 0, pool_cap: int = 16,
               path: str = "auto", session_every: int = 2,
               deadline_s: Optional[float] = 30.0,
               timeout_s: float = 60.0,
               checkpoint_dir: Optional[str] = None,
               log=None) -> dict:
    """Soak the fleet at ``address`` (comma list = failover set) with
    steered generated work across ``models``; returns the report the
    acceptance gate reads: per-model round reports, the audit ledger
    (``wrong_verdicts_total`` — must be 0 against a healthy fleet), and
    the fleet's own health answer mapped to ``qsm-tpu health`` exit
    semantics."""
    from ..models.registry import MODELS

    report: Dict = {"address": address, "models": {}, "rounds": rounds,
                    "batch": batch, "wrong_verdicts_total": 0,
                    "flips_total": 0, "seqs_total": 0}
    with CheckClient(address, timeout_s=timeout_s) as client:
        for model in models:
            spec = MODELS[model].make_spec()
            backend = _FleetBackend(client, model,
                                    deadline_s=deadline_s)
            loop = SteeringLoop(spec, backend, batch=batch, seed=seed,
                                pool_cap=pool_cap, path=path)
            if checkpoint_dir:
                import os

                ck = os.path.join(checkpoint_dir, f"fuzz_{model}.json")
                loop.load(ck)
            sessions = []
            round_reports = []
            for r in range(rounds):
                rr = loop.round()
                round_reports.append(rr)
                if log:
                    log(f"fuzz {model} round {r}: flips={rr['flips']} "
                        f"score={rr['score']}")
                if session_every and r % session_every == 0:
                    # stream the round's last flip (or any history) live
                    src = (loop.flip_histories[-1][0]
                           if loop.flip_histories else None)
                    if src is None:
                        from .core import generate_batch

                        src = generate_batch(spec, loop.pool.best().profile,
                                             seed * 7919 + r, 1,
                                             path=path)[0]
                    sessions.append(_stream_session(
                        client, model, src, deadline_s=deadline_s))
            if checkpoint_dir:
                loop.save(ck)
            st = loop.stats
            best = loop.pool.best()
            report["models"][model] = {
                "rounds": round_reports,
                "gen_seqs": st.gen_seqs,
                "gen_mutations": st.gen_mutations,
                "gen_flips": st.gen_flips,
                "gen_feedback_rounds": st.gen_feedback_rounds,
                "wrong_verdicts": backend.wrong_verdicts,
                "wrong": backend.wrong,
                "witnesses_verified": backend.witnesses_verified,
                "sheds": backend.sheds,
                "sessions": sessions,
                "session_flips": sum(s["flips"] for s in sessions),
                "best_profile": best.profile.to_dict() if best else None,
            }
            report["wrong_verdicts_total"] += backend.wrong_verdicts
            report["flips_total"] += st.gen_flips
            report["seqs_total"] += st.gen_seqs
        # the judge: the fleet's own SLO/health answer, mapped to the
        # same exit codes `qsm-tpu health` gives operators
        try:
            health = client.health()
        except (ConnectionError, OSError) as e:
            health = {"ok": False, "status": "unreachable",
                      "error": f"{type(e).__name__}: {e}"}
        report["health"] = health
        report["health_status"] = str(health.get("status",
                                                 "unreachable"))
        report["exit_code"] = (
            HEALTH_EXIT_CODES.get(report["health_status"],
                                  HEALTH_EXIT_UNREACHABLE)
            if health.get("ok") else HEALTH_EXIT_UNREACHABLE)
    return report
