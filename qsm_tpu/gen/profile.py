"""``GenProfile`` — the generation plane's workload grammar.

A profile is the COMPLETE description of a workload shape: together with
a seed it reproduces a corpus bit-for-bit (the same (seed, config)
determinism contract the rest of the framework rides — core/generator.py
docstring).  The steering loop (gen/steer.py) never mutates histories
directly; it mutates profiles, because a profile survives checkpointing
as six JSON scalars while a corpus is megabytes of arrays.

The knobs map one-to-one onto what the check plane is sensitive to:

* ``op_mix`` — per-command weights; skewing toward mutators vs readers
  moves histories between trivially-linearizable and contended;
* ``key_skew`` — argument bias toward low values (0 = uniform): high
  skew piles every pid onto the same keys, which is where atomicity
  bugs and search blow-ups both live;
* ``overlap`` — probability an idle pid invokes while others are
  outstanding: the direct dial on real-time-order density (overlap 0 is
  a sequential history; 1 maximizes concurrent spans);
* ``p_pending`` — crash/drop rate (ops that never respond);
* ``p_adverse`` — the near-miss dial: completions default to a
  model-consistent response (the corpus is linearizable BY CONSTRUCTION
  — its own completion order is a witness), and with this probability a
  response is drawn uniformly instead.  Small values produce the
  boundary corpora a linearizability fuzzer exists for: almost-valid
  histories that are expensive to search and occasionally violate;
* ``n_pids`` / ``n_ops`` — the batch geometry, bucket-sized downstream.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

# mutation bounds: a mutated profile must stay inside the domain every
# consumer accepts (bucket_for caps n_ops; the scheduler plane's pid
# range; probabilities in [0, 1])
_MAX_PIDS = 16
_MAX_OPS = 128
_MAX_SKEW = 4.0


@dataclasses.dataclass(frozen=True)
class GenProfile:
    """One workload shape (module docstring).  Frozen: the steering
    loop's mutate() returns a NEW profile, so seed-pool entries never
    alias — a scored profile is exactly the one that earned the score."""

    op_mix: Tuple[float, ...] = ()   # per-cmd weights; () = uniform
    key_skew: float = 0.0            # arg bias toward 0 (0 = uniform)
    n_pids: int = 4
    n_ops: int = 24
    overlap: float = 0.5             # invoke-vs-complete tick bias
    p_pending: float = 0.0           # ops that never respond
    p_adverse: float = 0.01          # off-model response rate

    def to_dict(self) -> dict:
        return {"op_mix": list(self.op_mix), "key_skew": self.key_skew,
                "n_pids": self.n_pids, "n_ops": self.n_ops,
                "overlap": self.overlap, "p_pending": self.p_pending,
                "p_adverse": self.p_adverse}

    @classmethod
    def from_dict(cls, d: dict) -> "GenProfile":
        return cls(op_mix=tuple(float(w) for w in d.get("op_mix", ())),
                   key_skew=float(d.get("key_skew", 0.0)),
                   n_pids=int(d.get("n_pids", 4)),
                   n_ops=int(d.get("n_ops", 24)),
                   overlap=float(d.get("overlap", 0.5)),
                   p_pending=float(d.get("p_pending", 0.0)),
                   p_adverse=float(d.get("p_adverse", 0.01)))

    def weights(self, n_cmds: int) -> Tuple[float, ...]:
        """The op mix normalized against a spec's alphabet: padded/cut
        to ``n_cmds`` and floored at a small epsilon so no command is
        ever starved to exactly zero (a mix that can never emit a
        mutator generates corpora no mutation can rescue)."""
        mix = list(self.op_mix[:n_cmds])
        mix += [1.0] * (n_cmds - len(mix))
        mix = [max(0.05, float(w)) for w in mix]
        total = sum(mix)
        return tuple(w / total for w in mix)

    def mutate(self, rng) -> "GenProfile":
        """One seeded perturbation — exactly one knob moves per call, so
        a score delta is attributable to it (the steering loop's credit
        assignment stays legible).  ``rng`` is a ``random.Random``."""
        knob = rng.randrange(6)
        if knob == 0:   # re-weight one command
            mix = list(self.op_mix) or [1.0]
            i = rng.randrange(len(mix) + 1)
            if i == len(mix):
                mix.append(1.0)  # widen the mix to cover one more cmd
            else:
                mix[i] = max(0.05, mix[i] * rng.choice((0.5, 2.0)))
            return dataclasses.replace(self, op_mix=tuple(mix))
        if knob == 1:   # key skew
            skew = min(_MAX_SKEW, max(
                0.0, self.key_skew + rng.choice((-0.5, 0.5))))
            return dataclasses.replace(self, key_skew=skew)
        if knob == 2:   # overlap density
            ov = min(0.95, max(0.05,
                               self.overlap + rng.choice((-0.15, 0.15))))
            return dataclasses.replace(self, overlap=ov)
        if knob == 3:   # pending rate
            pp = min(0.3, max(0.0,
                              self.p_pending + rng.choice((-0.05, 0.05))))
            return dataclasses.replace(self, p_pending=pp)
        if knob == 4:   # near-miss rate
            pa = min(0.5, max(0.0,
                              self.p_adverse + rng.choice((-0.05, 0.05))))
            return dataclasses.replace(self, p_adverse=pa)
        # geometry: nudge pids or ops (ops by a bucket-friendly step)
        if rng.random() < 0.5:
            pids = min(_MAX_PIDS, max(2, self.n_pids
                                      + rng.choice((-1, 1))))
            return dataclasses.replace(self, n_pids=pids)
        ops = min(_MAX_OPS, max(4, self.n_ops + rng.choice((-8, 8))))
        return dataclasses.replace(self, n_ops=ops)
