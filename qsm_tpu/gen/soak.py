"""The chaos soak rig — ``qsm-tpu soak`` / ``tools/soak_sessions.py``:
thousands of open monitor sessions held through real fleet churn.

ISSUE 18's durable-session gate, executable: the rig spawns a 3-node
fleet (durable ``--session-dir`` substrates, segmented replogs) behind
an active/standby router pair sharing one lease store, opens ≥1000
monitor sessions through the failover client, then drives the fault
schedule the acceptance criteria name while the streams keep appending:

* (a) a **rolling restart** of all three nodes — SIGKILL, respawn on
  the same port, durable sessions restore from snapshot+journal and
  re-commit their decided prefixes from the banked rows (``prefix_hits``,
  zero engine folds — the monkeypatched pin lives in tests/test_monitor);
* (b) a **SIGKILL of the active router** — the standby takes the lease
  within ~1.5x TTL and the comma-address client rides the failover;
  the PR 17 closed loop (gen/fleet.py ``fuzz_fleet``) runs against the
  survivor mid-takeover, every verdict re-proved by a fresh memo oracle;
* (c) one **node leave + node join** over the elastic-membership verbs
  (``node.leave`` migrates the departed owner's live sessions,
  ``node.join`` hands the newcomer its replog by anti-entropy).

Nothing the fleet answers is trusted: every session's full event stream
is re-checked by a fresh ``WingGongCPU(memo=True)`` oracle at the end —
a decided close verdict that contradicts the oracle is a wrong verdict,
a flip the oracle refutes is an unproved flip, an oracle VIOLATION the
session never flipped is a LOST flip.  The PR 15 ``health`` verb judges
the surviving fleet and the report maps it to the ``qsm-tpu health``
exit codes.  All of it lands in one report dict (``gate_ok`` is the
acceptance line) that tools/soak_sessions.py banks as
BENCH_SESSIONS_<tag>.json.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ..obs.slo import HEALTH_EXIT_CODES, HEALTH_EXIT_UNREACHABLE
from ..ops.backend import Verdict
from ..ops.wing_gong_cpu import WingGongCPU
from ..serve.client import CheckClient
from ..serve.protocol import history_to_rows
from .profile import GenProfile

# retry budget for one session verb while a fault lands: routers shed
# during takeover and nodes vanish mid-restart — the rig is a client
# that does what real clients do (seq-idempotent re-send), so a verb
# only counts as LOST after the whole window passes
_RPC_TRIES = 60
_RPC_SLEEP_S = 0.25


def _spawn(cmd: List[str], banner_key: str, *,
           env_extra: Optional[dict] = None,
           timeout_s: float = 60.0) -> Tuple[subprocess.Popen, str]:
    """Start one fleet process and read its single JSON banner line;
    ``(proc, address)``.  Nodes are pinned to the CPU platform like
    every spawned checker process (the pool's rule: nothing races the
    operator's device plane)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            env=env)
    line = ""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.strip():
            break
    try:
        return proc, json.loads(line)[banner_key]
    except (ValueError, KeyError):
        proc.kill()
        raise RuntimeError(f"{cmd[3] if len(cmd) > 3 else cmd[0]} "
                           f"printed no {banner_key!r} banner")


def _kill(proc: Optional[subprocess.Popen], sig=signal.SIGKILL) -> None:
    if proc is None or proc.poll() is not None:
        return
    try:
        proc.send_signal(sig)
        proc.wait(timeout=10.0)
    except (OSError, subprocess.TimeoutExpired):
        proc.kill()


class _Fleet:
    """The rig's process tree: 3 durable nodes + 2 lease-sharing
    routers, each respawnable piecemeal (that IS the soak)."""

    def __init__(self, run_dir: str, *, lease_ttl_s: float,
                 max_sessions: int, faults: Optional[str]):
        self.run_dir = run_dir
        self.lease_ttl_s = lease_ttl_s
        self.max_sessions = max_sessions
        self.faults = faults
        self.nodes: Dict[str, Tuple[subprocess.Popen, str]] = {}
        self.routers: Dict[str, Tuple[subprocess.Popen, str]] = {}

    def spawn_node(self, nid: str, port: int = 0) -> str:
        cmd = [sys.executable, "-m", "qsm_tpu", "serve",
               "--port", str(port), "--node-id", nid,
               "--replog-dir", os.path.join(self.run_dir, "replog", nid),
               "--session-dir", os.path.join(self.run_dir, "sess", nid),
               "--max-sessions", str(self.max_sessions),
               "--replog-seal-rows", "64", "--flush-ms", "5"]
        env = {"QSM_TPU_FAULTS": self.faults} if self.faults else None
        proc, addr = _spawn(cmd, "serving", env_extra=env)
        self.nodes[nid] = (proc, addr)
        return addr

    def spawn_router(self, rid: str, lease: str) -> str:
        addrs = ",".join(a for _, a in self.nodes.values())
        cmd = [sys.executable, "-m", "qsm_tpu", "fleet",
               "--addrs", addrs, "--port", "0", "--router-id", rid,
               "--session-journal",
               os.path.join(self.run_dir, "router_sess"),
               "--lease-store", lease,
               "--lease-ttl-s", str(self.lease_ttl_s),
               "--heartbeat-s", "0.25", "--anti-entropy-s", "0.5"]
        proc, addr = _spawn(cmd, "fleet")
        self.routers[rid] = (proc, addr)
        return addr

    def restart_node(self, nid: str) -> str:
        """SIGKILL ``nid`` and respawn it on the SAME port with the
        same durable dirs — the same-host:port crash/respawn the
        rolling restart models (routers re-link without a re-address;
        sessions restore from the store)."""
        proc, addr = self.nodes[nid]
        _kill(proc)
        port = int(addr.rsplit(":", 1)[1])
        last: Optional[Exception] = None
        for _ in range(20):          # the freed port can lag a beat
            try:
                return self.spawn_node(nid, port=port)
            except RuntimeError as e:
                last = e
                time.sleep(0.5)
        raise RuntimeError(f"node {nid} respawn on :{port} failed "
                           f"({last})")

    def router_roles(self) -> Dict[str, str]:
        roles = {}
        for rid, (proc, addr) in self.routers.items():
            if proc.poll() is not None:
                continue
            try:
                with CheckClient(addr, timeout_s=5.0) as c:
                    st = c.stats().get("stats") or {}
                    roles[rid] = (st.get("lease") or {}).get(
                        "role", "?")
            except (OSError, ConnectionError, ValueError):
                roles[rid] = "unreachable"
        return roles

    def active_router(self, timeout_s: float = 30.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for rid, role in self.router_roles().items():
                if role == "active":
                    return rid
            time.sleep(0.2)
        raise RuntimeError(f"no active router within {timeout_s}s "
                           f"(roles: {self.router_roles()})")

    def stop(self) -> None:
        for proc, _ in list(self.routers.values()):
            _kill(proc, signal.SIGTERM)
        for proc, _ in list(self.nodes.values()):
            _kill(proc, signal.SIGTERM)


def _retry(fn, *args, what: str = "", ok=lambda d: d.get("ok"),
           tries: int = _RPC_TRIES, **kwargs) -> dict:
    """One session verb, ridden through the fault window: sheds,
    takeover refusals and connection loss all retry (the verbs are
    seq-idempotent by contract); only a whole exhausted window is a
    rig failure."""
    doc: dict = {}
    for i in range(tries):
        try:
            doc = fn(*args, **kwargs)
        except (OSError, ConnectionError, ValueError) as e:
            doc = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if ok(doc):
            return doc
        time.sleep(_RPC_SLEEP_S)
    raise RuntimeError(f"{what or getattr(fn, '__name__', 'rpc')} "
                       f"exhausted {tries} tries: "
                       f"{json.dumps(doc)[:300]}")


def soak_sessions(*, sessions: int = 1000, ops_per_session: int = 12,
                  model: str = "register", seed: int = 0,
                  workers: int = 8, max_sessions: int = 256,
                  lease_ttl_s: float = 1.0, fuzz_rounds: int = 2,
                  fuzz_batch: int = 8, run_dir: Optional[str] = None,
                  faults: Optional[str] = None, log=None) -> dict:
    """Run the whole schedule; returns the gate report (module
    docstring).  ``sessions`` histories are generated up front and
    their ground truth fixed by a fresh memo oracle BEFORE any fleet
    process exists — the fleet can only agree or be caught."""
    import tempfile

    from ..models.registry import MODELS
    from .core import generate_batch
    from .fleet import fuzz_fleet

    def say(msg: str) -> None:
        if log:
            log(msg)

    t_start = time.monotonic()
    spec = MODELS[model].make_spec()
    profile = GenProfile(n_ops=ops_per_session, n_pids=3,
                         p_adverse=0.05)
    histories = generate_batch(spec, profile, seed, sessions,
                               path="py")
    say(f"soak: {sessions} generated histories, fixing ground truth")
    truth = [int(v) for v in
             WingGongCPU(memo=True).check_histories(spec, histories)]
    rows_of = [history_to_rows(h) for h in histories]
    # three append chunks per session, one per fault phase
    chunks = [(0, 1), (1, 2), (2, 3)]

    owns_dir = run_dir is None
    run_dir = run_dir or tempfile.mkdtemp(prefix="qsm_soak_")
    fleet = _Fleet(run_dir, lease_ttl_s=lease_ttl_s,
                   max_sessions=max_sessions, faults=faults)
    report: Dict = {
        "rig": "soak_sessions", "model": model, "sessions": sessions,
        "ops_per_session": ops_per_session, "seed": seed,
        "max_sessions_per_node": max_sessions, "faults": faults,
        "truth_violations": sum(1 for v in truth
                                if v == int(Verdict.VIOLATION)),
    }
    flipped = [False] * sessions       # any flip the fleet pushed
    closes: List[dict] = [{}] * sessions
    local = threading.local()

    def client() -> CheckClient:
        if getattr(local, "c", None) is None:
            local.c = CheckClient(router_addrs, timeout_s=15.0)
        return local.c

    def sid(i: int) -> str:
        return f"soak-{i:05d}"

    def open_one(i: int) -> None:
        _retry(client().session_open, model, session=sid(i),
               what=f"open {sid(i)}")

    def append_chunk(i: int, lo_hi: Tuple[int, int]) -> None:
        rows = rows_of[i]
        per = max(1, len(rows) // len(chunks))
        lo, hi = lo_hi[0] * per, (lo_hi[1] * per if lo_hi[1]
                                  < len(chunks) else len(rows))
        if lo >= hi:
            return
        doc = _retry(client().session_append, sid(i), rows[lo:hi],
                     seq=lo, what=f"append {sid(i)}@{lo}")
        if doc.get("flip"):
            flipped[i] = True

    def close_one(i: int) -> None:
        doc = _retry(client().session_close, sid(i),
                     what=f"close {sid(i)}")
        if doc.get("flipped"):
            flipped[i] = True
        closes[i] = doc

    def sweep(fn, phase: str, chunk=None) -> None:
        t0 = time.monotonic()
        with ThreadPoolExecutor(max_workers=workers) as pool:
            futs = [pool.submit(fn, i) if chunk is None
                    else pool.submit(fn, i, chunk)
                    for i in range(sessions)]
            for f in futs:
                f.result()
        say(f"soak: {phase} done in "
            f"{time.monotonic() - t0:.1f}s")

    try:
        for nid in ("n0", "n1", "n2"):
            fleet.spawn_node(nid)
        lease = os.path.join(run_dir, "lease.json")
        r0 = fleet.spawn_router("r0", lease)
        r1 = fleet.spawn_router("r1", lease)
        router_addrs = f"{r0},{r1}"
        active = fleet.active_router()
        say(f"soak: fleet up, active router {active}")

        sweep(open_one, f"open x{sessions}")
        sweep(append_chunk, "chunk 0", chunks[0])

        # -- (a) rolling restart of all three nodes ---------------------
        t0 = time.monotonic()
        for nid in ("n0", "n1", "n2"):
            fleet.restart_node(nid)
            say(f"soak: node {nid} SIGKILLed + respawned")
        report["rolling_restart_s"] = round(time.monotonic() - t0, 2)
        sweep(append_chunk, "chunk 1 (post-restart)", chunks[1])

        # -- (b) SIGKILL the active router; standby takes the lease -----
        proc, _ = fleet.routers[active]
        t0 = time.monotonic()
        _kill(proc)                       # SIGKILL, no goodbye
        say(f"soak: active router {active} SIGKILLed")
        survivor = [rid for rid in fleet.routers if rid != active][0]
        new_active = fleet.active_router(
            timeout_s=max(30.0, lease_ttl_s * 20))
        report["router_takeover_s"] = round(time.monotonic() - t0, 2)
        report["router_takeover"] = new_active == survivor
        survivor_addr = fleet.routers[survivor][1]
        say(f"soak: standby {new_active} active after "
            f"{report['router_takeover_s']}s; running closed loop")
        fuzz = fuzz_fleet(survivor_addr, [model], rounds=fuzz_rounds,
                          batch=fuzz_batch, seed=seed + 1,
                          session_every=2, deadline_s=30.0,
                          timeout_s=30.0, log=log)
        report["fuzz"] = {
            "wrong_verdicts_total": fuzz["wrong_verdicts_total"],
            "flips_total": fuzz["flips_total"],
            "seqs_total": fuzz["seqs_total"],
            "health_status": fuzz["health_status"]}

        # -- (c) one node leave + one node join -------------------------
        with CheckClient(survivor_addr, timeout_s=15.0) as admin:
            left = _retry(admin.node_leave, "n0", what="node.leave n0")
            report["node_leave"] = {
                "sessions_migrated": left.get("sessions_migrated", 0),
                "nodes": left.get("nodes")}
            n3 = fleet.spawn_node("n3")
            joined = _retry(admin.node_join, "n3", n3,
                            what="node.join n3")
            report["node_join"] = {
                "handoff": joined.get("handoff"),
                "nodes": joined.get("nodes")}
        _kill(fleet.nodes.pop("n0")[0])
        say(f"soak: n0 left ({report['node_leave']}), n3 joined "
            f"({report['node_join']})")
        sweep(append_chunk, "chunk 2 (post-churn)", chunks[2])
        sweep(close_one, f"close x{sessions}")

        # -- audit: the fleet's word against a fresh oracle -------------
        undecided = int(Verdict.BUDGET_EXCEEDED)
        wrong: List[dict] = []
        lost_flips: List[int] = []
        unproved_flips: List[int] = []
        prefix_hits = advances = 0
        reprove = WingGongCPU(memo=True)   # fresh — no shared state
        for i, doc in enumerate(closes):
            got = doc.get("verdict")
            want = ("LINEARIZABLE" if truth[i]
                    == int(Verdict.LINEARIZABLE) else
                    "VIOLATION" if truth[i] == int(Verdict.VIOLATION)
                    else None)
            if want is not None and got != want:
                wrong.append({"session": sid(i), "fleet": got,
                              "oracle": want,
                              "seed": histories[i].seed})
            if flipped[i]:
                if int(reprove.check_histories(
                        spec, [histories[i]])[0]) not in (
                            int(Verdict.VIOLATION), undecided):
                    unproved_flips.append(i)
            elif truth[i] == int(Verdict.VIOLATION) \
                    and got != "VIOLATION":
                lost_flips.append(i)
            prefix_hits += int(doc.get("prefix_hits", 0))
            advances += int(doc.get("advances", 0))
        report["wrong_verdicts"] = len(wrong)
        report["wrong"] = wrong[:32]
        report["flips_total"] = sum(flipped)
        report["lost_flips"] = len(lost_flips)
        report["unproved_flips"] = len(unproved_flips)
        report["prefix_hits_total"] = prefix_hits
        report["frontier_advances_total"] = advances

        # durable-resume evidence from the nodes themselves
        restored = 0
        node_stats = {}
        for nid, (proc_n, addr) in fleet.nodes.items():
            try:
                with CheckClient(addr, timeout_s=5.0) as c:
                    s = (c.stats().get("stats") or {}).get(
                        "session") or {}
                node_stats[nid] = {
                    "restored": s.get("restored", 0),
                    "evicted": s.get("evicted", 0),
                    "prefix_hits": s.get("prefix_hits", 0)}
                restored += int(s.get("restored", 0))
            except (OSError, ConnectionError, ValueError):
                node_stats[nid] = {"unreachable": True}
        report["node_sessions"] = node_stats
        report["resume_restored_total"] = restored

        # the judge: the surviving fleet's own SLO health answer
        try:
            with CheckClient(survivor_addr, timeout_s=15.0) as c:
                health = c.health()
        except (OSError, ConnectionError, ValueError) as e:
            health = {"ok": False, "status": "unreachable",
                      "error": f"{type(e).__name__}: {e}"}
        report["health_status"] = str(health.get("status",
                                                 "unreachable"))
        report["exit_code"] = (
            HEALTH_EXIT_CODES.get(report["health_status"],
                                  HEALTH_EXIT_UNREACHABLE)
            if health.get("ok") else HEALTH_EXIT_UNREACHABLE)
        report["elapsed_s"] = round(time.monotonic() - t_start, 1)
        report["gate_ok"] = bool(
            report["wrong_verdicts"] == 0
            and report["lost_flips"] == 0
            and report["unproved_flips"] == 0
            and report["router_takeover"]
            and report["node_leave"]["nodes"] is not None
            and report["node_join"]["nodes"] is not None
            and report["resume_restored_total"] > 0
            and report["prefix_hits_total"] > 0
            and report["fuzz"]["wrong_verdicts_total"] == 0
            and report["exit_code"] == 0)
        return report
    finally:
        fleet.stop()
        if owns_dir:
            import shutil

            shutil.rmtree(run_dir, ignore_errors=True)
