"""Batched command-sequence generation (the generation plane's core).

Two-stage by design: a **raw draw table** — ``uint32[lanes, draws]`` of
seeded randomness — and a **host-side assembly** that spends those draws
building well-formed concurrent histories under a :class:`.GenProfile`.
The split is what makes the plane portable AND batchable:

* the pure-Python table (``random.Random`` per lane) works with
  ``JAX_PLATFORMS=cpu`` and no device, byte-identical everywhere;
* the JAX table is one ``jax.random`` key split per lane under ``vmap``
  — thousands of lanes of randomness in one device call, the same
  batch-amortization move the checker kernel makes (a lane's draws are
  a pure function of (seed, lane), so corpora are reproducible per
  path);
* the assembly is identical for both, and consumes a FIXED number of
  draws per simulated-clock tick — so a lane's history is a pure
  function of its draw row, never of Python iteration order.

Assembly follows the simulated clock of utils/fuzz.py::random_history
(each tick either invokes on an idle pid or completes an outstanding
op) with the profile's knobs applied: ``overlap`` biases the
invoke-vs-complete coin, ``op_mix``/``key_skew`` shape the command and
argument draws, ``p_pending`` crashes completions.  Completions track a
model state in completion order and respond model-consistently — the
corpus is linearizable BY CONSTRUCTION (its own completion order is the
witness) — except with probability ``p_adverse``, where the response is
drawn uniformly from the command's domain.  That makes the interesting
verdict the RARE one, so a steering loop chasing flips is chasing real
near-miss structure.  The checker still decides which corpora violate;
generation never does (package docstring soundness note).
"""

from __future__ import annotations

import random
from typing import List, Optional

import numpy as np

from ..core.history import History, Op, bucket_for
from ..sched.runner import PENDING_T
from .profile import GenProfile

# fixed draw budget per simulated-clock tick (invoke-or-complete coin,
# pid choice, cmd-or-pending, arg, adverse coin, resp) — alignment
# never depends on which branch a tick took
_DRAWS_PER_TICK = 6
# a history of n ops takes at most 2n+1 ticks (each op is one invoke
# tick + at most one complete tick); headroom doubles it
_U32 = float(1 << 32)


def _n_draws(n_ops: int) -> int:
    return _DRAWS_PER_TICK * (4 * n_ops + 8)


class DrawStream:
    """A cursor over one lane's raw draws.  Exhaustion raises — the
    table is sized by construction (``_n_draws``), so hitting the end
    means the assembly's draw discipline broke, not bad luck."""

    def __init__(self, row: np.ndarray):
        self._row = row
        self._i = 0

    def unit(self) -> float:
        """Uniform in [0, 1)."""
        if self._i >= len(self._row):
            raise RuntimeError("draw stream exhausted (sizing bug)")
        v = float(self._row[self._i]) / _U32
        self._i += 1
        return v

    def randrange(self, n: int) -> int:
        return min(n - 1, int(self.unit() * n))


def _raw_draws_py(seed: int, n_lanes: int, n_draws: int) -> np.ndarray:
    """The canonical table: one ``random.Random`` per lane, seeded by
    (seed, lane) — byte-identical on every platform, no jax import."""
    out = np.empty((n_lanes, n_draws), np.uint32)
    for lane in range(n_lanes):
        rng = random.Random(f"gen:{seed}:{lane}")
        out[lane] = [rng.getrandbits(32) for _ in range(n_draws)]
    return out


def _raw_draws_jax(seed: int, n_lanes: int, n_draws: int) -> np.ndarray:
    """The batched table: per-lane key splits under ``vmap``, one device
    call for the whole batch.  Deterministic per (seed, lane) within a
    jax installation; NOT byte-identical to the Python table (different
    PRNG family) — callers pin determinism per path, never across."""
    import jax
    import jax.numpy as jnp

    keys = jax.random.split(jax.random.PRNGKey(seed), n_lanes)
    bits = jax.vmap(
        lambda k: jax.random.bits(k, (n_draws,), dtype=jnp.uint32))(keys)
    return np.asarray(bits)


def _pick_weighted(stream: DrawStream, weights) -> int:
    u = stream.unit()
    acc = 0.0
    for i, w in enumerate(weights):
        acc += w
        if u < acc:
            return i
    return len(weights) - 1


def _skewed_arg(stream: DrawStream, n_args: int, skew: float) -> int:
    # u ** (1 + skew) piles mass toward 0 as skew grows; skew 0 is
    # exactly uniform
    u = stream.unit()
    return min(n_args - 1, int(n_args * (u ** (1.0 + skew))))


def _complete(spec, profile: GenProfile, stream: DrawStream, state,
              cmd: int, arg: int):
    """One completion's (resp, next_state): model-consistent along the
    completion-order walk, or (with ``p_adverse``) an off-model draw —
    the state walk then advances anyway (first valid resp) so ONE
    adversarial completion perturbs one op, not every op after it."""
    adverse = stream.unit() < profile.p_adverse
    drawn = stream.randrange(spec.CMDS[cmd].n_resps)
    consistent, nxt = None, state
    for resp in spec.resp_domain(cmd):
        new_state, ok = spec.step_py(list(state), cmd, arg, resp)
        if ok:
            consistent = resp
            nxt = [int(v) for v in new_state]
            break
    if consistent is None or adverse:
        return drawn, nxt
    return consistent, nxt


def generate_history(spec, profile: GenProfile, stream: DrawStream,
                     *, seed: Optional[int] = None,
                     program_id: Optional[int] = None) -> History:
    """Assemble one history from a lane's draws (module docstring)."""
    weights = profile.weights(spec.n_cmds)
    remaining = profile.n_ops
    outstanding = {}
    dead = set()
    done: List[Op] = []
    # the completion-order model walk the consistent responses ride
    state = [int(v) for v in spec.initial_state()]
    t = 0
    while remaining > 0 or outstanding:
        mark = stream._i
        idle = [p for p in range(profile.n_pids)
                if p not in outstanding and p not in dead]
        can_invoke = remaining > 0 and idle
        if not can_invoke and not outstanding:
            break  # every pid is dead; undone ops are simply not issued
        if can_invoke and (not outstanding
                           or stream.unit() < profile.overlap):
            pid = idle[stream.randrange(len(idle))]
            cmd = _pick_weighted(stream, weights)
            arg = _skewed_arg(stream, spec.CMDS[cmd].n_args,
                              profile.key_skew)
            outstanding[pid] = Op(pid=pid, cmd=cmd, arg=arg, resp=-1,
                                  invoke_time=t, response_time=PENDING_T)
            remaining -= 1
        else:
            pids = sorted(outstanding)
            pid = pids[stream.randrange(len(pids))]
            op = outstanding.pop(pid)
            if stream.unit() < profile.p_pending:
                done.append(op)  # never responds (crash/drop shape)
                dead.add(pid)    # a blocked pid can't invoke again
            else:
                resp, state = _complete(spec, profile, stream, state,
                                        op.cmd, op.arg)
                done.append(Op(pid=op.pid, cmd=op.cmd, arg=op.arg,
                               resp=resp, invoke_time=op.invoke_time,
                               response_time=t))
        # fixed spend: burn whatever this tick's branch left over
        while stream._i - mark < _DRAWS_PER_TICK:
            stream.unit()
        t += 1
    done.sort(key=lambda o: o.invoke_time)
    return History(done, seed=seed, program_id=program_id)


def generate_batch(spec, profile: GenProfile, seed: int, n: int,
                   path: str = "auto") -> List[History]:
    """``n`` histories from one seeded draw table.

    ``path`` picks the table source: ``"py"`` (canonical, no jax),
    ``"jax"`` (vmap'd key splits), or ``"auto"`` (jax when importable,
    else py).  Provenance rides each history (``seed``/``program_id``)
    so any lane is replayable alone."""
    if path == "auto":
        try:
            import jax  # noqa: F401 — probe only
            path = "jax"
        except Exception:  # pragma: no cover — jax is baked in here
            path = "py"
    draws = (_raw_draws_jax if path == "jax" else _raw_draws_py)(
        seed, n, _n_draws(profile.n_ops))
    return [generate_history(spec, profile, DrawStream(draws[lane]),
                             seed=seed, program_id=lane)
            for lane in range(n)]


def profile_bucket(profile: GenProfile) -> int:
    """The planner compile bucket this profile's histories land in —
    batches are sized so the device kernel compiles ONCE per profile
    geometry (core/history.py OP_BUCKETS)."""
    return bucket_for(profile.n_ops)
