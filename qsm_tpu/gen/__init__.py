"""The coverage-guided workload generation plane (docs/GENERATION.md).

Everything upstream of this package answers "is this history
linearizable?"; this package manufactures the histories worth asking
about.  Three layers, innermost first:

* :mod:`.core` — batched command-sequence generation: a seeded raw-draw
  table (pure-Python stream, or per-lane ``jax.random`` key splits under
  ``vmap``) assembled host-side into well-formed concurrent
  :class:`~qsm_tpu.core.history.History` batches, parameterized by a
  :class:`.GenProfile` and sized to the planner's compile buckets.
* :mod:`.steer` — the feedback loop: a BOUNDED seed pool of profiles
  mutated and scored by what the check plane already measures — search
  nodes per history (``SearchStats``), verdict flips, corpus shape
  (``profile_corpus``) — with ``atomic_write_json`` checkpoints.
* :mod:`.fleet` — the closed loop: ``qsm-tpu fuzz --addr`` soaks a live
  fleet with generated check requests and monitor sessions, every
  returned verdict re-proved against a fresh memo oracle.

Soundness note: generation STEERS, it never judges.  A generated corpus
feeds the same check plane as any other workload; no counter or score in
this package contributes to a verdict (the ``gen_*`` counters in
search/stats.py are additive bookkeeping only).
"""

from .core import generate_batch, generate_history
from .profile import GenProfile
from .steer import SeedPool, SteeringLoop

__all__ = ["GenProfile", "SeedPool", "SteeringLoop", "generate_batch",
           "generate_history"]
