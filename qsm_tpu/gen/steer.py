"""The steering loop — mutation-driven profile search, scored by the
check plane's own feedback.

Nothing here invents a fitness function: the signals are counters the
framework already maintains for other reasons, which keeps the fuzzer's
notion of "interesting" anchored to what actually costs the checker
work or changes its answers:

* **search nodes per history** (``SearchStats`` deltas around the check
  call, search/stats.py) — corpora that make the lineariser explore are
  corpora near the boundary of the real-time order's freedom;
* **verdict flips** (VIOLATION verdicts) — with model-consistent
  generation (gen/core.py ``p_adverse``) a violation is the rare,
  interesting event, not the ambient one;
* **corpus shape** (``profile_corpus``, search/planner.py) — histories
  that refuse to cut (low segment density) deny the checker its
  decomposition fast paths, and the per-spec selectivity table
  (``compile_selectivity_table``) seeds the initial op mix toward
  commands whose postconditions prune hardest.

The pool is BOUNDED (``SeedPool``, capacity-disciplined the way every
retained structure in this codebase must be — the QSM-GEN-UNBOUNDED
lint pass gates exactly this class's discipline), and the whole loop
state checkpoints via ``atomic_write_json`` so ``--resume`` rails
(tools/bench_gen.py, resilience/checkpoint.py) restart mid-campaign
without replaying rounds.

Soundness: the loop SCORES verdicts, it never issues them.  Every
verdict used here came from a real backend, and the ``gen_*`` counters
it accumulates are additive bookkeeping (tests/test_stats_merge.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
from typing import Callable, List, Optional

from ..ops.backend import Verdict
from ..resilience.checkpoint import atomic_write_json
from ..search.planner import profile_corpus
from ..search.stats import SearchStats, collect_search_stats, stats_delta
from .core import generate_batch
from .profile import GenProfile

# a flip is worth this many search-nodes-per-history in the score: a
# violation is the event the whole plane exists to find, so one flip
# outranks any plausible nodes/history delta on these corpus sizes
_FLIP_WEIGHT = 10_000.0
# refusing-to-cut bonus: (2 - mean_segments) scaled — corpora the
# decomposition gates cannot split keep the search honest
_SHAPE_WEIGHT = 50.0
# kept violating histories: a tail window, not a campaign-length log
# (QSM-GEN-UNBOUNDED discipline — consumers want the RECENT flips to
# replay/stream; an unbounded keep is a slow OOM on long soaks)
_FLIP_KEEP = 64


@dataclasses.dataclass
class PoolSeed:
    """One scored profile.  ``seed`` is the draw-table seed the score
    was earned with — keeping it makes every pool entry replayable."""

    profile: GenProfile
    seed: int
    score: float = 0.0
    flips: int = 0
    nodes_per_hist: float = 0.0
    rounds: int = 0  # times this entry was selected as a parent

    def to_dict(self) -> dict:
        return {"profile": self.profile.to_dict(), "seed": self.seed,
                "score": self.score, "flips": self.flips,
                "nodes_per_hist": self.nodes_per_hist,
                "rounds": self.rounds}

    @classmethod
    def from_dict(cls, d: dict) -> "PoolSeed":
        return cls(profile=GenProfile.from_dict(d["profile"]),
                   seed=int(d["seed"]), score=float(d["score"]),
                   flips=int(d["flips"]),
                   nodes_per_hist=float(d["nodes_per_hist"]),
                   rounds=int(d["rounds"]))


class SeedPool:
    """Bounded, score-ordered corpus of profiles.

    Capacity discipline: every ``add`` compares against ``cap`` and
    evicts the worst entry — the pool can never grow past its bound no
    matter how long a campaign runs (the unbounded twin of this class
    is the QSM-GEN-UNBOUNDED fixture, analysis/fixtures.py)."""

    def __init__(self, cap: int = 16):
        if cap < 1:
            raise ValueError(f"pool cap must be >= 1, got {cap}")
        self.cap = cap
        self._seeds: List[PoolSeed] = []

    def __len__(self) -> int:
        return len(self._seeds)

    def add(self, entry: PoolSeed) -> None:
        self._seeds.append(entry)
        self._seeds.sort(key=lambda s: -s.score)
        while len(self._seeds) > self.cap:
            self._seeds.pop()  # worst-scored out; the bound holds

    def pick(self, rng: random.Random) -> Optional[PoolSeed]:
        """Rank-weighted parent selection: the best entry is the likely
        parent but the tail keeps breathing (pure greed converges on
        one local shape and stops covering)."""
        if not self._seeds:
            return None
        n = len(self._seeds)
        weights = [n - i for i in range(n)]  # rank-linear
        total = sum(weights)
        u = rng.random() * total
        acc = 0
        for s, w in zip(self._seeds, weights):
            acc += w
            if u < acc:
                return s
        return self._seeds[-1]

    def best(self) -> Optional[PoolSeed]:
        return self._seeds[0] if self._seeds else None

    def to_dict(self) -> dict:
        return {"cap": self.cap,
                "seeds": [s.to_dict() for s in self._seeds]}

    @classmethod
    def from_dict(cls, d: dict) -> "SeedPool":
        pool = cls(cap=int(d.get("cap", 16)))
        for row in d.get("seeds", ()):
            pool.add(PoolSeed.from_dict(row))
        return pool


class SteeringLoop:
    """Mutate → generate → check → score → keep (module docstring).

    ``backend`` is anything with ``check_histories(spec, histories)``
    returning verdict ints — the property plane's oracles, a planned
    device ladder, or a serve-plane shim (gen/fleet.py).  ``rounds()``
    of work happen one :meth:`round` at a time so callers own pacing,
    checkpoint cadence and budget accounting."""

    def __init__(self, spec, backend, *, profile: Optional[GenProfile]
                 = None, pool_cap: int = 16, batch: int = 32,
                 seed: int = 0, path: str = "auto",
                 on_flip: Optional[Callable] = None):
        self.spec = spec
        self.backend = backend
        self.batch = batch
        self.path = path
        self.on_flip = on_flip
        self.rng = random.Random(f"steer:{spec.name}:{seed}")
        self._next_seed = seed * 1_000_003 + 1
        self.pool = SeedPool(cap=pool_cap)
        self.stats = SearchStats(engine="gen")
        self.flip_histories: List = []  # (history, verdict) of violations
        base = profile if profile is not None else self.seed_profile()
        self.pool.add(PoolSeed(profile=base, seed=seed))

    # -- initial profile ----------------------------------------------
    def seed_profile(self) -> GenProfile:
        """The selectivity-informed starting point: commands whose
        postconditions accept in FEWER states get more weight — they
        are the mutators/guards whose interleavings carry the near-miss
        structure (a command accepted everywhere constrains nothing).
        Specs without a scalar domain start uniform."""
        mix = ()
        bound = self.spec.scalar_state_bound(32)  # nominal op count
        if self.spec.STATE_DIM == 1 and bound:
            from ..core.spec import compile_selectivity_table

            sel = compile_selectivity_table(self.spec, bound)
            # per-cmd mean acceptance fraction -> weight 1.5 - fraction
            per_cmd = sel.reshape(self.spec.n_cmds, -1).mean(axis=1)
            mix = tuple(float(max(0.1, 1.5 - f)) for f in per_cmd)
        return GenProfile(op_mix=mix)

    # -- one feedback round -------------------------------------------
    def round(self) -> dict:
        """Mutate a parent, generate a batch, check it, score it, and
        keep it iff it earns a pool slot.  Returns the round report."""
        parent = self.pool.pick(self.rng)
        parent.rounds += 1
        profile = parent.profile.mutate(self.rng)
        seed = self._next_seed
        self._next_seed += 1
        hists = generate_batch(self.spec, profile, seed, self.batch,
                               path=self.path)
        before = collect_search_stats(self.backend)
        verdicts = self.backend.check_histories(self.spec, hists)
        delta = stats_delta(collect_search_stats(self.backend), before)
        nodes = float(getattr(delta, "nodes_explored", 0) or 0)
        nodes_per_hist = nodes / max(1, len(hists))
        flips = 0
        for h, v in zip(hists, verdicts):
            if int(v) == int(Verdict.VIOLATION):
                flips += 1
                self.flip_histories.append((h, int(v)))
                if self.on_flip is not None:
                    self.on_flip(self.spec, profile, h)
        if len(self.flip_histories) > _FLIP_KEEP:
            self.flip_histories = self.flip_histories[-_FLIP_KEEP:]
        shape = profile_corpus(hists)
        score = (nodes_per_hist + _FLIP_WEIGHT * flips
                 + _SHAPE_WEIGHT * max(0.0, 2.0 - shape.mean_segments))
        self.pool.add(PoolSeed(profile=profile, seed=seed, score=score,
                               flips=flips,
                               nodes_per_hist=nodes_per_hist))
        self.stats.gen_seqs += len(hists)
        self.stats.gen_mutations += 1
        self.stats.gen_flips += flips
        self.stats.gen_feedback_rounds += 1
        return {"profile": profile.to_dict(), "seed": seed,
                "score": round(score, 2), "flips": flips,
                "nodes_per_hist": round(nodes_per_hist, 2),
                "mean_segments": round(shape.mean_segments, 3),
                "pool": len(self.pool)}

    def run(self, rounds: int) -> List[dict]:
        return [self.round() for _ in range(rounds)]

    # -- stats plumbing (collect_search_stats walks this) -------------
    def search_stats(self) -> SearchStats:
        st = dataclasses.replace(self.stats)
        st.absorb(collect_search_stats(self.backend))
        return st

    # -- checkpointing (resilience/checkpoint.py rails) ---------------
    def save(self, path: str) -> None:
        atomic_write_json(path, {
            "spec": self.spec.name,
            "next_seed": self._next_seed,
            "pool": self.pool.to_dict(),
            "stats": self.stats.to_compact(),
            "gen": {"seqs": self.stats.gen_seqs,
                    "mutations": self.stats.gen_mutations,
                    "flips": self.stats.gen_flips,
                    "rounds": self.stats.gen_feedback_rounds},
        })

    def load(self, path: str) -> bool:
        """Adopt a checkpoint's pool and counters; False if absent.
        The rng re-seeds from the restored round count so a resumed
        campaign diverges from a fresh one only by the banked work."""
        if not os.path.exists(path):
            return False
        with open(path) as f:
            doc = json.load(f)
        if doc.get("spec") != self.spec.name:
            raise ValueError(
                f"checkpoint is for spec {doc.get('spec')!r}, "
                f"not {self.spec.name!r}")
        self.pool = SeedPool.from_dict(doc["pool"])
        self._next_seed = int(doc["next_seed"])
        g = doc.get("gen", {})
        self.stats.gen_seqs = int(g.get("seqs", 0))
        self.stats.gen_mutations = int(g.get("mutations", 0))
        self.stats.gen_flips = int(g.get("flips", 0))
        self.stats.gen_feedback_rounds = int(g.get("rounds", 0))
        self.rng = random.Random(
            f"steer:{self.spec.name}:resume:{self.stats.gen_feedback_rounds}")
        return True
