"""Router lease — the fleet's one-active-brain contract.

PR 12 left the fleet with exactly one router: kill it and the tier is
gone.  Running N routers against the same fleet config fixes
availability but creates the split-brain hazard — two routers both
believing they are active could answer the same traffic from diverging
health views.  This module is the arbitration: a filesystem-backed
*lease* (one JSON record beside the replog dirs) holding

* a **term** — a monotonically increasing integer, bumped by every
  takeover; an active router stamps its term on every response, and a
  router holding a stale term answers ``SHED`` with a
  ``router_superseded`` block, never a verdict;
* a **holder** — the router id that owns the current term;
* an **expiry** — wall-clock ``expires_at`` a bounded TTL ahead,
  refreshed by :meth:`renew` on the active router's sweep beat.

Safety argument (one-way per term): the active serves only while
``now < expires_at`` of its OWN last successful renew; a standby
:meth:`acquire`\\ s only after observing ``now >= expires_at`` (plus a
grace) of the SAME record and bumping the term.  Both read the same
file and the same host clock, so at most one router can believe its
term is live at any instant, and a router that lost term T can never
serve under T again — it re-enters only by winning a LATER term
through the same gated path.  Read-modify-write races between two
candidates are excluded by an ``flock``-held lock file: the kernel
owns the exclusion, so a candidate SIGKILLed mid-acquire releases it
with its process — no stale-lock state exists to break (and no
break-the-stale-lock race, where two breakers could each unlink the
other's fresh lock and both proceed, can arise).

The scope is deliberately single-host-filesystem (the deployment shape
of the local fleet: N node processes + routers sharing a disk and a
clock); a multi-host fleet would back the same record with its shared
store.  Consumed by :class:`~qsm_tpu.fleet.router.FleetRouter`
(``lease_path=``); lint family (j) gates the promotion discipline
(QSM-FLEET-LEASE: every promote path must consult term/expiry and
stay bounded)."""

from __future__ import annotations

import json
import os
import time
from typing import Optional

_ARTIFACT = "qsm_tpu_router_lease"
_VERSION = 1


class Lease:
    """One router's handle on the shared lease record (see module
    docstring).  All methods are one bounded filesystem transaction;
    ``None`` returns mean "you do not hold it" — callers re-consult on
    their next beat, never spin."""

    def __init__(self, path: str, holder: str, ttl_s: float = 3.0):
        self.path = path
        self.holder = str(holder)
        self.ttl_s = max(0.2, float(ttl_s))
        self._lock_fd = None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    # -- reading -------------------------------------------------------
    def read(self) -> Optional[dict]:
        """The current record, or None (missing/garbled — a garbled
        lease is treated as expired: the next acquire rewrites it)."""
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if doc.get("artifact") != _ARTIFACT:
            return None
        if not isinstance(doc.get("term"), int) \
                or not isinstance(doc.get("expires_at"), (int, float)):
            return None
        return doc

    @staticmethod
    def expired(rec: Optional[dict], grace_s: float = 0.0) -> bool:
        """True when the record's term is no longer live (plus the
        caller's grace — standbys wait it out so clock skew inside one
        host's filesystem timestamps can never overlap two actives)."""
        if rec is None:
            return True
        return time.time() >= float(rec["expires_at"]) + max(
            0.0, grace_s)

    # -- the write transactions ----------------------------------------
    def acquire(self, grace_s: float = 0.0) -> Optional[dict]:
        """Take the lease iff nobody holds a live term: no record, an
        expired record (past ``grace_s``), or our own record.  The new
        term is ``old term + 1`` (a re-acquire of our own live record
        keeps the term — that is a renew).  Returns the record now in
        force when WE hold it, else None."""
        if not self._lock():
            return None
        try:
            rec = self.read()
            if rec is not None and rec.get("holder") != self.holder \
                    and not self.expired(rec, grace_s):
                return None  # a live foreign term: never contested
            old_term = int(rec["term"]) if rec is not None else 0
            if rec is not None and rec.get("holder") == self.holder \
                    and not self.expired(rec):
                term = old_term        # still ours: refresh, not bump
            else:
                term = old_term + 1    # a takeover mints a NEW term
            return self._write(term)
        finally:
            self._unlock()

    def renew(self, term: int) -> Optional[dict]:
        """Refresh ``expires_at`` iff we still hold exactly ``term``.
        None = lost (superseded, expired-and-taken, or the record is
        gone) — the caller must stop serving under ``term``."""
        if not self._lock():
            return None
        try:
            rec = self.read()
            if rec is None or rec.get("holder") != self.holder \
                    or int(rec["term"]) != int(term):
                return None
            if self.expired(rec):
                # our own record expired before this renew landed: the
                # term MAY already be contested — refreshing it could
                # resurrect a stale active after a standby's expiry
                # read.  One-way: give it up; re-entry is a new term.
                return None
            return self._write(int(term))
        finally:
            self._unlock()

    def release(self) -> None:
        """Expire our own record in place (clean shutdown: the standby
        need not wait out the TTL).  A TOMBSTONE, not an unlink — the
        term survives, so the successor still mints term+1 and the
        monotonic-term contract holds across clean handovers (merged
        logs must never see the same term from two brains).  A foreign
        record is left alone."""
        if not self._lock():
            return
        try:
            rec = self.read()
            if rec is not None and rec.get("holder") == self.holder:
                from ..resilience.checkpoint import atomic_write_json

                # backdated past any sane grace (grace <= 2*ttl) so
                # the successor's very next beat sees it expired
                rec = {**rec, "released": True,
                       "expires_at": round(
                           time.time() - 2 * self.ttl_s, 4)}
                atomic_write_json(self.path, rec)
        finally:
            self._unlock()

    # -- plumbing ------------------------------------------------------
    def _write(self, term: int) -> dict:
        from ..resilience.checkpoint import atomic_write_json

        rec = {"artifact": _ARTIFACT, "version": _VERSION,
               "term": int(term), "holder": self.holder,
               "ttl_s": self.ttl_s,
               "expires_at": round(time.time() + self.ttl_s, 4)}
        atomic_write_json(self.path, rec)
        return rec

    @property
    def _lock_path(self) -> str:
        return self.path + ".lock"

    def _lock(self) -> bool:
        """``flock(LOCK_EX | LOCK_NB)`` mutual exclusion around
        read-modify-write.  Held for microseconds; contention loses
        THIS beat (never blocks).  Kernel-owned: a holder SIGKILLed
        mid-transaction releases with its process, so no stale-lock
        state exists and nothing ever needs breaking (an unlink-based
        break would race — two breakers could each remove the other's
        fresh lock and both enter the critical section: exactly the
        split-brain this lock exists to exclude).  The lock file
        itself is deliberately never unlinked."""
        import fcntl

        try:
            fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR)
        except OSError:
            return False
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False  # live contention: lose this beat
        self._lock_fd = fd
        return True

    def _unlock(self) -> None:
        fd = getattr(self, "_lock_fd", None)
        if fd is None:
            return
        self._lock_fd = None
        try:
            os.close(fd)  # closing the fd releases the flock
        except OSError:
            pass
