"""Router lease — the fleet's one-active-brain contract.

PR 12 left the fleet with exactly one router: kill it and the tier is
gone.  Running N routers against the same fleet config fixes
availability but creates the split-brain hazard — two routers both
believing they are active could answer the same traffic from diverging
health views.  This module is the arbitration: a shared *lease record*
(one JSON document) holding

* a **term** — a monotonically increasing integer, bumped by every
  takeover; an active router stamps its term on every response, and a
  router holding a stale term answers ``SHED`` with a
  ``router_superseded`` block, never a verdict;
* a **holder** — the router id that owns the current term;
* an **expiry** — wall-clock ``expires_at`` a bounded TTL ahead,
  refreshed by :meth:`Lease.renew` on the active router's sweep beat.

Safety argument (one-way per term): the active serves only while
``now < expires_at`` of its OWN last successful renew; a standby
:meth:`Lease.acquire`\\ s only after observing ``now >= expires_at``
(plus a grace) of the SAME record and bumping the term.  Both read the
same record and the same authority clock, so at most one router can
believe its term is live at any instant, and a router that lost term T
can never serve under T again — it re-enters only by winning a LATER
term through the same gated path.

The record lives behind a pluggable :class:`LeaseStore` (ISSUE 18):

* :class:`FileLeaseStore` — the single-host shape: one JSON file
  beside the replog dirs, read-modify-write races between candidates
  excluded by an ``flock``-held lock file.  The kernel owns the
  exclusion, so a candidate SIGKILLed mid-acquire releases it with
  its process — no stale-lock state exists to break (and no
  break-the-stale-lock race, where two breakers could each unlink the
  other's fresh lock and both proceed, can arise).
* :class:`TcpLeaseStore` — routers spanning hosts: every transaction
  is ONE bounded round trip of the serve protocol's ``lease.acquire``
  / ``lease.renew`` / ``lease.release`` / ``lease.read`` ops against
  a lease-hosting node (``CheckServer(lease_path=...)``), whose OWN
  FileLeaseStore runs the identical transaction under the identical
  flock — the safety argument is unchanged, the authority clock is
  the lease host's.  Any transport failure loses THIS beat (returns
  None), exactly like flock contention: callers re-consult on their
  next beat, never spin.

Fault plane (resilience/faults.py): :meth:`Lease.acquire` and
:meth:`Lease.renew` pass the ``lease`` fault site —
``QSM_TPU_FAULTS="partition:lease"`` makes the store unreachable for
the beat (a lost beat, not an error), ``raise:lease@2`` fails the
second transaction, with the full ``action:site[:p][@nth]`` grammar.

Consumed by :class:`~qsm_tpu.fleet.router.FleetRouter`
(``lease_path=`` — a filesystem path or ``tcp://host:port``); lint
family (j) gates the promotion discipline (QSM-FLEET-LEASE: every
promote path must consult term/expiry and stay bounded)."""

from __future__ import annotations

import json
import os
import time
from typing import Optional, Union

from ..resilience.faults import inject

_ARTIFACT = "qsm_tpu_router_lease"
_VERSION = 1
TCP_SCHEME = "tcp://"


def lease_expired(rec: Optional[dict], grace_s: float = 0.0) -> bool:
    """True when the record's term is no longer live (plus the
    caller's grace — standbys wait it out so clock skew inside one
    authority's timestamps can never overlap two actives)."""
    if rec is None:
        return True
    return time.time() >= float(rec["expires_at"]) + max(0.0, grace_s)


class LeaseStore:
    """The storage contract behind the lease record.  All methods are
    one bounded transaction; ``None`` returns mean "you do not hold
    it" — callers re-consult on their next beat, never spin."""

    def read(self) -> Optional[dict]:
        raise NotImplementedError

    def acquire(self, holder: str, ttl_s: float,
                grace_s: float = 0.0) -> Optional[dict]:
        raise NotImplementedError

    def renew(self, holder: str, term: int,
              ttl_s: float) -> Optional[dict]:
        raise NotImplementedError

    def release(self, holder: str) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        raise NotImplementedError


class FileLeaseStore(LeaseStore):
    """The filesystem store: one JSON record, flock-excluded
    transactions (see module docstring)."""

    def __init__(self, path: str):
        self.path = path
        self._lock_fd = None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    # -- reading -------------------------------------------------------
    def read(self) -> Optional[dict]:
        """The current record, or None (missing/garbled — a garbled
        lease is treated as expired: the next acquire rewrites it)."""
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if doc.get("artifact") != _ARTIFACT:
            return None
        if not isinstance(doc.get("term"), int) \
                or not isinstance(doc.get("expires_at"), (int, float)):
            return None
        return doc

    # -- the write transactions ----------------------------------------
    def acquire(self, holder: str, ttl_s: float,
                grace_s: float = 0.0) -> Optional[dict]:
        """Take the lease iff nobody holds a live term: no record, an
        expired record (past ``grace_s``), or our own record.  The new
        term is ``old term + 1`` (a re-acquire of our own live record
        keeps the term — that is a renew).  Returns the record now in
        force when WE hold it, else None."""
        if not self._lock():
            return None
        try:
            rec = self.read()
            if rec is not None and rec.get("holder") != holder \
                    and not lease_expired(rec, grace_s):
                return None  # a live foreign term: never contested
            old_term = int(rec["term"]) if rec is not None else 0
            if rec is not None and rec.get("holder") == holder \
                    and not lease_expired(rec):
                term = old_term        # still ours: refresh, not bump
            else:
                term = old_term + 1    # a takeover mints a NEW term
            return self._write(term, holder, ttl_s)
        finally:
            self._unlock()

    def renew(self, holder: str, term: int,
              ttl_s: float) -> Optional[dict]:
        """Refresh ``expires_at`` iff ``holder`` still holds exactly
        ``term``.  None = lost (superseded, expired-and-taken, or the
        record is gone) — the caller must stop serving under ``term``."""
        if not self._lock():
            return None
        try:
            rec = self.read()
            if rec is None or rec.get("holder") != holder \
                    or int(rec["term"]) != int(term):
                return None
            if lease_expired(rec):
                # the holder's own record expired before this renew
                # landed: the term MAY already be contested —
                # refreshing it could resurrect a stale active after a
                # standby's expiry read.  One-way: give it up;
                # re-entry is a new term.
                return None
            return self._write(int(term), holder, ttl_s)
        finally:
            self._unlock()

    def release(self, holder: str) -> None:
        """Expire ``holder``'s own record in place (clean shutdown:
        the standby need not wait out the TTL).  A TOMBSTONE, not an
        unlink — the term survives, so the successor still mints
        term+1 and the monotonic-term contract holds across clean
        handovers (merged logs must never see the same term from two
        brains).  A foreign record is left alone."""
        if not self._lock():
            return
        try:
            rec = self.read()
            if rec is not None and rec.get("holder") == holder:
                from ..resilience.checkpoint import atomic_write_json

                # backdated past any sane grace (grace <= 2*ttl, read
                # from the record itself) so the successor's very next
                # beat sees it expired
                ttl = float(rec.get("ttl_s", 1.0))
                rec = {**rec, "released": True,
                       "expires_at": round(time.time() - 2 * ttl, 4)}
                atomic_write_json(self.path, rec)
        finally:
            self._unlock()

    def describe(self) -> str:
        return self.path

    # -- plumbing ------------------------------------------------------
    def _write(self, term: int, holder: str, ttl_s: float) -> dict:
        from ..resilience.checkpoint import atomic_write_json

        ttl = max(0.2, float(ttl_s))
        rec = {"artifact": _ARTIFACT, "version": _VERSION,
               "term": int(term), "holder": str(holder),
               "ttl_s": ttl,
               "expires_at": round(time.time() + ttl, 4)}
        atomic_write_json(self.path, rec)
        return rec

    @property
    def _lock_path(self) -> str:
        return self.path + ".lock"

    def _lock(self) -> bool:
        """``flock(LOCK_EX | LOCK_NB)`` mutual exclusion around
        read-modify-write.  Held for microseconds; contention loses
        THIS beat (never blocks).  Kernel-owned: a holder SIGKILLed
        mid-transaction releases with its process, so no stale-lock
        state exists and nothing ever needs breaking (an unlink-based
        break would race — two breakers could each remove the other's
        fresh lock and both enter the critical section: exactly the
        split-brain this lock exists to exclude).  The lock file
        itself is deliberately never unlinked."""
        import fcntl

        try:
            fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR)
        except OSError:
            return False
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False  # live contention: lose this beat
        self._lock_fd = fd
        return True

    def _unlock(self) -> None:
        fd = getattr(self, "_lock_fd", None)
        if fd is None:
            return
        self._lock_fd = None
        try:
            os.close(fd)  # closing the fd releases the flock
        except OSError:
            pass


class TcpLeaseStore(LeaseStore):
    """The multi-host store: each transaction is one bounded serve-
    protocol round trip against a lease-hosting node
    (``CheckServer(lease_path=...)``), which runs the identical
    FileLeaseStore transaction under its own flock.  ANY transport
    failure — connect refused, timeout, torn response — loses this
    beat (None), the same contract a lost flock beat has; the caller's
    next beat re-consults.  No connection is pooled: a lease beat is
    rare (~TTL/3) and a fresh bounded socket per transaction means a
    half-dead pooled connection can never wedge the HA plane."""

    def __init__(self, address: str, timeout_s: float = 5.0):
        if address.startswith(TCP_SCHEME):
            address = address[len(TCP_SCHEME):]
        self.address = address
        self.timeout_s = max(0.2, float(timeout_s))

    def _ask(self, doc: dict) -> Optional[dict]:
        from ..serve.protocol import LineChannel, connect, send_doc

        try:
            sock = connect(self.address, timeout_s=self.timeout_s)
        except OSError:
            return None
        try:
            send_doc(sock, doc)
            line = LineChannel(sock).read_line(timeout_s=self.timeout_s)
        except (OSError, TimeoutError):
            return None
        finally:
            try:
                sock.close()
            except OSError:
                pass
        if line is None:
            return None
        try:
            resp = json.loads(line)
        except ValueError:
            return None
        if not isinstance(resp, dict) or not resp.get("ok"):
            return None
        return resp

    def read(self) -> Optional[dict]:
        resp = self._ask({"op": "lease.read"})
        if resp is None:
            return None
        rec = resp.get("record")
        return rec if isinstance(rec, dict) else None

    def acquire(self, holder: str, ttl_s: float,
                grace_s: float = 0.0) -> Optional[dict]:
        resp = self._ask({"op": "lease.acquire", "holder": str(holder),
                          "ttl_s": float(ttl_s),
                          "grace_s": float(grace_s)})
        if resp is None or not resp.get("acquired"):
            return None
        return resp.get("record")

    def renew(self, holder: str, term: int,
              ttl_s: float) -> Optional[dict]:
        resp = self._ask({"op": "lease.renew", "holder": str(holder),
                          "term": int(term), "ttl_s": float(ttl_s)})
        if resp is None or not resp.get("renewed"):
            return None
        return resp.get("record")

    def release(self, holder: str) -> None:
        self._ask({"op": "lease.release", "holder": str(holder)})

    def describe(self) -> str:
        return TCP_SCHEME + self.address


def make_store(target: Union[str, LeaseStore]) -> LeaseStore:
    """``tcp://host:port`` → :class:`TcpLeaseStore`; an already-built
    store passes through; anything else is a filesystem path."""
    if isinstance(target, LeaseStore):
        return target
    target = str(target)
    if target.startswith(TCP_SCHEME):
        return TcpLeaseStore(target)
    return FileLeaseStore(target)


class Lease:
    """One router's handle on the shared lease record (see module
    docstring).  ``path`` is a filesystem path, a ``tcp://host:port``
    lease-server address, or a pre-built :class:`LeaseStore`; the
    method surface (and every term/expiry semantic) is identical over
    all of them."""

    def __init__(self, path: Union[str, LeaseStore], holder: str,
                 ttl_s: float = 3.0):
        self.store = make_store(path)
        self.holder = str(holder)
        self.ttl_s = max(0.2, float(ttl_s))

    @property
    def path(self) -> str:
        return self.store.describe()

    @property
    def _lock_path(self) -> str:
        # back-compat for the flock-contention pin (file store only)
        return self.store._lock_path

    # -- reading -------------------------------------------------------
    def read(self) -> Optional[dict]:
        return self.store.read()

    @staticmethod
    def expired(rec: Optional[dict], grace_s: float = 0.0) -> bool:
        return lease_expired(rec, grace_s)

    # -- the write transactions ----------------------------------------
    def acquire(self, grace_s: float = 0.0) -> Optional[dict]:
        if inject("lease") in ("partition", "wedge"):
            return None  # store unreachable this beat: a lost beat
        return self.store.acquire(self.holder, self.ttl_s, grace_s)

    def renew(self, term: int) -> Optional[dict]:
        if inject("lease") in ("partition", "wedge"):
            return None
        return self.store.renew(self.holder, int(term), self.ttl_s)

    def release(self) -> None:
        self.store.release(self.holder)
