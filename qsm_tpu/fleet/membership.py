"""Fleet membership — node health, quarantine, and the routing ring.

The fleet tier treats a NODE exactly the way ``serve/pool.py`` treats
a worker process and ``resilience/failover.py`` treats a chip: health
is probed with bounded calls, a node that keeps missing its bound is
presumed wedged and QUARANTINED one-way (routing stops; only the
membership's own probes keep visiting), and a quarantined node is
RE-ADMITTED only on sustained health — ``readmit_after`` consecutive
good probes, not one lucky answer.  Both thresholds and every probe
bound come from the ``fleet-probe`` :data:`~qsm_tpu.resilience.policy.
PRESETS` entry, the same one-timeout-table discipline as the rest of
the stack.

Routing identity lives here too: :class:`HashRing` is a consistent
hash over virtual node points.  Keys are the serving plane's ONE cache
identity — ``serve.cache.fingerprint_key(spec, history)`` — so the
same (spec, history) always lands on the same node while it is
healthy, which is what keeps a node's verdict bank and per-sub-history
cache rows (PR 9) hot.  Health is filtered at LOOKUP time against the
full ring, so a node leaving moves only the keys it owned and a node
returning takes back exactly those keys.

Observability: ``node.down`` / ``node.shed`` / ``fleet.quarantine`` /
``fleet.readmit`` events ride the router's obs sink; quarantine and
node death are flight-recorder dump triggers (qsm_tpu/obs)."""

from __future__ import annotations

import bisect
import hashlib
import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..resilience.policy import RetryPolicy, preset
from ..serve.protocol import LineChannel, connect, send_doc


class HashRing:
    """Consistent hash: each node contributes ``vnodes`` points on a
    sha256 ring; a key routes to the first point clockwise whose node
    is allowed (healthy and not excluded).  Deterministic for a given
    node set — the routing table is a pure function, never state."""

    def __init__(self, node_ids: Sequence[str], vnodes: int = 64):
        points: List[Tuple[int, str]] = []
        for nid in node_ids:
            for v in range(vnodes):
                h = hashlib.sha256(f"{nid}:{v}".encode()).hexdigest()
                points.append((int(h[:16], 16), nid))
        points.sort()
        self._points = points
        self._keys = [p[0] for p in points]
        self.node_ids = list(node_ids)

    def node_for(self, key: str, allowed: Set[str],
                 exclude: Optional[Set[str]] = None) -> Optional[str]:
        """The key's owner among ``allowed - exclude`` (first
        clockwise point; walking the ring keeps non-excluded keys
        where they were).  None when nobody qualifies."""
        if not self._points:
            return None
        exclude = exclude or set()
        pos = int(hashlib.sha256(key.encode()).hexdigest()[:16], 16)
        start = bisect.bisect_right(self._keys, pos)
        seen: Set[str] = set()
        for i in range(len(self._points)):
            nid = self._points[(start + i) % len(self._points)][1]
            if nid in seen:
                continue
            seen.add(nid)
            if nid in allowed and nid not in exclude:
                return nid
        return None


class _Node:
    """One node's health record (all fields guarded by Membership's
    lock — probes, the router's failure feedback and ``stats`` readers
    share it)."""

    __slots__ = ("node_id", "address", "healthy", "quarantined",
                 "consecutive_failures", "consecutive_successes",
                 "probes", "failures", "quarantines", "readmissions",
                 "last_ok", "last_error", "next_probe_at")

    def __init__(self, node_id: str, address: str):
        self.node_id = node_id
        self.address = address
        self.healthy = True          # innocent until a probe says not
        self.quarantined = False
        self.consecutive_failures = 0
        self.consecutive_successes = 0
        self.probes = 0
        self.failures = 0
        self.quarantines = 0
        self.readmissions = 0
        self.last_ok = 0.0
        self.last_error = ""
        self.next_probe_at = 0.0


class Membership:
    """See module docstring.  ``nodes`` is a sequence of
    ``(node_id, address)`` pairs; the probe loop runs on one daemon
    thread between :meth:`start` and :meth:`stop`."""

    def __init__(self, nodes: Sequence[Tuple[str, str]], *,
                 policy: Optional[RetryPolicy] = None,
                 down_after: int = 2,
                 quarantine_after: int = 3,
                 readmit_after: int = 2,
                 heartbeat_s: float = 1.0,
                 vnodes: int = 64,
                 obs=None):
        self.policy = policy or preset("fleet-probe")
        # one missed probe under load is suspicion, not death: a node
        # leaves the healthy set after ``down_after`` CONSECUTIVE
        # failures (flapping every key off a node over one slow stats
        # answer would cost more than it saves — the router's
        # per-request tried-set already excludes a node that just
        # failed THIS request, whatever membership thinks)
        self.down_after = max(1, int(down_after))
        self.quarantine_after = max(self.down_after,
                                    int(quarantine_after))
        self.readmit_after = max(1, int(readmit_after))
        self.heartbeat_s = heartbeat_s
        self._nodes: Dict[str, _Node] = {
            nid: _Node(nid, addr) for nid, addr in nodes}
        self.vnodes = int(vnodes)
        self.ring = HashRing(list(self._nodes), vnodes=self.vnodes)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._obs = obs
        self.probes = 0
        self.probe_failures = 0
        self.quarantines = 0
        self.readmissions = 0
        self.joins = 0
        self.leaves = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "Membership":
        self._thread = threading.Thread(target=self._probe_loop,
                                        daemon=True,
                                        name="qsm-fleet-membership")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)

    # -- the probe loop ------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            now = time.monotonic()
            for node in list(self._nodes.values()):
                with self._lock:
                    due = now >= node.next_probe_at
                if due:
                    self.probe(node.node_id)

    def probe(self, node_id: str) -> bool:
        """One bounded health round-trip (a ``stats`` request — the
        cheapest op every node answers).  Feeds the same success/
        failure bookkeeping the router's dispatch feedback does."""
        node = self._nodes[node_id]
        with self._lock:
            self.probes += 1
            node.probes += 1
        ok = False
        try:
            sock = connect(node.address,
                           timeout_s=self.policy.timeout_s or 5.0)
            try:
                send_doc(sock, {"op": "stats"})
                line = LineChannel(sock).read_line(
                    timeout_s=self.policy.timeout_s or 5.0,
                    stop=self._stop.is_set)
                ok = bool(line) and bool(json.loads(line).get("ok"))
            finally:
                sock.close()
        except (OSError, ValueError, TimeoutError) as e:
            self.note_failure(node_id, e, probe=True)
            return False
        if ok:
            self.note_success(node_id)
        else:
            self.note_failure(node_id, RuntimeError("bad stats answer"),
                              probe=True)
        return ok

    # -- health feedback (probe loop AND router dispatch) --------------
    def note_failure(self, node_id: str, err: BaseException,
                     probe: bool = False) -> None:
        node = self._nodes.get(node_id)
        if node is None:
            return
        quarantined_now = False
        with self._lock:
            if probe:
                self.probe_failures += 1
            node.failures += 1
            node.consecutive_failures += 1
            node.consecutive_successes = 0
            node.last_error = f"{type(err).__name__}: {err}"[:200]
            was_healthy = node.healthy
            if node.consecutive_failures >= self.down_after:
                node.healthy = False
            # while down, probes back off (bounded by the preset's
            # schedule shape) so a dead node costs beats, not spins
            backoff = (self.policy.backoff_s or 1.0) * min(
                2 ** max(0, node.consecutive_failures - 1), 16)
            node.next_probe_at = time.monotonic() + backoff
            if (node.consecutive_failures >= self.quarantine_after
                    and not node.quarantined):
                node.quarantined = True
                node.quarantines += 1
                self.quarantines += 1
                quarantined_now = True
        if was_healthy and not node.healthy:
            self._emit("node.down", node=node_id,
                       error=node.last_error)
        if quarantined_now:
            self._emit("fleet.quarantine", node=node_id,
                       failures=node.consecutive_failures)

    def note_success(self, node_id: str) -> None:
        node = self._nodes.get(node_id)
        if node is None:
            return
        readmitted = recovered = False
        with self._lock:
            node.consecutive_failures = 0
            node.consecutive_successes += 1
            node.last_ok = time.monotonic()
            node.next_probe_at = 0.0
            if node.quarantined:
                # one good answer is luck; sustained health re-admits
                if node.consecutive_successes >= self.readmit_after:
                    node.quarantined = False
                    node.healthy = True
                    node.readmissions += 1
                    self.readmissions += 1
                    readmitted = True
            elif not node.healthy:
                node.healthy = True
                recovered = True
        if readmitted:
            self._emit("fleet.readmit", node=node_id)
        elif recovered:
            self._emit("node.up", node=node_id)

    def _emit(self, name: str, **attrs) -> None:
        if self._obs is None or not self._obs.on:
            return
        self._obs.event(name, **attrs)

    # -- elastic membership (ISSUE 18) ---------------------------------
    def add_node(self, node_id: str, address: str) -> bool:
        """JOIN: the node's vnode points enter the ring.  Consistent
        hashing moves ONLY the key ranges those points claim — every
        other key keeps its owner, so the fleet's hot banks stay hot
        through a rebalance.  Idempotent: re-joining a member is a
        no-op (False), except that a member re-joining from a NEW
        address re-addresses in place (a node that moved hosts keeps
        its identity, health record and key ranges)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                if node.address == address:
                    return False
                node.address = address           # moved hosts, same nid
            else:
                self._nodes[node_id] = _Node(node_id, address)
                self.ring = HashRing(list(self._nodes),
                                     vnodes=self.vnodes)
            self.joins += 1
        self._emit("fleet.join", node=node_id, address=address)
        return True

    def remove_node(self, node_id: str) -> bool:
        """LEAVE: the node's vnode points retire; only the key ranges
        it owned move (to the next point clockwise).  Idempotent —
        removing a non-member is a no-op (False)."""
        with self._lock:
            if node_id not in self._nodes:
                return False
            del self._nodes[node_id]
            self.ring = HashRing(list(self._nodes), vnodes=self.vnodes)
            self.leaves += 1
        self._emit("fleet.leave", node=node_id)
        return True

    # -- routing queries -----------------------------------------------
    def address_of(self, node_id: str) -> str:
        return self._nodes[node_id].address

    def healthy_ids(self) -> Set[str]:
        with self._lock:
            return {nid for nid, n in self._nodes.items()
                    if n.healthy and not n.quarantined}

    def routable_ids(self) -> Set[str]:
        """The set routing draws from: the healthy nodes — or, when a
        probe storm (a slow host, a mass flap) empties that set, every
        non-quarantined node.  Routing to a suspect node is cheap (the
        dispatch path's bounded attempts + tried-set exclusion handle
        a truly dead one); routing EVERYTHING to the in-process ladder
        because probes were slow starves the fleet of its own banks."""
        healthy = self.healthy_ids()
        if healthy:
            return healthy
        with self._lock:
            return {nid for nid, n in self._nodes.items()
                    if not n.quarantined}

    def all_ids(self) -> List[str]:
        return list(self._nodes)

    def node_for(self, key: str,
                 exclude: Optional[Set[str]] = None) -> Optional[str]:
        return self.ring.node_for(key, self.routable_ids(), exclude)

    # -- observability -------------------------------------------------
    def shed_state(self) -> dict:
        """The compact fleet block SHED responses carry
        (admission.shed_doc): enough for a client to tell 'overloaded'
        from 'down to one node'."""
        with self._lock:
            live = sum(1 for n in self._nodes.values()
                       if n.healthy and not n.quarantined)
            return {"nodes": len(self._nodes), "live": live,
                    "quarantined": sum(1 for n in self._nodes.values()
                                       if n.quarantined)}

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "nodes": [{
                    "node": n.node_id, "address": n.address,
                    "healthy": n.healthy,
                    "quarantined": n.quarantined,
                    "probes": n.probes, "failures": n.failures,
                    "quarantines": n.quarantines,
                    "readmissions": n.readmissions,
                    "last_error": n.last_error,
                } for n in self._nodes.values()],
                "probes": self.probes,
                "probe_failures": self.probe_failures,
                "quarantines": self.quarantines,
                "readmissions": self.readmissions,
                "joins": self.joins,
                "leaves": self.leaves,
                "policy": self.policy.name,
            }
