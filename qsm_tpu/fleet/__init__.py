"""``qsm_tpu.fleet`` — the multi-node serving tier (docs/SERVING.md
"Fleet").

The r08 worker pool scales one host; this package scales hosts while
keeping the defining property *survival*: nodes crash, wedge,
partition and restart while verdicts stay correct and available.

* ``router``     — :class:`FleetRouter`: the existing client protocol
  unchanged in front of N CheckServer nodes; consistent-hash routing
  by the verdict-cache identity, bounded exclude-and-re-dispatch on
  node loss, the router's own host ladder as the last rung, SHED with
  the per-node health block;
* ``membership`` — :class:`Membership` / :class:`HashRing`: bounded
  health probes (``fleet-probe`` preset), one-way quarantine after
  repeated wedges, re-admission on sustained health, and the
  consistent-hash routing ring;
* ``replog``     — :class:`SegmentedLog`: the append-only verdict
  bank generalized into content-fingerprinted segments that an
  anti-entropy loop replicates node-to-node, enabling rolling
  restarts with zero dropped or wrong verdicts; row-level segment
  subsumption keeps catch-up bounded past compactions;
* ``lease``      — :class:`Lease`: the filesystem term+TTL record
  arbitrating which of N routers is the fleet's one active brain
  (router HA — split-brain-safe takeover, one-way per term);
* ``gossip``     — :class:`GossipAgent`: node-to-node digest/pull/push
  anti-entropy with random peer fan-out, so banked verdicts keep
  converging with every router dead.

CLI: ``qsm-tpu fleet`` / ``qsm-tpu stats --serve ROUTER --fleet``;
bench: tools/bench_fleet.py (artifact ``BENCH_FLEET_r13.json``);
static gate: the QSM-FLEET pass family (analysis/fleet_passes.py).
"""

from .gossip import GossipAgent
from .lease import Lease
from .membership import HashRing, Membership
from .replog import SegmentedLog, segment_fingerprint
from .router import (FleetRouter, NodeDead, NodeFault, NodeLink,
                     NodePartitioned, NodeTimeout)

__all__ = [
    "FleetRouter", "GossipAgent", "HashRing", "Lease", "Membership",
    "NodeDead", "NodeFault", "NodeLink", "NodePartitioned",
    "NodeTimeout", "SegmentedLog", "segment_fingerprint",
]
