"""Segmented, replicated verdict log — the fleet's durable memory.

The single-file append bank (serve/cache.py) is exactly right for one
node and exactly wrong for a fleet: catch-up would mean shipping (and
rewriting) the whole bank, and two nodes could never exchange "what do
you have that I don't" cheaper than O(everything).  This module
generalizes the bank into SEGMENTS — the unit of durability, identity
and replication:

* **Banking** stays an O(batch) fsync'd append, now into an ``active``
  segment; at ``seal_rows`` rows the active segment SEALS into an
  immutable file whose name carries its origin node, its local
  sequence number, and a **content fingerprint** (sha256 over the row
  lines).  A sealed segment never changes, so its fingerprint IS its
  identity fleet-wide.
* **Anti-entropy** is a digest exchange over that identity: a node
  answers :meth:`digests` (name → fingerprint of every sealed segment
  it holds or has absorbed), a peer diffs with :meth:`missing`, pulls
  whole segments with :meth:`read_segment` and adopts them with
  :meth:`adopt` — fingerprint-verified, atomic, idempotent.  A joining
  or restarted node catches up to the fleet's live verdict set without
  any node rewriting anything.
* **Torn tails stay local.**  Only the ACTIVE segment can tear (a
  SIGKILL mid-append); the loader detects a tail that does not end on
  a clean parseable line, TRUNCATES it in place (atomic rewrite) and
  never replays the torn row as a verdict.  Sealed segments are
  verified against their fingerprint on load — a corrupt one is moved
  aside ``.quarantine``, never adopted, never served.
* **Compaction absorbs, never forgets — boundedly.**  When the row
  count outgrows the live set, every segment folds into one fresh
  local segment holding the post-merge (later-row-wins) live entries —
  and the absorbed segments' names+fingerprints are recorded in
  ``absorbed.json`` so the anti-entropy diff does not re-pull what
  compaction just deduplicated.  The record is HARD-CAPPED
  (``absorbed_cap``, fold-forward: oldest names drop first), so a
  100-compaction lifetime stays O(cap) on disk — safe to forget
  because row-level subsumption (below) protects anything the record
  no longer lists.
* **Row-level subsumption** (ISSUE 13): a compacted segment is a NEW
  identity holding rows its peers may all hold already.  Before a
  segment ships, the would-be receiver checks the segment's row-key
  coverage (:meth:`row_keys` on the owner, the ``replog.covers`` /
  ``replog.subsumed`` wire ops) against its OWN live set; full
  coverage records the name as *subsumed* (:meth:`note_subsumed` —
  capped like the absorbed record) and the rows never cross the wire.
  Catch-up cost per compaction drops from one full-live-set ship per
  peer to one key-list exchange.

Verdicts are pure functions of (spec, history) — fingerprint-keyed
rows from different nodes can only agree on the verdict — so adoption
order across nodes is free; later-row-wins matters only within a
node's own sequence (witness refreshes), which local seq order
preserves.  Consumed by :class:`~qsm_tpu.serve.cache.VerdictCache`
via its ``store`` parameter and by the router's anti-entropy loop
(fleet/router.py); wire surface: the ``replog.*`` server ops
(serve/protocol.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
from typing import Dict, List, Optional, Tuple

ACTIVE_NAME = "active.jsonl"
ABSORBED_NAME = "absorbed.json"
_SEG_ARTIFACT = "qsm_tpu_replog_seg"
_ACTIVE_ARTIFACT = "qsm_tpu_replog_active"
_ABSORBED_ARTIFACT = "qsm_tpu_replog_absorbed"
_VERSION = 1
# seg-<node>-<seq>-<fp12>.jsonl — lexicographic sort groups a node's
# segments in sequence order (seq is zero-padded)
_SEG_RE = re.compile(r"^seg-(?P<node>[A-Za-z0-9_.]+)-(?P<seq>\d{6})"
                     r"-(?P<fp>[0-9a-f]{12})\.jsonl$")


def segment_fingerprint(lines: List[str]) -> str:
    """Content identity of a segment: sha256 over its row lines (one
    per row, newline-joined — byte-stable however the file is framed)."""
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()


class SegmentedLog:
    """See module docstring.  Implements the VerdictCache ``store``
    contract (``load`` / ``append`` / ``compact`` / ``total_rows``)
    plus the anti-entropy surface (``digests`` / ``missing`` /
    ``read_segment`` / ``adopt``).  Thread-safe: the cache flushes
    under its own lock while anti-entropy ops arrive on server
    connection threads."""

    def __init__(self, dir: str, node_id: str = "n0",
                 seal_rows: int = 256, absorbed_cap: int = 64):
        self.dir = dir
        self.node_id = str(node_id)
        self.seal_rows = max(1, int(seal_rows))
        # hard bound on the absorbed AND subsumed records (fold-forward
        # semantics: oldest names drop first; row-level subsumption
        # protects anything forgotten)
        self.absorbed_cap = max(1, int(absorbed_cap))
        self._lock = threading.RLock()
        self._active_rows = 0        # data rows in the active segment
        self._active_clean = False   # file exists and ends on a clean line
        self._sealed: Dict[str, str] = {}    # name -> fingerprint
        self._absorbed: Dict[str, str] = {}  # compacted-away name -> fp
        self._subsumed: Dict[str, str] = {}  # coverage-skipped name -> fp
        self._next_seq = 1
        self.truncated_tails = 0     # torn active tails dropped on load
        self.quarantined_segments = 0  # fingerprint-mismatch segs set aside
        self.seals = 0
        self.adoptions = 0
        self.subsumptions = 0        # ships skipped: rows already held
        os.makedirs(dir, exist_ok=True)
        self._scan()

    # -- paths ---------------------------------------------------------
    def _seg_path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    @property
    def _active_path(self) -> str:
        return os.path.join(self.dir, ACTIVE_NAME)

    # -- startup scan --------------------------------------------------
    def _scan(self) -> None:
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            names = []
        local_seqs = [0]
        for name in names:
            m = _SEG_RE.match(name)
            if m is None:
                continue
            self._sealed[name] = self._verify_or_quarantine(name, m)
            if m.group("node") == self.node_id:
                local_seqs.append(int(m.group("seq")))
        self._sealed = {k: v for k, v in self._sealed.items()
                        if v is not None}
        ab, sub, next_seq = self._read_absorbed()
        self._absorbed = ab
        self._subsumed = sub
        for name in list(ab) + list(sub):
            m = _SEG_RE.match(name)
            if m is not None and m.group("node") == self.node_id:
                local_seqs.append(int(m.group("seq")))
        # the persisted high-water seq survives the capped absorbed
        # record forgetting old local names — a reused (seq, fp) name
        # colliding with a copy a peer still holds must stay impossible
        self._next_seq = max(max(local_seqs) + 1, next_seq)
        self._load_active_counts()

    def _verify_or_quarantine(self, name: str, m) -> Optional[str]:
        """The sealed segment's fingerprint, or None after setting a
        corrupt file aside (a bad replica must never be served OR
        offered to peers — quarantining it also makes the anti-entropy
        diff re-pull a good copy)."""
        try:
            _header, lines = self._read_lines(self._seg_path(name))
        except (OSError, ValueError):
            lines = None
        if (lines is not None
                and segment_fingerprint(lines)[:12] == m.group("fp")):
            return m.group("fp")
        try:
            os.replace(self._seg_path(name),
                       self._seg_path(name) + ".quarantine")
        except OSError:
            pass
        self.quarantined_segments += 1
        return None

    @staticmethod
    def _read_lines(path: str) -> Tuple[dict, List[str]]:
        with open(path) as f:
            text = f.read()
        raw = [ln for ln in text.splitlines() if ln.strip()]
        if not raw:
            return {}, []
        header = json.loads(raw[0])
        return header, raw[1:]

    def _load_active_counts(self) -> None:
        """Count the active segment's clean rows.  A GARBLED tail (the
        SIGKILL landed mid-append) is TRUNCATED on the spot — the torn
        row is never replayed as a verdict, and never left where the
        next append would weld onto it.  A final line that parses but
        lacks its newline is content-complete: kept, but the file is
        rewritten so the boundary is clean again."""
        path = self._active_path
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            self._active_rows = 0
            self._active_clean = False
            return
        clean: List[str] = []
        torn = False
        for ln in text.splitlines():
            if not ln.strip():
                continue
            try:
                json.loads(ln)
            except ValueError:
                torn = True
                break  # trust nothing at or past the tear
            clean.append(ln)
        if torn or (clean and not text.endswith("\n")):
            from ..resilience.checkpoint import atomic_write_text

            if torn:
                self.truncated_tails += 1
            if clean:
                atomic_write_text(path, "\n".join(clean) + "\n")
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        have_header = bool(clean) and clean[0].startswith(
            '{"artifact"')
        self._active_rows = max(0, len(clean) - 1) if have_header \
            else len(clean)
        self._active_clean = bool(clean)

    def _read_absorbed(self) -> Tuple[Dict[str, str], Dict[str, str], int]:
        try:
            with open(os.path.join(self.dir, ABSORBED_NAME)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}, {}, 1
        if doc.get("artifact") != _ABSORBED_ARTIFACT:
            return {}, {}, 1
        names = doc.get("names")
        sub = doc.get("subsumed")
        try:
            next_seq = max(1, int(doc.get("next_seq", 1)))
        except (TypeError, ValueError):
            next_seq = 1
        return (dict(names) if isinstance(names, dict) else {},
                dict(sub) if isinstance(sub, dict) else {},
                next_seq)

    def _cap_record(self, record: Dict[str, str]) -> Dict[str, str]:
        """Fold-forward: keep only the NEWEST ``absorbed_cap`` entries
        (dict insertion order = record order).  Forgotten names stay
        safe — the next offer of one is caught by the row-level
        subsumption check against the live set, which is exactly what
        covered the name when it entered this record."""
        while len(record) > self.absorbed_cap:
            record.pop(next(iter(record)))
        return record

    def _write_absorbed(self) -> None:
        from ..resilience.checkpoint import atomic_write_json

        atomic_write_json(
            os.path.join(self.dir, ABSORBED_NAME),
            {"artifact": _ABSORBED_ARTIFACT, "version": _VERSION,
             # NOT sorted: insertion order is the fold-forward order
             "names": dict(self._absorbed),
             "subsumed": dict(self._subsumed),
             "next_seq": self._next_seq})

    # -- the VerdictCache store contract -------------------------------
    @property
    def total_rows(self) -> int:
        with self._lock:
            return (self._active_rows
                    + sum(self._seg_rows(n) for n in self._sealed))

    def _seg_rows(self, name: str) -> int:
        cache = getattr(self, "_row_counts", None)
        if cache is None:
            cache = self._row_counts = {}
        n = cache.get(name)
        if n is None:
            try:
                _h, lines = self._read_lines(self._seg_path(name))
                n = len(lines)
            except (OSError, ValueError):
                n = 0
            cache[name] = n
        return n

    def load(self) -> List[dict]:
        """Every banked row in merge order: sealed segments (sorted by
        name — a node's own segments ride in sequence order) then the
        active segment.  Later rows supersede earlier ones exactly like
        the single-file bank's load."""
        with self._lock:
            rows: List[dict] = []
            for name in sorted(self._sealed):
                try:
                    _h, lines = self._read_lines(self._seg_path(name))
                except (OSError, ValueError):
                    continue
                rows.extend(self._parse_rows(lines))
            try:
                _h, lines = self._read_lines(self._active_path)
            except (OSError, ValueError):
                lines = []
            rows.extend(self._parse_rows(lines))
            return rows

    @staticmethod
    def _parse_rows(lines: List[str]) -> List[dict]:
        out = []
        for ln in lines:
            try:
                doc = json.loads(ln)
            except ValueError:
                continue
            if isinstance(doc, dict) and "key" in doc:
                out.append(doc)
        return out

    def append(self, lines: List[str]) -> None:
        """One fsync'd append of pre-serialized row lines into the
        active segment; seals it when full.  O(batch), like the bank."""
        if not lines:
            return
        with self._lock:
            header_line = None
            if not self._active_clean:
                header_line = json.dumps(
                    {"artifact": _ACTIVE_ARTIFACT, "version": _VERSION,
                     "node": self.node_id})
            with open(self._active_path, "a") as f:
                body = "\n".join(lines) + "\n"
                if header_line is not None:
                    body = header_line + "\n" + body
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            self._active_clean = True
            self._active_rows += len(lines)
            if self._active_rows >= self.seal_rows:
                self._seal_locked()

    def _seal_locked(self) -> None:
        try:
            _h, lines = self._read_lines(self._active_path)
        except (OSError, ValueError):
            return
        if not lines:
            return
        fp = segment_fingerprint(lines)
        name = f"seg-{self.node_id}-{self._next_seq:06d}-{fp[:12]}.jsonl"
        self._write_segment(name, fp, lines)
        self._sealed[name] = fp[:12]
        self._next_seq += 1
        self.seals += 1
        try:
            os.unlink(self._active_path)
        except OSError:
            pass
        self._active_rows = 0
        self._active_clean = False

    def _write_segment(self, name: str, fp: str, lines: List[str]) -> None:
        from ..resilience.checkpoint import atomic_write_text

        header = json.dumps({"artifact": _SEG_ARTIFACT,
                             "version": _VERSION, "rows": len(lines),
                             "fingerprint": fp})
        atomic_write_text(self._seg_path(name),
                          "\n".join([header] + lines) + "\n")
        rc = getattr(self, "_row_counts", None)
        if rc is not None:
            rc[name] = len(lines)

    def compact(self, lines: List[str]) -> None:
        """Fold everything into ONE fresh local segment holding the
        caller's post-merge live rows; absorbed segment names are
        REMEMBERED (capped, fold-forward — :meth:`_cap_record`) so the
        anti-entropy diff never re-pulls them."""
        with self._lock:
            fp = segment_fingerprint(lines)
            name = (f"seg-{self.node_id}-{self._next_seq:06d}"
                    f"-{fp[:12]}.jsonl")
            self._write_segment(name, fp, lines)
            self._next_seq += 1
            for old, old_fp in list(self._sealed.items()):
                # re-inserted at the record's newest end either way:
                # this compaction is the entry's newest coverage proof
                self._absorbed.pop(old, None)
                self._absorbed[old] = old_fp
                try:
                    os.unlink(self._seg_path(old))
                except OSError:
                    pass
            # a name both subsumed and now absorbed needs one record
            for old in list(self._subsumed):
                if old in self._absorbed:
                    self._subsumed.pop(old)
            self._cap_record(self._absorbed)
            self._cap_record(self._subsumed)
            self._sealed = {name: fp[:12]}
            try:
                os.unlink(self._active_path)
            except OSError:
                pass
            self._active_rows = 0
            self._active_clean = False
            self._write_absorbed()

    # -- the anti-entropy surface --------------------------------------
    def digests(self) -> Dict[str, str]:
        """name → fingerprint of every sealed segment this node HOLDS.
        Absorbed segments ride separately (:meth:`absorbed`): a peer
        must not pull them, but must also not think we lack them."""
        with self._lock:
            return dict(self._sealed)

    def absorbed(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._absorbed)

    def subsumed(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._subsumed)

    def covered(self) -> Dict[str, str]:
        """Everything this node need never be shipped: absorbed by its
        own compactions plus subsumed by row-level coverage — the set
        the ``replog.digests`` wire op advertises beside the held
        segments."""
        with self._lock:
            return {**self._absorbed, **self._subsumed}

    def missing(self, remote: Dict[str, str]) -> List[str]:
        """Remote segment names this node neither holds nor has
        absorbed/subsumed — what a catch-up must consider pulling."""
        with self._lock:
            return sorted(n for n in remote
                          if n not in self._sealed
                          and n not in self._absorbed
                          and n not in self._subsumed)

    @staticmethod
    def row_keys_of(lines: List[str]) -> List[str]:
        """The cache keys of already-read row lines (one parse — the
        push leg has the lines in hand and must not re-read the file
        just for its keys)."""
        return [str(r["key"])
                for r in SegmentedLog._parse_rows(lines)]

    def row_keys(self, name: str) -> Optional[List[str]]:
        """The cache keys of one HELD segment's rows — the coverage a
        peer checks against its live set before asking for the rows
        themselves (the ``replog.covers`` wire op)."""
        with self._lock:
            if name not in self._sealed:
                return None
            try:
                _h, lines = self._read_lines(self._seg_path(name))
            except (OSError, ValueError):
                return None
            return self.row_keys_of(lines)

    def covers(self, names) -> List[dict]:
        """``[{name, fingerprint, keys}]`` for the held segments among
        ``names`` — the ``replog.covers`` wire payload, ONE file read
        per segment (keys parsed from the read, the fingerprint from
        the in-memory sealed map)."""
        out: List[dict] = []
        with self._lock:
            for name in names:
                fp = self._sealed.get(name)
                if fp is None:
                    continue
                try:
                    _h, lines = self._read_lines(self._seg_path(name))
                except (OSError, ValueError):
                    continue
                out.append({"name": name, "fingerprint": fp,
                            "keys": self.row_keys_of(lines)})
        return out

    def note_subsumed(self, name: str, fingerprint: str) -> bool:
        """Record that ``name``'s rows are already fully held locally:
        the segment is treated as covered — never pulled, never
        offered as missing — without its rows ever crossing the wire.
        Same name/fingerprint consistency gate as :meth:`adopt`; the
        record is capped like the absorbed one.  False = already
        held/covered (no-op)."""
        m = _SEG_RE.match(name)
        if m is None:
            raise ValueError(f"bad segment name {name!r}")
        if fingerprint and m.group("fp") != fingerprint[:12]:
            raise ValueError(
                f"segment {name} name does not match its content "
                f"fingerprint {fingerprint[:12]} (refusing to subsume)")
        with self._lock:
            if name in self._sealed or name in self._absorbed \
                    or name in self._subsumed:
                return False
            self._subsumed[name] = m.group("fp")
            self._cap_record(self._subsumed)
            self.subsumptions += 1
            self._write_absorbed()
        return True

    def read_segment(self, name: str) -> Optional[Tuple[str, List[str]]]:
        """(fingerprint, row lines) of one sealed segment, or None —
        the pull side of catch-up."""
        with self._lock:
            if name not in self._sealed:
                return None
            try:
                header, lines = self._read_lines(self._seg_path(name))
            except (OSError, ValueError):
                return None
            return str(header.get("fingerprint", "")), lines

    def adopt(self, name: str, fingerprint: str,
              lines: List[str]) -> List[dict]:
        """Adopt one replicated segment: fingerprint-verified, atomic,
        idempotent (a segment already held or absorbed is a no-op).
        Returns the adopted rows so the caller can fold them into its
        in-memory live set WITHOUT re-banking them — each verdict lands
        on this node's disk exactly once, in exactly this segment."""
        m = _SEG_RE.match(name)
        if m is None:
            raise ValueError(f"bad segment name {name!r}")
        if segment_fingerprint(lines) != fingerprint:
            raise ValueError(
                f"segment {name} fingerprint mismatch (torn or forged "
                "replication payload; refusing to adopt)")
        if m.group("fp") != fingerprint[:12]:
            # an inconsistent name/fingerprint pair would persist now
            # and quarantine on every restart — a permanent
            # quarantine/re-adopt churn loop; refuse it at the door
            raise ValueError(
                f"segment {name} name does not match its content "
                f"fingerprint {fingerprint[:12]} (refusing to adopt)")
        with self._lock:
            if name in self._sealed or name in self._absorbed \
                    or name in self._subsumed:
                return []
            self._write_segment(name, fingerprint, lines)
            self._sealed[name] = fingerprint[:12]
            self.adoptions += 1
        return self._parse_rows(lines)

    # -- observability -------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "node": self.node_id,
                    "sealed_segments": len(self._sealed),
                    "absorbed_segments": len(self._absorbed),
                    "subsumed_segments": len(self._subsumed),
                    "absorbed_cap": self.absorbed_cap,
                    "active_rows": self._active_rows,
                    "seal_rows": self.seal_rows,
                    "seals": self.seals,
                    "adoptions": self.adoptions,
                    "subsumptions": self.subsumptions,
                    "truncated_tails": self.truncated_tails,
                    "quarantined_segments": self.quarantined_segments}
