"""Fleet router — N CheckServer nodes behind one protocol-identical door.

The r08 worker pool scales one host; this tier scales hosts.  The
router speaks the EXISTING client protocol (serve/protocol.py JSON
lines — clients need no changes, ``CheckClient`` points at the router
address) and fronts N :class:`~qsm_tpu.serve.server.CheckServer`
nodes:

* **Routing is the cache identity.**  Each history routes by
  consistent hash over ``serve.cache.fingerprint_key(spec, history)``
  — the same key the verdict bank and PR 9's per-sub-history cache
  rows use — so identical traffic keeps landing where its verdicts
  (and its projected-spec sub-rows) are already banked and hot
  (``membership.HashRing``).
* **A lost node is a shed worker.**  A node that crashes, wedges or
  partitions mid-request fails its sub-request; the undecided lanes
  re-dispatch to a surviving node — bounded attempts from the
  ``fleet-route`` :data:`~qsm_tpu.resilience.policy.PRESETS` entry,
  the failed node EXCLUDED (the ``tried`` set; the discipline the
  QSM-FLEET-REDISPATCH lint pass gates) — and the router's own
  in-process host cpp→memo ladder is the last rung, exactly the
  ``serve/pool.py`` shed ladder one level up.  Nothing a dead node
  banked is lost (banking is per-node, replicated by anti-entropy);
  nothing undecided is ever guessed.
* **SHED, never wrong.**  The router has its own
  ``AdmissionController``; overload, deadline, or a fleet with no
  deciding path left answers ``SHED`` with the per-node health block
  (``admission.shed_doc`` ``fleet`` entry) plus the router's node id
  and flight-dump path.
* **Anti-entropy.**  A background loop exchanges replog segment
  digests between nodes (the ``replog.*`` ops) and ships missing
  segments owner→lacker, so a joining or restarted node catches up to
  the fleet's live verdict set without a full rewrite — the mechanism
  behind zero-verdict-loss rolling restarts (fleet/replog.py).
* **Chaos-testable.**  Every router→node round-trip passes the
  ``node`` fault site (``QSM_TPU_FAULTS=partition:node@5`` etc.), so
  node death, wedge and partition cells run on the CPU platform like
  every other degradation path (tests/test_fleet.py,
  tools/bench_fleet.py).
* **HA — the router is no longer the tier's last SPOF** (ISSUE 13).
  Started with ``lease_path=``, N routers race a filesystem lease
  (fleet/lease.py: term + bounded TTL): exactly one wins ACTIVE and
  stamps its ``term`` on every response; the rest run STANDBY —
  membership probing and stats stay live, but check/shrink answer
  ``SHED`` with a ``router`` block (``router_standby``) so a
  multi-address client hops on.  The active renews on the sweep beat;
  a standby promotes only after observing lease expiry PLUS its own
  independent health probe of the nodes, minting term+1 — one-way per
  term, so a router that lost term T sheds ``router_superseded``
  under T forever (split-brain pinned in tests/test_fleet_ha.py).
  Takeover emits the ``router.takeover`` span and fires a flight
  dump.  Clients ride it via ``CheckClient("a,b")`` multi-address
  failover (bounded, safe — all fleet ops are idempotent and verdicts
  bank by fingerprint).

Observability (qsm_tpu/obs): the request's trace id rides every
sub-request to the nodes; the router emits ``route.request`` /
``node.dispatch`` / ``node.shed`` / ``route.hop`` / ``route.ladder``
/ ``route.response`` events, so ``qsm-tpu trace <id>`` on the
router's span log shows the hop from a dead node to the surviving
one.  Node death, quarantine and partition are flight-recorder dump
triggers; per-node dispatch counters ride the metrics registry.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..obs import Observability, global_obs, new_span_id, new_trace_id, \
    set_global
from ..ops.backend import Verdict
from ..resilience.faults import InjectedFault, fired_snapshot, inject
from ..resilience.policy import RetryPolicy, preset
from ..serve.admission import AdmissionController
from ..serve.cache import fingerprint_key
from ..serve.protocol import (VERDICT_NAMES, LineChannel, connect,
                              history_to_rows, rows_to_history, send_doc)


class NodeFault(RuntimeError):
    """A sub-request lost to a node; its lanes are undecided and the
    router re-dispatches them (never guesses them)."""


class NodeDead(NodeFault):
    """Connection refused/reset/closed: the node process is gone."""


class NodeTimeout(NodeFault):
    """The node missed its round-trip bound: presumed wedged."""


class NodePartitioned(NodeFault):
    """The fault plane dropped this exchange's frames both directions
    (``partition:node``): the request never arrived, the answer never
    left — indistinguishable from a dead switch, handled the same."""


class NodeBusy(RuntimeError):
    """Every pooled link slot to this node is mid-request: router-local
    backpressure, NOT node-health evidence (the WorkerBusy lesson one
    level down — penalizing a hot node's health would chase traffic
    off exactly the node doing the most work).  Callers try another
    node without feeding membership a failure."""


# what a router→node exchange can fail with (InjectedFault covers
# raise:node / hang:node drills)
_LINK_FAULTS = (NodeFault, OSError, ConnectionError, TimeoutError,
                ValueError, InjectedFault)


class NodeLink:
    """Bounded connection pool to ONE node.  Each request borrows a
    pooled (socket, channel) pair — concurrent router connections fan
    into the node's own micro-batcher over parallel sockets — under a
    semaphore bound; a faulted socket is discarded, never reused.

    ``address`` may be a comma-separated list (``a,b``): fresh
    connections try each in order, so a peer reachable on more than
    one door (an HA router pair fronting the same fleet, a node
    re-bound after migration) fails over at connect time.  Safe for
    the same reason the stale-pool retry is: every fleet op is
    idempotent."""

    MAX_CONNS = 16

    def __init__(self, node_id: str, address: str):
        self.node_id = node_id
        self.address = address
        self.addresses = [a.strip() for a in str(address).split(",")
                          if a.strip()]
        if not self.addresses:
            raise ValueError(f"node {node_id}: empty address")
        self._free: List[Tuple[socket.socket, LineChannel]] = []
        self._lock = threading.Lock()
        self._sema = threading.BoundedSemaphore(self.MAX_CONNS)

    def _connect(self, timeout_s: float) -> socket.socket:
        last: Optional[BaseException] = None
        for addr in self.addresses:
            try:
                return connect(addr, timeout_s=timeout_s)
            except (socket.timeout, TimeoutError):
                # a connect TIMEOUT is wedge/partition evidence, not
                # death: propagate so the caller maps it to
                # NodeTimeout (NodeDead would trigger the fresh-
                # connection retry and double the stall per address
                # against a SYN-dropping peer)
                raise
            except OSError as e:
                last = e
        raise NodeDead(f"node {self.node_id}: no address answered "
                       f"({type(last).__name__}: {last})") from last

    def request(self, doc: dict, timeout_s: float) -> dict:
        """One bounded round-trip.  Raises a :class:`NodeFault` family
        member (the caller excludes this node and re-dispatches)."""
        act = inject("node")
        if act == "partition":
            raise NodePartitioned(
                f"node {self.node_id}: frames dropped both directions "
                "(injected partition)")
        if act == "wedge":
            raise NodeTimeout(f"node {self.node_id}: injected wedge")
        timeout_s = max(0.1, float(timeout_s))
        if not self._sema.acquire(timeout=timeout_s):
            raise NodeBusy(
                f"node {self.node_id}: no link slot inside "
                f"{timeout_s:.1f}s (all {self.MAX_CONNS} mid-request)")
        try:
            try:
                return self._round_trip(doc, timeout_s, pooled_ok=True)
            except NodeDead:
                # a POOLED socket dying is expected across a node
                # restart (the peer that owned it is gone; the node at
                # this address may be perfectly healthy) — drop every
                # idle pooled sibling (they died together) and retry
                # ONCE on a FRESH connection before declaring the node
                # lost.  Safe because every fleet op is idempotent:
                # check/shrink/stats are pure and replog.push
                # re-adoption is a no-op, so a request whose response
                # was lost can be re-asked (the same reasoning behind
                # CellJournal resume).  A fresh-connection failure is
                # the real signal and propagates.
                self.close_all()
                return self._round_trip(doc, timeout_s, pooled_ok=False)
        finally:
            self._sema.release()

    def _round_trip(self, doc: dict, timeout_s: float,
                    pooled_ok: bool) -> dict:
        pair: Optional[Tuple[socket.socket, LineChannel]] = None
        try:
            if pooled_ok:
                with self._lock:
                    pair = self._free.pop() if self._free else None
            try:
                if pair is None:
                    sock = self._connect(min(timeout_s, 10.0))
                    pair = (sock, LineChannel(sock))
                sock, chan = pair
                send_doc(sock, doc)
                line = chan.read_line(timeout_s=timeout_s)
            except socket.timeout as e:
                raise NodeTimeout(
                    f"node {self.node_id}: round-trip exceeded "
                    f"{timeout_s:.1f}s") from e
            except TimeoutError as e:
                raise NodeTimeout(f"node {self.node_id}: {e}") from e
            except OSError as e:
                raise NodeDead(
                    f"node {self.node_id}: {type(e).__name__}: {e}"
                ) from e
            if line is None:
                raise NodeDead(f"node {self.node_id}: connection closed")
            try:
                resp = json.loads(line)
            except ValueError as e:
                raise NodeDead(
                    f"node {self.node_id}: undecodable response") from e
            with self._lock:
                if len(self._free) < self.MAX_CONNS:
                    self._free.append(pair)
                    pair = None
            return resp
        finally:
            if pair is not None:
                try:
                    pair[0].close()
                except OSError:
                    pass

    def close_all(self) -> None:
        with self._lock:
            pairs, self._free = self._free, []
        for sock, _chan in pairs:
            try:
                sock.close()
            except OSError:
                pass


class _GroupResult:
    """One node group's decided lanes (or None verdicts = shed)."""

    __slots__ = ("verdicts", "cached", "witnesses", "batches", "node",
                 "faults", "sheds")

    def __init__(self, n: int):
        self.verdicts: List[Optional[int]] = [None] * n
        self.cached: List[bool] = [False] * n
        self.witnesses: List[Optional[list]] = [None] * n
        self.batches: List[dict] = []
        self.node: Optional[str] = None
        self.faults = 0
        self.sheds = 0


class _RoutedSession:
    """One monitor session's router-side journal: the event stream the
    failover replay re-feeds (bounded by the router's event cap) plus
    the node currently owning the live session."""

    __slots__ = ("sid", "model", "spec_kwargs", "events", "node",
                 "lock", "last_used")

    def __init__(self, sid: str, model: str, spec_kwargs: dict):
        self.sid = sid
        self.model = model
        self.spec_kwargs = spec_kwargs or {}
        self.events: List = []
        self.node: Optional[str] = None
        self.lock = threading.Lock()
        self.last_used = time.monotonic()  # idle-eviction clock


class FleetRouter:
    """See module docstring.  ``nodes`` is ``[(node_id, address),
    ...]``; ``start()`` binds and returns like ``CheckServer``."""

    def __init__(self, nodes, host: str = "127.0.0.1", port: int = 0,
                 unix_path: Optional[str] = None, *,
                 policy: Optional[RetryPolicy] = None,
                 probe_policy: Optional[RetryPolicy] = None,
                 serve_policy: Optional[RetryPolicy] = None,
                 ae_policy: Optional[RetryPolicy] = None,
                 queue_depth: int = 4096,
                 quarantine_after: int = 3,
                 readmit_after: int = 2,
                 heartbeat_s: float = 1.0,
                 anti_entropy_s: float = 3.0,
                 ae_max_segments: int = 32,
                 allow_shutdown: bool = True,
                 node_id: str = "router",
                 session_dir: Optional[str] = None,
                 lease_path: Optional[str] = None,
                 lease_ttl_s: float = 3.0,
                 ha_grace_s: Optional[float] = None,
                 ha_beat_s: Optional[float] = None,
                 trace_log: Optional[str] = None,
                 flight_dir: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 obs: Optional[Observability] = None,
                 collect_dir: Optional[str] = None,
                 collect_s: float = 1.0,
                 slo: Optional[str] = None,
                 slo_window_s: float = 60.0):
        from .membership import Membership

        if not nodes:
            raise ValueError("a fleet needs at least one node")
        self.host, self.port, self.unix_path = host, port, unix_path
        self.node_id = node_id
        self.policy = policy or preset("fleet-route")
        self.serve_policy = serve_policy or preset("serve")
        self.ae_policy = ae_policy or preset("anti-entropy")
        self.anti_entropy_s = anti_entropy_s
        self.ae_max_segments = max(1, int(ae_max_segments))
        self.allow_shutdown = allow_shutdown
        self.obs = obs if obs is not None else Observability(
            trace_log=trace_log, flight_dir=flight_dir)
        self.metrics_port = metrics_port
        self._metrics_server = None
        self.membership = Membership(
            nodes, policy=probe_policy,
            quarantine_after=quarantine_after,
            readmit_after=readmit_after,
            heartbeat_s=heartbeat_s, obs=self.obs)
        self.links: Dict[str, NodeLink] = {
            nid: NodeLink(nid, addr) for nid, addr in nodes}
        self.admission = AdmissionController(
            queue_depth=queue_depth, policy=self.serve_policy,
            fleet_state=self.membership.shed_state)
        # the last-rung in-process ladder: one warm host engine +
        # witness oracle per spec, built lazily, dispatch-serialized
        # (engines are stateful — the _EngineEntry discipline)
        self._specs: Dict[str, object] = {}
        self._ladders: Dict[str, tuple] = {}
        # RLock: _ladder_for's build path re-enters through _spec_for
        self._ladders_lock = threading.RLock()
        # the session verbs' last rung (ISSUE 18): an in-router
        # SessionManager that takes a session when the fleet is
        # exhausted instead of shedding it — built lazily, like the
        # check path's warm engines above
        self._local_sessions = None
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._t0 = time.monotonic()
        # counters shared across connection threads (QSM-RACE-UNGUARDED)
        self._lock = threading.Lock()
        self.requests = 0
        self.histories = 0
        self.shrink_requests = 0
        self.node_faults = 0     # node exchanges lost (death/wedge/part.)
        self.lease_faults = 0    # lease-store transactions lost (faults)
        self.node_sheds = 0      # node answered SHED (backpressure)
        self.redispatches = 0    # lane groups moved to another node
        self.ladder_batches = 0  # groups the in-process rung decided
        self.ladder_lanes = 0
        self.ae_sweeps = 0
        self.ae_segments_shipped = 0
        self.ae_segments_subsumed = 0  # ships skipped: rows already held
        self.ae_rows_shipped = 0
        # monitor sessions (qsm_tpu/monitor): the router journals each
        # session's event stream (bounded) and routes its ops by the
        # session key; a node lost mid-session is excluded and the
        # journal REPLAYED onto the next ring node — which resumes from
        # the decided prefixes banked under prefix fingerprints (a
        # respawned node reloads them from its replog), so failover
        # costs bank hits, not re-searches (docs/MONITOR.md "Fleet").
        self._sessions_lock = threading.Lock()
        self._sessions: Dict[str, _RoutedSession] = {}
        # durable session journals (ISSUE 18, monitor/store.py): with
        # ``session_dir`` every journal snapshots/appends behind the
        # live object, so a router restart — or the STANDBY taking the
        # lease, pointed at the same shared store like the lease file —
        # rehydrates a session it never served and replays it onto the
        # ring.  None = journals die with the process (pre-ISSUE-18).
        self._session_store = None
        if session_dir is not None:
            from ..monitor.store import SessionStore

            self._session_store = SessionStore(session_dir)
        self.max_sessions = 1024
        self.session_event_cap = 65_536   # per-session journal bound
        # a client that crashed without closing must not pin a journal
        # forever: at the cap, journals idle past this are reclaimed
        # (the node-side session evicts on its own clock; a returning
        # client re-opens and replays by seq)
        self.session_idle_s = 3600.0
        self.session_requests = 0
        self.session_replays = 0          # journals replayed onto a node
        self.session_evicted = 0          # idle journals reclaimed at cap
        self.session_ladder = 0           # verbs the in-router rung took
        self.session_migrations = 0       # owners invalidated by a leave
        self.session_rehydrated = 0       # journals loaded from the store
        self._session_n = 0
        # router HA (fleet/lease.py; module docstring).  Without a
        # lease the router is unconditionally active — the single-
        # router deployment is byte-identical to PR 12.
        self.lease = None
        self.ha_role = "active"      # active | standby | superseded
        self.term = 0                # the term this router last HELD
        self.takeovers = 0
        self.ha_sheds = 0            # check/shrink refused while not active
        self._lease_expires = 0.0    # epoch bound of OUR live term
        self._observed: dict = {}    # last foreign lease record seen
        if lease_path is not None:
            from .lease import Lease

            self.lease = Lease(lease_path, holder=node_id,
                               ttl_s=lease_ttl_s)
            self.ha_role = "standby"  # until the first beat decides
            self.ha_grace_s = (ha_grace_s if ha_grace_s is not None
                               else self.lease.ttl_s * 0.5)
            self._beat_s = (ha_beat_s if ha_beat_s is not None
                            else max(0.05, self.lease.ttl_s / 3.0))
        else:
            self.ha_grace_s = 0.0
            self._beat_s = anti_entropy_s
        # the span id of the event that opened THIS router's current
        # term (router.elect / router.takeover / router.superseded):
        # every route.request roots under it, so `qsm-tpu trace` pulls
        # the fleet-level cause (the takeover) into a request's tree
        # via the causal closure — edges, never cross-process clocks
        self._term_span = ""
        # fleet-wide span collection (obs/collect.py): a dedicated
        # loop pulls every node's span log into ONE collected log with
        # per-node cursors persisted under collect_dir — what `qsm-tpu
        # trace <id> --addr ROUTER` reconstructs cross-process trees
        # from.  Its OWN thread, never the lease beat's: a wedged
        # node's scrape timeout must not delay lease renewal into a
        # spurious takeover.  NOT gated on the lease either: a standby
        # keeps its collected log warm, so a takeover does not lose
        # the old era's node spans.
        self.collector = None
        self.collect_s = max(0.1, float(collect_s))
        if collect_dir is not None:
            from ..obs import SpanCollector

            self.collector = SpanCollector(collect_dir)
        self._m_route_s = self.obs.metrics.histogram(
            "qsm_fleet_route_seconds",
            "router end-to-end request latency, labeled by verb")
        self.obs.metrics.register_collector(self._metric_samples)
        # metrics federation (docs/OBSERVABILITY.md "Fleet"): the
        # router's /metrics scrape fans out obs.metrics to every node
        # at scrape time and re-labels the samples with `node` — down
        # nodes become a staleness gauge, never a hang (bounded
        # round-trips, parallel fan-out)
        self.obs.metrics.register_collector(self._federated_samples)
        # SLO plane (obs/slo.py): same shape as CheckServer's — the
        # router's own per-verb route latency + shed counters under
        # declared objectives, health op + slo.breach flight trigger
        self.slo = None
        if slo:
            from ..obs import SloEvaluator, parse_slo

            self.slo = SloEvaluator(
                parse_slo(slo), latency_hist=self._m_route_s,
                requests_fn=lambda: self.requests,
                sheds_fn=self._shed_total, window_s=slo_window_s,
                on_breach=self._on_slo_breach)
            self.obs.metrics.register_collector(self.slo.metric_samples)

    # -- lifecycle -----------------------------------------------------
    @property
    def address(self) -> str:
        if self.unix_path:
            return self.unix_path
        return f"{self.host}:{self.port}"

    def start(self) -> "FleetRouter":
        if self.unix_path:
            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(self.unix_path)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._sock.settimeout(0.2)
        self.membership.start()
        # adopt the process-global obs slot only when it is free: a
        # co-resident CheckServer (in-process tests) owns its own —
        # the router must not silently steal its fault/degrade events
        if global_obs() is None:
            set_global(self.obs)
        if self.metrics_port is not None:
            from ..obs import MetricsServer

            self._metrics_server = MetricsServer(
                self.obs.metrics,
                host=self.host if not self.unix_path else "127.0.0.1",
                port=self.metrics_port).start()
            self.metrics_port = self._metrics_server.port
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="qsm-fleet-accept")
        t.start()
        self._threads.append(t)
        if self.lease is not None:
            # the first beat decides the starting role (winner of the
            # lease race goes active; the rest stand by)
            try:
                self.ha_beat()
            except OSError:
                pass
        if self._beat_s and self._beat_s > 0:
            t = threading.Thread(target=self._beat_loop,
                                 daemon=True, name="qsm-fleet-beat")
            t.start()
            self._threads.append(t)
        if self.collector is not None:
            t = threading.Thread(target=self._collect_loop,
                                 daemon=True, name="qsm-fleet-collect")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        first_stop = not self._stop.is_set()
        self._stop.set()
        if first_stop and self.lease is not None \
                and self.ha_role == "active":
            # clean shutdown hands the term over immediately: the
            # standby need not wait out the TTL (a SIGKILLed active
            # can't run this line — that path IS the TTL wait)
            self.lease.release()
        self.membership.stop()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self.unix_path:
            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
        for t in self._threads:
            t.join(2.0)
        for link in self.links.values():
            link.close_all()
        if first_stop:
            self.obs.dump_flight("router_stop", force=True)
        if self.collector is not None:
            self.collector.close()
        self.obs.metrics.unregister_collector(self._metric_samples)
        self.obs.metrics.unregister_collector(self._federated_samples)
        if self.slo is not None:
            self.obs.metrics.unregister_collector(
                self.slo.metric_samples)
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if global_obs() is self.obs:
            set_global(None)
        self.obs.close()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self._stop.wait(timeout_s)

    # -- connection plumbing (the CheckServer shape) -------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_connection,
                             args=(conn,), daemon=True,
                             name="qsm-fleet-conn").start()

    def _serve_connection(self, conn: socket.socket) -> None:
        chan = LineChannel(conn)
        try:
            while not self._stop.is_set():
                line = chan.read_line(stop=self._stop.is_set)
                if line is None:
                    return
                try:
                    req = json.loads(line)
                except ValueError:
                    self._send(conn, {"ok": False, "error": "bad json"})
                    continue
                self._handle(conn, req)
                if req.get("op") == "shutdown" and self.allow_shutdown:
                    return
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _send(self, conn: socket.socket, doc: dict) -> None:
        if "node" not in doc:
            doc = {**doc, "node": self.node_id}
        if self.lease is not None and "term" not in doc:
            # the HA contract: every response says which term answered
            # it, so merged logs (and the split-brain pins) can tell a
            # stale brain's answers from the live one's
            doc = {**doc, "term": self.term}
        send_doc(conn, doc)

    _SESSION_OPS = ("session.open", "session.append", "session.close")
    _MEMBER_OPS = ("node.join", "node.leave")

    def _handle(self, conn: socket.socket, req: dict) -> None:
        op = req.get("op", "check")
        if op in ("check", "shrink") + self._SESSION_OPS \
                + self._MEMBER_OPS \
                and not self._active_now():
            # a non-active (or expired-term) router must never answer
            # a verdict: SHED with the router block, client hops on
            trace = str(req.get("trace") or "") or new_trace_id()
            self._send(conn, self._ha_shed(req, trace))
            return
        if op == "stats":
            self._send(conn, {"ok": True, "stats": self.stats()})
        elif op in ("obs.spans", "obs.trace", "obs.metrics", "health"):
            # the observability surface stays up whatever the lease
            # says: a standby's collected log and health answer are
            # exactly what an operator needs mid-takeover
            try:
                self._handle_obs(conn, op, req)
            except OSError:
                raise
            except Exception as e:  # noqa: BLE001 — answer, don't die
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "error": f"{type(e).__name__}: {e}"})
        elif op == "shutdown":
            if self.allow_shutdown:
                self._send(conn, {"ok": True, "stopping": True})
                self.stop()
            else:
                self._send(conn, {"ok": False,
                                  "error": "shutdown disabled"})
        elif op in ("check", "shrink"):
            try:
                if op == "check":
                    self._handle_check(conn, req)
                else:
                    self._handle_shrink(conn, req)
            except OSError:
                raise
            except Exception as e:  # noqa: BLE001 — answer, don't die
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "error": f"{type(e).__name__}: {e}"})
        elif op in self._SESSION_OPS:
            try:
                self._handle_session(conn, op, req)
            except OSError:
                raise
            except Exception as e:  # noqa: BLE001 — answer, don't die
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "session": req.get("session"),
                                  "error": f"{type(e).__name__}: {e}"})
        elif op in self._MEMBER_OPS:
            try:
                self._handle_membership(conn, op, req)
            except OSError:
                raise
            except Exception as e:  # noqa: BLE001 — answer, don't die
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "error": f"{type(e).__name__}: {e}"})
        else:
            self._send(conn, {"ok": False,
                              "error": f"unknown op {op!r}"})

    # -- spec / ladder plumbing ----------------------------------------
    def _spec_key(self, model: str, spec_kwargs: dict) -> str:
        return json.dumps([model, spec_kwargs or {}], sort_keys=True)

    def _spec_for(self, model: str, spec_kwargs: dict):
        """The spec instance routing fingerprints against — built
        WITHOUT the ladder engines (the healthy path needs only the
        spec; engine/oracle construction waits for the first actual
        ladder rung)."""
        key = self._spec_key(model, spec_kwargs)
        with self._ladders_lock:
            entry = self._specs.get(key)
            if entry is None:
                from ..models.registry import make

                entry = self._specs[key] = make(
                    model, "atomic", spec_kwargs or None)[0]
            return entry

    def _ladder_for(self, model: str, spec_kwargs: dict):
        """(spec, host engine, witness oracle, dispatch lock) — the
        in-process last rung, one warm set per spec, built on first
        ladder use only."""
        spec = self._spec_for(model, spec_kwargs)
        key = self._spec_key(model, spec_kwargs)
        with self._ladders_lock:
            entry = self._ladders.get(key)
            if entry is None:
                from ..ops.wing_gong_cpu import WingGongCPU
                from ..resilience.failover import host_fallback

                entry = self._ladders[key] = (
                    spec, host_fallback(spec), WingGongCPU(memo=True),
                    threading.Lock())
            return entry

    # -- the one failover step (check AND shrink re-dispatch loops) ----
    def _hop_busy(self, key: str, target: str, tried: Set[str],
                  trace: str, root: str, lanes: int = 0
                  ) -> Optional[str]:
        """Next target after a saturated link: no health feedback (see
        NodeBusy), just the ring walk and its span."""
        nxt = self.membership.node_for(key, exclude=tried)
        self.obs.event("route.hop", trace=trace, parent=root,
                       lanes=lanes, hop_from=target,
                       hop_to=nxt or "ladder", busy=True,
                       traces=[trace])
        return nxt

    def _shed_node(self, key: str, target: str, tried: Set[str],
                   e: BaseException, trace: str, root: str,
                   lanes: int = 0) -> Optional[str]:
        """Account one LOST node exchange and pick the next target:
        the fault counter, membership feedback, the flight-dump
        trigger event (node.shed / node.partition, naming the doomed
        traces) and the route.hop span — ONE implementation for both
        re-dispatch loops, so the safety-critical shape the
        QSM-FLEET-REDISPATCH pass gates cannot diverge between them."""
        with self._lock:
            self.node_faults += 1
        self.membership.note_failure(target, e)
        name = ("node.partition" if isinstance(e, NodePartitioned)
                else "node.shed")
        self.obs.event(name, trace=trace, parent=root, node=target,
                       error=f"{type(e).__name__}: {e}"[:200],
                       traces=[trace])
        nxt = self.membership.node_for(key, exclude=tried)
        with self._lock:
            self.redispatches += 1
        self.obs.event("route.hop", trace=trace, parent=root,
                       lanes=lanes, hop_from=target,
                       hop_to=nxt or "ladder", traces=[trace])
        return nxt

    # -- the check path ------------------------------------------------
    def _handle_check(self, conn: socket.socket, req: dict) -> None:
        from ..models.registry import MODELS

        t_req = time.perf_counter()
        model = req.get("model")
        if model not in MODELS:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": f"unknown model {model!r}; one "
                                       f"of {sorted(MODELS)}"})
            return
        rows_list = req.get("histories")
        if rows_list is None and "history" in req:
            rows_list = [req["history"]]
        if not isinstance(rows_list, list) or not rows_list:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": "request needs a non-empty "
                                       "'histories' (or 'history') "
                                       "array"})
            return
        hists = [rows_to_history(rows) for rows in rows_list]
        spec_kwargs = req.get("spec_kwargs") or {}
        spec = self._spec_for(model, spec_kwargs)
        want_witness = bool(req.get("witness"))
        deadline = self.admission.deadline_for(req.get("deadline_s"))
        trace = str(req.get("trace") or "") or new_trace_id()
        root = ""
        if self.obs.on:
            root = new_span_id()
            self.obs.tracer.emit("route.request", trace=trace,
                                 span=root, parent=self._term_span,
                                 model=model, lanes=len(hists))
        with self._lock:
            self.requests += 1
            self.histories += len(hists)
        if not self.admission.try_admit(len(hists)):
            self._respond(conn, self._shed(req, "queue full", trace,
                                           root), trace, root, t_req)
            return
        try:
            doc = self._route_check(req, model, spec, spec_kwargs,
                                    hists, want_witness, deadline,
                                    trace, root, t_req)
            self._respond(conn, doc, trace, root, t_req,
                          status="shed" if doc.get("shed") else "ok")
        finally:
            self.admission.release(len(hists))

    def _route_check(self, req, model, spec, spec_kwargs, hists,
                     want_witness, deadline, trace, root,
                     t_req) -> dict:
        # route each history by its cache identity; histories sharing a
        # node coalesce into ONE sub-request (the node's micro-batcher
        # takes it from there)
        keys = [fingerprint_key(spec, h) for h in hists]
        routable = self.membership.routable_ids()
        groups: Dict[Optional[str], List[int]] = {}
        for i, key in enumerate(keys):
            nid = self.membership.ring.node_for(key, routable) \
                if routable else None
            groups.setdefault(nid, []).append(i)
        if self.obs.on:
            for nid, idxs in sorted(groups.items(),
                                    key=lambda kv: str(kv[0])):
                self.obs.event("route.assign", trace=trace, parent=root,
                               node=nid or "ladder", lanes=len(idxs))
        results: Dict[Optional[str], _GroupResult] = {}
        group_errors: List[BaseException] = []

        def run_group(nid: Optional[str], idxs: List[int]) -> None:
            try:
                results[nid] = self._dispatch_group(
                    nid, [hists[i] for i in idxs], keys[idxs[0]],
                    model, spec, spec_kwargs, want_witness, deadline,
                    trace, root, req.get("deadline_s"))
            except Exception as e:  # noqa: BLE001 — re-raised below
                # a deterministic error (bad kwargs reaching the
                # ladder, an engine bug) must answer as an ERROR, not
                # masquerade as a retryable SHED — swallow nothing
                group_errors.append(e)

        items = sorted(groups.items(), key=lambda kv: str(kv[0]))
        threads = [threading.Thread(target=run_group, args=(nid, idxs),
                                    daemon=True,
                                    name=f"qsm-fleet-group-{nid}")
                   for nid, idxs in items[1:]]
        for t in threads:
            t.start()
        run_group(*items[0])
        for t in threads:
            t.join(max(0.1, deadline - time.monotonic()) + 5.0)
        if group_errors:
            raise group_errors[0]
        verdicts: List[Optional[int]] = [None] * len(hists)
        cached = [False] * len(hists)
        witnesses: List[Optional[list]] = [None] * len(hists)
        batches: List[dict] = []
        nodes_used: Dict[str, int] = {}
        faults = sheds = 0
        for nid, idxs in items:
            res = results.get(nid)
            if res is None:
                return self._shed(req, "fleet exhausted", trace, root)
            faults += res.faults
            sheds += res.sheds
            for j, i in enumerate(idxs):
                verdicts[i] = res.verdicts[j]
                cached[i] = res.cached[j]
                witnesses[i] = res.witnesses[j]
            batches.extend(res.batches)
            if res.node is not None:
                nodes_used[res.node] = (nodes_used.get(res.node, 0)
                                        + len(idxs))
        if any(v is None for v in verdicts):
            # a group shed (deadline / all nodes + ladder refused):
            # never answer partially, never guess
            return self._shed(req, "fleet shed", trace, root)
        doc = {
            "id": req.get("id"), "ok": True, "model": model,
            "trace": trace,
            "verdicts": [VERDICT_NAMES[v] for v in verdicts],
            "cached": cached,
            "violations": sum(v == int(Verdict.VIOLATION)
                              for v in verdicts),
            "undecided": sum(v == int(Verdict.BUDGET_EXCEEDED)
                             for v in verdicts),
            "batches": batches,
            "nodes": nodes_used,
            "seconds": round(time.perf_counter() - t_req, 4),
        }
        if faults:
            doc["node_faults"] = faults
        if sheds:
            doc["node_sheds"] = sheds
        if want_witness:
            doc["witnesses"] = [
                [list(p) for p in w] if w is not None else None
                for w in witnesses]
        return doc

    def _dispatch_group(self, nid: Optional[str], hists, group_key: str,
                        model: str, spec, spec_kwargs, want_witness,
                        deadline: float, trace: str, root: str,
                        deadline_s) -> _GroupResult:
        """Decide one node group: bounded attempts across the ring with
        the failed nodes EXCLUDED, then the in-process ladder.  Lanes
        are all-or-nothing per attempt (a lost node banked nothing the
        router saw), mirroring ``WorkerPool.dispatch``."""
        res = _GroupResult(len(hists))
        subreq = {"op": "check", "id": "fleet-sub", "model": model,
                  "histories": [history_to_rows(h) for h in hists],
                  "trace": trace}
        if spec_kwargs:
            subreq["spec_kwargs"] = spec_kwargs
        if want_witness:
            subreq["witness"] = True
        if deadline_s is not None:
            subreq["deadline_s"] = deadline_s
        tried: Set[str] = set()
        target = nid
        for _attempt in range(max(1, self.policy.attempts)):
            if target is None or self._stop.is_set():
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return res  # deadline: undecided lanes stay None (shed)
            tried.add(target)
            timeout_s = min(self.policy.timeout_s or 30.0, remaining)
            dispatch_span = self.obs.event(
                "node.dispatch", trace=trace, parent=root,
                node=target, lanes=len(hists), traces=[trace])
            if dispatch_span:
                # the node's own `request` root parents under THIS
                # dispatch edge, so the collected fleet tree shows
                # router -> node causally (docs/OBSERVABILITY.md)
                subreq["parent"] = dispatch_span
            try:
                resp = self.links[target].request(subreq, timeout_s)
            except NodeBusy:
                target = self._hop_busy(group_key, target, tried,
                                        trace, root, lanes=len(hists))
                continue
            except _LINK_FAULTS as e:
                res.faults += 1
                target = self._shed_node(group_key, target, tried, e,
                                         trace, root, lanes=len(hists))
                continue
            if resp.get("ok"):
                self.membership.note_success(target)
                res.node = str(resp.get("node") or target)
                names = resp.get("verdicts") or []
                for j, name in enumerate(names[:len(hists)]):
                    res.verdicts[j] = VERDICT_NAMES.index(name)
                res.cached = list(resp.get("cached")
                                  or [False] * len(hists))
                if want_witness:
                    res.witnesses = list(resp.get("witnesses")
                                         or [None] * len(hists))
                for b in resp.get("batches") or []:
                    b = {**b, "node": res.node}
                    if res.faults:
                        # the batch survived a node loss: its own cost
                        # record says so (SearchStats node_faults,
                        # compact "ndf")
                        search = dict(b.get("search") or {})
                        search["ndf"] = (search.get("ndf", 0)
                                         + res.faults)
                        b["search"] = search
                    res.batches.append(b)
                return res
            if resp.get("shed"):
                # cross-fleet backpressure: this node refused honestly;
                # another node (or the ladder) may have room.  NOT a
                # health fault — shedding is the healthy overload answer.
                with self._lock:
                    self.node_sheds += 1
                res.sheds += 1
                prev, target = target, self.membership.node_for(
                    group_key, exclude=tried)
                self.obs.event("route.hop", trace=trace, parent=root,
                               lanes=len(hists), hop_from=prev,
                               hop_to=target or "ladder", shed=True,
                               traces=[trace])
                continue
            # a clean error answer (bad kwargs reach every node the
            # same way): re-dispatching cannot help — fail the group
            # to the ladder, which will raise the same way if it is a
            # request problem
            break
        return self._ladder_group(res, hists, model, spec_kwargs,
                                  want_witness, deadline, trace, root)

    def _ladder_group(self, res: _GroupResult, hists, model: str,
                      spec_kwargs, want_witness, deadline: float,
                      trace: str, root: str) -> _GroupResult:
        """The last rung: the router's own warm host cpp→memo ladder —
        exact verdicts, in-process, serialized per spec."""
        if time.monotonic() >= deadline:
            return res
        spec, engine, oracle, lock = self._ladder_for(model, spec_kwargs)
        self.obs.event("route.ladder", trace=trace, parent=root,
                       lanes=len(hists))
        with lock:
            if want_witness:
                pairs = [oracle.check_witness(spec, h) for h in hists]
                verdicts = [int(v) for v, _w in pairs]
                res.witnesses = [w for _v, w in pairs]
            else:
                verdicts = [int(v) for v in
                            engine.check_histories(spec, hists)]
        res.verdicts = verdicts
        res.node = self.node_id
        with self._lock:
            self.ladder_batches += 1
            self.ladder_lanes += len(hists)
        res.batches.append({
            "batch": f"ladder-{self.ladder_batches}",
            "lanes": len(hists), "width": len(hists),
            "flush": "ladder", "node": self.node_id,
            "search": {"ndf": res.faults}})
        return res

    # -- the shrink verb -----------------------------------------------
    def _handle_shrink(self, conn: socket.socket, req: dict) -> None:
        """Route one minimization to the node owning the ORIGINAL
        history's fingerprint (its verdict bank has the best chance of
        memo hits), bounded re-dispatch on node loss, in-process
        ladder shrink as the last rung."""
        from ..models.registry import MODELS

        t_req = time.perf_counter()
        model = req.get("model")
        if model not in MODELS:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": f"unknown model {model!r}; one "
                                       f"of {sorted(MODELS)}"})
            return
        rows = req.get("history")
        if not isinstance(rows, list) or not rows:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": "shrink needs ONE non-empty "
                                       "'history' rows array"})
            return
        h = rows_to_history(rows)
        spec_kwargs = req.get("spec_kwargs") or {}
        spec = self._spec_for(model, spec_kwargs)
        key = fingerprint_key(spec, h)
        deadline = self.admission.deadline_for(req.get("deadline_s"))
        trace = str(req.get("trace") or "") or new_trace_id()
        root = ""
        if self.obs.on:
            root = new_span_id()
            self.obs.tracer.emit("route.request", trace=trace,
                                 span=root, parent=self._term_span,
                                 model=model, op="shrink", ops=len(h))
        with self._lock:
            self.requests += 1
            self.shrink_requests += 1
        if not self.admission.try_admit(1):
            self._respond(conn, self._shed(req, "queue full", trace,
                                           root), trace, root,
                          t_req, verb='shrink')
            return
        try:
            subreq = {**req, "trace": trace}
            tried: Set[str] = set()
            target = self.membership.node_for(key)
            faults = 0
            for _attempt in range(max(1, self.policy.attempts)):
                if target is None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                tried.add(target)
                # bounded like a check round-trip: a wedged node must
                # cost one link timeout, not the request's whole
                # deadline (a node mid-shrink that outlives the bound
                # still banks its result for the re-ask to hit)
                timeout_s = min(self.policy.timeout_s or 30.0,
                                remaining)
                dispatch_span = self.obs.event(
                    "node.dispatch", trace=trace, parent=root,
                    node=target, op="shrink", traces=[trace])
                if dispatch_span:
                    subreq["parent"] = dispatch_span
                try:
                    resp = self.links[target].request(subreq, timeout_s)
                except NodeBusy:
                    target = self._hop_busy(key, target, tried, trace,
                                            root)
                    continue
                except _LINK_FAULTS as e:
                    faults += 1
                    target = self._shed_node(key, target, tried, e,
                                             trace, root)
                    continue
                if resp.get("ok") or resp.get("shed"):
                    self.membership.note_success(target)
                    doc = {**resp, "id": req.get("id"), "trace": trace}
                    if faults:
                        doc["node_faults"] = faults
                    self._respond(conn, doc, trace, root, t_req,
                                  status=("shed" if resp.get("shed")
                                          else "ok"), verb='shrink')
                    return
                break  # clean error answer: the ladder will say why
            doc = self._ladder_shrink(req, model, spec_kwargs, h,
                                      deadline, trace, root, faults,
                                      t_req)
            self._respond(conn, doc, trace, root, t_req, verb='shrink')
        finally:
            self.admission.release(1)

    def _ladder_shrink(self, req, model, spec_kwargs, h, deadline,
                       trace, root, faults, t_req) -> dict:
        from ..shrink.shrinker import Shrinker

        spec, engine, _oracle, lock = self._ladder_for(model,
                                                       spec_kwargs)
        self.obs.event("route.ladder", trace=trace, parent=root,
                       op="shrink", ops=len(h))

        def decide(hists):
            if time.monotonic() >= deadline:
                return None
            with lock:
                return np.asarray(
                    engine.check_histories(spec, list(hists)))

        shrinker = Shrinker(spec, decide, deadline=deadline)
        res = shrinker.run(h)
        with self._lock:
            self.ladder_batches += 1
        doc = {
            "id": req.get("id"), "ok": True, "model": model,
            "trace": trace, "node": self.node_id,
            "verdict": VERDICT_NAMES[int(res.verdict)],
            "initial_ops": res.initial_ops,
            "final_ops": res.final_ops,
            "ratio": round(res.ratio, 3),
            "rounds": res.rounds,
            "engine_calls": res.engine_calls,
            "lanes": res.lanes_checked,
            "memo_hits": res.memo_hits,
            "complete": res.complete,
            "one_minimal": res.one_minimal,
            "undecided_neighbors": res.undecided_neighbors,
            "history": history_to_rows(res.history),
            "why": res.why + ["decided on the router's in-process "
                              "ladder (fleet last rung)"],
            "seconds": round(time.perf_counter() - t_req, 4),
        }
        if faults:
            doc["node_faults"] = faults
        return doc

    # -- monitor sessions (qsm_tpu/monitor; docs/MONITOR.md "Fleet") ---
    def _handle_session(self, conn: socket.socket, op: str,
                        req: dict) -> None:
        """Route one session verb by the session key.  The router
        journals every event it forwards; a node lost mid-session
        (death/wedge/partition) is excluded and the journal replayed
        onto the next ring node — re-open + seq-0 re-append, idempotent
        on both legs, with the decided-prefix bank absorbing the
        engine cost (a respawned node's replog serves the prefixes).
        SHED semantics match ``check``: queue-full, caps and an
        exhausted fleet answer SHED, never a wrong or partial verdict."""
        from ..models.registry import MODELS

        t_req = time.perf_counter()
        trace = str(req.get("trace") or "") or new_trace_id()
        root = ""
        if self.obs.on:
            root = new_span_id()
            self.obs.tracer.emit("route.request", trace=trace,
                                 span=root, parent=self._term_span,
                                 op=op, session=req.get("session"))
        with self._lock:
            self.requests += 1
            self.session_requests += 1
        if op == "session.open":
            model = req.get("model")
            if model not in MODELS:
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "trace": trace,
                                  "error": f"unknown model {model!r}; "
                                           f"one of {sorted(MODELS)}"})
                return
            sid = req.get("session")
            # a named sid that is not live may still be DURABLE (a
            # router restart, or this is the standby post-takeover on
            # the shared store): rehydrate before creating fresh, or
            # the re-open would wipe the journal the client resumes on
            rehydrated = None
            with self._sessions_lock:
                known = sid is not None and str(sid) in self._sessions
            if sid is not None and not known:
                rehydrated = self._rehydrate_session(str(sid))
            created = False
            with self._sessions_lock:
                if sid is not None and str(sid) in self._sessions:
                    sess = self._sessions[str(sid)]
                    if sess.model != model:
                        self._send(conn, {
                            "id": req.get("id"), "ok": False,
                            "trace": trace,
                            "error": f"session {sid} is open against "
                                     f"{sess.model!r}"})
                        return
                else:
                    if len(self._sessions) >= self.max_sessions:
                        now = time.monotonic()
                        for stale in [k for k, v in
                                      self._sessions.items()
                                      if now - v.last_used
                                      >= self.session_idle_s]:
                            self._sessions.pop(stale)
                            self.session_evicted += 1
                    if len(self._sessions) >= self.max_sessions:
                        self._respond(conn, self._shed(
                            req, "session cap", trace, root), trace,
                            root, t_req, verb='session')
                        return
                    if rehydrated is not None:
                        if rehydrated.model != model:
                            self._send(conn, {
                                "id": req.get("id"), "ok": False,
                                "trace": trace,
                                "error": f"session {sid} is durable "
                                         f"against "
                                         f"{rehydrated.model!r}"})
                            return
                        sess = rehydrated
                        self._sessions[sess.sid] = sess
                        self.session_rehydrated += 1
                    else:
                        if sid is None:
                            self._session_n += 1
                            sid = f"{self.node_id}-s{self._session_n:06d}"
                        sess = _RoutedSession(str(sid), model,
                                              req.get("spec_kwargs")
                                              or {})
                        self._sessions[sess.sid] = sess
                        created = True
            if created and self._session_store is not None:
                # seed the durable journal before any events ride it
                # (session lock only — never under _sessions_lock, the
                # one global order; disk IO stays off the registry)
                with sess.lock:
                    self._session_store.snapshot(
                        sess.sid, self._session_doc(sess))
        else:
            sid = str(req.get("session") or "")
            with self._sessions_lock:
                sess = self._sessions.get(sid)
            if sess is None:
                sess = self._rehydrate_session(sid)
                if sess is not None:
                    with self._sessions_lock:
                        raced = self._sessions.get(sid)
                        if raced is not None:
                            sess = raced
                        else:
                            self._sessions[sid] = sess
                            self.session_rehydrated += 1
            if sess is None:
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "session": sid, "trace": trace,
                                  "error": f"unknown session {sid!r}"})
                return
        if not self.admission.try_admit(1):
            self._respond(conn, {**self._shed(req, "queue full", trace,
                                              root), "session":
                                 sess.sid}, trace, root, t_req, verb='session')
            return
        try:
            from ..monitor import SessionLimit

            sess.last_used = time.monotonic()
            deadline = self.admission.deadline_for(req.get("deadline_s"))
            try:
                with sess.lock:
                    doc = self._route_session(sess, op, req, deadline,
                                              trace, root)
                    if doc is None:
                        # the fleet is exhausted: the session verbs'
                        # LAST RUNG (ISSUE 18) is the router's own
                        # in-process SessionManager, exactly the check
                        # path's host ladder — SHED only if that rung
                        # refuses too
                        doc = self._session_ladder(sess, op, req,
                                                   deadline, trace,
                                                   root)
            except SessionLimit as e:
                doc = {**self._shed(req, str(e), trace, root),
                       "session": sess.sid}
            if doc is None:
                doc = {**self._shed(req, "fleet exhausted", trace,
                                    root), "session": sess.sid}
            elif op == "session.close" and doc.get("ok"):
                with self._sessions_lock:
                    self._sessions.pop(sess.sid, None)
                if self._session_store is not None:
                    self._session_store.drop(sess.sid)
            self._respond(conn, doc, trace, root, t_req,
                          status="shed" if doc.get("shed") else "ok",
                          verb='session')
        finally:
            self.admission.release(1)

    @staticmethod
    def _session_doc(sess: _RoutedSession) -> dict:
        """The durable form of one routed session (caller holds
        ``sess.lock``): identity + the full journal.  Small by bound —
        the event cap bounds the journal, snap-every bounds the tail."""
        return {"sid": sess.sid, "model": sess.model,
                "spec_kwargs": dict(sess.spec_kwargs),
                "events": [list(e) for e in sess.events]}

    def _rehydrate_session(self, sid: str
                           ) -> Optional["_RoutedSession"]:
        """Rebuild a routed session from the durable store; None on a
        miss or an unreadable doc.  The caller registers it (and only
        the registered object counts — a racing rehydrate loses).  The
        rebuilt session has ``node=None``, so the next verb replays the
        journal onto the ring owner exactly like a node-loss failover."""
        if self._session_store is None:
            return None
        loaded = self._session_store.load(sid)
        if loaded is None:
            return None
        doc, tail = loaded
        try:
            sess = _RoutedSession(str(doc["sid"]), str(doc["model"]),
                                  dict(doc.get("spec_kwargs") or {}))
            events = [list(e) for e in doc.get("events", [])]
            for batch in tail:
                start = int(batch["seq"])
                if start > len(events):
                    break            # torn tail: stop at the gap
                events.extend(batch["events"]
                              [max(0, len(events) - start):])
            sess.events = events[:self.session_event_cap]
        except (KeyError, TypeError, ValueError):
            return None
        return sess

    def _route_session(self, sess: _RoutedSession, op: str, req: dict,
                       deadline: float, trace: str, root: str
                       ) -> Optional[dict]:
        """One session verb under bounded exclude-and-replay failover;
        None = no node could take it (the caller sheds)."""
        subreq = {**req, "session": sess.sid, "trace": trace}
        if op == "session.append":
            events = req.get("events")
            if not isinstance(events, list) or not events:
                raise ValueError("session.append needs a non-empty "
                                 "'events' array")
            seq = req.get("seq")
            start = int(seq) if seq is not None else len(sess.events)
            if start > len(sess.events):
                raise ValueError(
                    f"session {sess.sid}: append seq {start} leaves a "
                    f"gap (journal holds {len(sess.events)})")
            fresh = events[max(0, len(sess.events) - start):]
            if len(sess.events) + len(fresh) > self.session_event_cap:
                from ..monitor import SessionLimit

                raise SessionLimit(
                    f"session {sess.sid}: router journal cap "
                    f"{self.session_event_cap} reached")
            sess.events.extend(fresh)
            if self._session_store is not None and fresh:
                # journal the fresh suffix behind the live object
                # (caller holds sess.lock; same snap-every compaction
                # contract as MonitorSession.append)
                self._session_store.append_events(sess.sid, start,
                                                  fresh)
                if self._session_store.tail_len(sess.sid) \
                        >= self._session_store.snap_every:
                    self._session_store.snapshot(
                        sess.sid, self._session_doc(sess))
            # the forwarded append is ALWAYS seq-stamped with the
            # batch's journal position: a seq-less client's events
            # were just replayed inside the journal (a fresh/restarted
            # owner), and forwarding them unframed would apply them a
            # second time and desync the node's stream counter
            subreq["seq"] = start
        if op == "session.open":
            subreq.setdefault("spec_kwargs", sess.spec_kwargs)
        key = f"session:{sess.sid}"
        tried: Set[str] = set()
        target = sess.node if sess.node is not None \
            and sess.node in self.membership.routable_ids() \
            else self.membership.node_for(key)
        faults = 0
        for _attempt in range(max(1, self.policy.attempts)):
            if target is None or self._stop.is_set():
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            tried.add(target)
            timeout_s = min(self.policy.timeout_s or 30.0, remaining)
            dispatch_span = self.obs.event(
                "node.dispatch", trace=trace, parent=root,
                node=target, op=op, session=sess.sid, traces=[trace])
            if dispatch_span:
                subreq["parent"] = dispatch_span
            try:
                if target != sess.node:
                    # a fresh owner (first dispatch, or post-failover):
                    # re-establish the session there — open + full
                    # journal replay, both idempotent (seq framing; a
                    # respawned node resumes from its banked prefixes)
                    self._replay_session(sess, target, timeout_s,
                                         trace, root)
                resp = self.links[target].request(subreq, timeout_s)
            except NodeBusy:
                target = self._hop_busy(key, target, tried, trace,
                                        root)
                continue
            except _LINK_FAULTS as e:
                faults += 1
                sess.node = None
                target = self._shed_node(key, target, tried, e, trace,
                                         root)
                continue
            if resp.get("unknown_session"):
                # the node restarted and lost the live object (its
                # answer is machine-readable by contract): force a
                # journal replay onto it on the next attempt — NOT a
                # health fault, the node is up and answering
                self.membership.note_success(target)
                sess.node = None
                continue
            if resp.get("ok") or resp.get("shed"):
                self.membership.note_success(target)
                sess.node = target if resp.get("ok") else sess.node
                doc = {**resp, "id": req.get("id"), "trace": trace}
                if faults:
                    doc["node_faults"] = faults
                return doc
            # a clean error (bad events reach every node the same way):
            # surface it — re-dispatch cannot help
            return {**resp, "id": req.get("id"), "trace": trace}
        return None

    def _replay_session(self, sess: _RoutedSession, target: str,
                        timeout_s: float, trace: str, root: str
                        ) -> None:
        """Re-establish a journaled session on ``target`` (link faults
        propagate to the caller's failover loop)."""
        link = self.links[target]
        open_doc = {"op": "session.open", "id": "fleet-sub",
                    "model": sess.model,
                    "spec_kwargs": sess.spec_kwargs,
                    "session": sess.sid, "trace": trace}
        if root:
            open_doc["parent"] = root
        opened = link.request(open_doc, timeout_s)
        if not opened.get("ok"):
            raise NodeFault(f"node {target}: session.open refused: "
                            f"{opened.get('error') or opened}")
        if sess.events:
            with self._lock:
                self.session_replays += 1
            replay_span = self.obs.event(
                "session.replay", trace=trace, parent=root,
                session=sess.sid, node=target,
                events=len(sess.events))
            replay_doc = {"op": "session.append", "id": "fleet-sub",
                          "session": sess.sid, "seq": 0,
                          "events": sess.events, "trace": trace}
            if replay_span:
                replay_doc["parent"] = replay_span
            replayed = link.request(replay_doc, timeout_s)
            if not replayed.get("ok"):
                raise NodeFault(
                    f"node {target}: session replay refused: "
                    f"{replayed.get('error') or replayed}")

    def _session_ladder(self, sess: _RoutedSession, op: str, req: dict,
                        deadline: float, trace: str, root: str
                        ) -> Optional[dict]:
        """The session verbs' last in-process rung (ISSUE 18): with
        every node excluded, the router's own SessionManager takes the
        session — the journal replays into a local MonitorSession
        exactly as it would onto a node (idempotent by seq), and the
        verdict stays exact.  A flip here pushes the UNMINIMIZED
        stream as the repro (the shrink plane lives on the nodes;
        ``complete: false`` says so honestly).  Caller holds
        ``sess.lock``; the local session/manager locks nest inside it
        and never the other way — the one global order."""
        from ..monitor import SessionManager

        with self._ladders_lock:
            if self._local_sessions is None:
                self._local_sessions = SessionManager(
                    max_sessions=self.max_sessions,
                    max_events=self.session_event_cap)
            mgr = self._local_sessions
        spec = self._spec_for(sess.model, sess.spec_kwargs)
        self.obs.event("route.ladder", trace=trace, parent=root,
                       op=op, session=sess.sid)
        with self._lock:
            self.session_ladder += 1
        s, resumed = mgr.open(sess.sid, spec, None, trace=trace)
        with s.lock:
            s.model, s.spec_kwargs = sess.model, dict(sess.spec_kwargs)
            if sess.events:   # idempotent journal replay, like a node
                s.append([list(e) if isinstance(e, (list, tuple))
                          else e for e in sess.events], seq=0)
            if op == "session.close":
                verdict = s.close()
                doc = {"id": req.get("id"), "ok": True,
                       "session": s.sid, "seq": s.seq,
                       "verdict": VERDICT_NAMES[verdict],
                       "trace": trace, "flipped": s.flipped,
                       "ladder": True,
                       **{k: v for k, v in s.counters().items()
                          if k != "frontiers"}}
                mgr.close(s.sid)
                return doc
            already_pushed = s.flip_pushed
            verdict = s.decide()
            c = s.counters()
            doc = {"id": req.get("id"), "ok": True, "session": s.sid,
                   "seq": s.seq, "verdict": VERDICT_NAMES[verdict],
                   "trace": trace, "ladder": True,
                   "decided_prefix": c["committed_ops"],
                   "window_ops": c["window_ops"]}
            if op == "session.open":
                doc.update(model=sess.model, resumed=resumed,
                           per_key=False)
            else:
                # the client's batch was journaled before routing, so
                # its events are inside the replay above; the applied
                # count it expects is its own batch's length
                doc["applied"] = len(req.get("events") or [])
            if s.flipped and not already_pushed:
                s.flip_pushed = True
                mgr.note_flip()
                rows = [list(r) for r in (s.flip_rows or s.rows)]
                doc["flip"] = {
                    "verdict": VERDICT_NAMES[int(Verdict.VIOLATION)],
                    "initial_ops": len(rows), "final_ops": len(rows),
                    "rounds": 0, "one_minimal": False,
                    "complete": False, "repro": rows,
                    "why": "router last rung: shrink plane lives on "
                           "the nodes — unminimized stream repro"}
            elif s.flipped:
                doc["flipped"] = True
        return doc

    # -- elastic membership (ISSUE 18; docs/SERVING.md) ----------------
    def _handle_membership(self, conn: socket.socket, op: str,
                           req: dict) -> None:
        """``node.join`` adds a node to the ring (consistent hashing
        moves only the ranges its vnode points claim) and opens its
        link; an anti-entropy sweep runs on the spot so the newcomer
        receives the replog segments its new ranges need (handoff is
        gossip-driven and subsumption-bounded — nodes already holding
        the rows ship nothing).  ``node.leave`` retires the node,
        closes its link, and invalidates it as owner of every routed
        session (each journal replays onto the new ring owner on its
        next verb — live migration, exactly-once by seq).  Both are
        idempotent; both are active-gated like every routing op."""
        nid = str(req.get("node") or "")
        if not nid:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": f"{op} needs 'node'"})
            return
        if op == "node.join":
            addr = str(req.get("address") or "")
            if not addr:
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "error": "node.join needs 'address'"})
                return
            joined = self.membership.add_node(nid, addr)
            old = self.links.get(nid)
            if old is None or joined:
                self.links[nid] = NodeLink(nid, addr)
                if old is not None:
                    old.close_all()
            swept = {}
            if joined and not self._stop.is_set():
                # seed the newcomer's replog NOW (bounded by the
                # anti-entropy preset; the periodic beat finishes any
                # backlog) so its first routed keys hit warm banks
                swept = self.anti_entropy_sweep()
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "joined": joined, "node": nid,
                              "nodes": len(self.membership.all_ids()),
                              "handoff": swept})
            return
        left = self.membership.remove_node(nid)
        migrated = 0
        if left:
            link = self.links.pop(nid, None)
            if link is not None:
                link.close_all()
            # live session migration: snapshot under the registry lock,
            # invalidate owners under each SESSION lock outside it
            # (session-lock-before-manager-lock — the one global order)
            with self._sessions_lock:
                owned = [s for s in self._sessions.values()
                         if s.node == nid]
            for sess in owned:
                with sess.lock:
                    if sess.node == nid:
                        sess.node = None
                        migrated += 1
            if migrated:
                with self._lock:
                    self.session_migrations += migrated
                self.obs.event("session.migrate", node=nid,
                               sessions=migrated)
        self._send(conn, {"id": req.get("id"), "ok": True,
                          "left": left, "node": nid,
                          "sessions_migrated": migrated,
                          "nodes": len(self.membership.all_ids())})

    # -- shed / respond ------------------------------------------------
    def _shed(self, req: dict, reason: str, trace: str = "",
              parent: str = "") -> dict:
        self.obs.event("admission.shed", trace=trace, parent=parent,
                       reason=reason)
        self.obs.note_shed()
        doc = self.admission.shed_doc(req.get("id"), reason,
                                      trace=trace or None,
                                      flight=self.obs.flight_path())
        # the fleet SHED contract: the shedding node's id + dump path
        # ride the refusal (ISSUE 12) — shed_doc added `flight`; the
        # node id lands via _send's stamp, duplicated here for callers
        # reading the doc without the egress stamp
        doc["node"] = self.node_id
        return doc

    def _respond(self, conn, doc: dict, trace: str, root: str,
                 t_req: float, status: str = "ok",
                 verb: str = "check") -> None:
        if doc.get("shed") and status == "ok":
            # every shed — admission-driven included — must close its
            # causal tree as a shed, or span tooling undercounts them
            status = "shed"
        dt = time.perf_counter() - t_req
        if self.obs.on:
            self.obs.tracer.emit("route.response", trace=trace,
                                 parent=root,
                                 ms=round(dt * 1000.0, 3),
                                 status=status,
                                 shed=bool(doc.get("shed")))
        self._m_route_s.observe(dt, verb=verb)
        self._send(conn, doc)

    # -- the HA lease (fleet/lease.py; module docstring) ---------------
    def _active_now(self) -> bool:
        """May THIS router answer verdicts right now?  Leaseless =
        always; leased = active role AND our term's own expiry still
        ahead (one bounded clock compare on the hot path — the beat
        refreshes the bound; a renewal that cannot land in time makes
        this False before any standby can have promoted)."""
        if self.lease is None:
            return True
        return self.ha_role == "active" \
            and time.time() < self._lease_expires

    def ha_beat(self) -> dict:
        """One lease heartbeat: the active renews its term; everyone
        else walks the gated promotion path — observe the record,
        consult its term and expiry, and only past expiry (plus grace)
        probe the nodes independently and acquire term+1.  Public so
        tests and the split-brain pins drive it synchronously."""
        if self.lease is None:
            return {"role": self.ha_role, "term": self.term}
        if self.ha_role == "active":
            rec = self._lease_call(self.lease.renew, self.term)
            if rec is not None:
                self._lease_expires = rec["expires_at"]
            else:
                self._demote(self.lease.read())
            return {"role": self.ha_role, "term": self.term}
        # standby / superseded: the ONE promotion path (QSM-FLEET-LEASE
        # gates exactly this shape — term/expiry consulted, no loop)
        rec = self.lease.read()
        if rec is not None:
            self._observed = {"term": rec.get("term"),
                              "holder": rec.get("holder"),
                              "expires_at": rec.get("expires_at")}
        if not self.lease.expired(rec, self.ha_grace_s):
            return {"role": self.ha_role, "term": self.term}
        if not self._nodes_reachable():
            # a standby that cannot see the fleet must not grab the
            # term just to answer everything from its own ladder
            return {"role": self.ha_role, "term": self.term,
                    "blocked": "no reachable node"}
        got = self._lease_call(self.lease.acquire, self.ha_grace_s)
        if got is not None:
            self._promote(got, superseded=rec)
        return {"role": self.ha_role, "term": self.term}

    def _lease_call(self, fn, *args):
        """One lease-store transaction under the ``lease`` fault site
        (resilience/faults.py): an injected raise/hang — like any
        transport loss a TcpLeaseStore already maps to None — is a
        LOST BEAT, counted, never a dead beat thread.  Safety is
        preserved by construction: a lost renew demotes (one-way per
        term), a lost acquire just waits for the next beat."""
        try:
            return fn(*args)
        except (InjectedFault, OSError):
            with self._lock:
                self.lease_faults += 1
            return None

    def _nodes_reachable(self) -> bool:
        """The standby's independent pre-promotion health probe: at
        least one fleet node must answer THIS router directly — a
        lease expiry observed from behind a partition is not a mandate
        to serve."""
        return any(self.membership.probe(nid)
                   for nid in self.membership.all_ids())

    def _promote(self, rec: dict, superseded: Optional[dict]) -> None:
        takeover = superseded is not None  # vs. a fresh lease's election
        with self._lock:
            self.ha_role = "active"
            self.term = int(rec["term"])
            if takeover:
                self.takeovers += 1
        self._lease_expires = rec["expires_at"]
        if takeover:
            # the takeover span (the bench/test acceptance: `qsm-tpu
            # trace` shows it with the superseded term) — also a
            # flight-dump trigger (obs._DUMP_TRIGGERS), so a takeover
            # leaves an artifact naming what the new active saw.  Its
            # span id becomes the term's root edge: every request this
            # term serves parents under it, so the causal closure of
            # any post-takeover trace includes the takeover itself.
            self._term_span = self.obs.event(
                "router.takeover", node=self.node_id, term=self.term,
                superseded_term=superseded.get("term"),
                superseded_holder=superseded.get("holder"))
        else:
            self._term_span = self.obs.event(
                "router.elect", node=self.node_id, term=self.term)

    def _demote(self, seen: Optional[dict]) -> None:
        """One-way per term: our term is gone (superseded or expired
        unrenewable).  We keep standing by — re-entry only by WINNING
        a later term through the gated promotion path."""
        if seen is not None:
            self._observed = {"term": seen.get("term"),
                              "holder": seen.get("holder"),
                              "expires_at": seen.get("expires_at")}
        with self._lock:
            self.ha_role = "superseded"
        self._lease_expires = 0.0
        # the supersession becomes this router's term edge: its
        # subsequent router_superseded SHED spans parent under it, so
        # a client's bounce off the stale brain reconstructs with its
        # cause in the collected tree
        self._term_span = self.obs.event(
            "router.superseded", node=self.node_id, term=self.term,
            active_term=(seen or {}).get("term"),
            active_holder=(seen or {}).get("holder"))

    def _ha_shed(self, req: dict, trace: str) -> dict:
        """The non-active refusal: SHED with the ``router`` block — a
        stale-term router must never answer a verdict, and the block
        tells a multi-address client (and the operator) where the
        active brain is."""
        with self._lock:
            self.ha_sheds += 1
            was_active = self.term > 0
        reason = "router_superseded" if was_active else "router_standby"
        # the advisory active_term/active_holder come from the BEAT's
        # cached observation (refreshed every ~TTL/3) — a refused
        # request must not cost a lease-file read on the request
        # thread.  One exception: a just-expired active that has not
        # beaten yet observed nothing; read once so its very first
        # superseded SHED can still name the successor.
        observed = self._observed
        if not observed and self.lease is not None:
            rec = self.lease.read()
            if rec is not None:
                observed = self._observed = {
                    "term": rec.get("term"),
                    "holder": rec.get("holder"),
                    "expires_at": rec.get("expires_at")}
        # the refusal leaves a SPAN, parented under this router's term
        # edge (router.superseded / the standby's last observation):
        # a client bouncing between `--addr a,b` during a takeover
        # window reconstructs in the collected tree — its trace shows
        # the stale door's refusal AND the active door's answer
        # (test-pinned in tests/test_obs_fleet.py)
        self.obs.event("admission.shed", trace=trace,
                       parent=self._term_span, reason=reason,
                       role=self.ha_role, term=self.term)
        doc = {"id": req.get("id"), "ok": False, "shed": True,
               "reason": reason, "node": self.node_id}
        if trace:
            doc["trace"] = trace
        doc["router"] = {
            "role": self.ha_role, "term": self.term,
            "active_term": observed.get("term"),
            "active_holder": observed.get("holder"),
        }
        return doc

    # -- the beat loop (lease renewal + anti-entropy) ------------------
    def _beat_loop(self) -> None:
        next_ae = time.monotonic()
        while not self._stop.wait(self._beat_s):
            if self.lease is not None:
                try:
                    self.ha_beat()
                except Exception:  # noqa: BLE001 — the beat survives
                    pass
            if (self.anti_entropy_s and self.anti_entropy_s > 0
                    and self._active_now()
                    and time.monotonic() >= next_ae):
                next_ae = time.monotonic() + self.anti_entropy_s
                try:
                    self.anti_entropy_sweep()
                except Exception:  # noqa: BLE001 — the loop must survive
                    continue

    def anti_entropy_sweep(self) -> dict:
        """One digest-exchange reconciliation: collect every healthy
        node's sealed-segment digests, ship each node the segments it
        neither holds nor has absorbed (owner → lacker), bounded per
        sweep (``ae_max_segments`` and the ``anti-entropy`` preset's
        deadline) so a big backlog drains over several beats.  Public
        so tests and the rolling-restart bench drive it synchronously."""
        sweep_deadline = time.monotonic() + (
            self.ae_policy.deadline_s or 60.0)
        timeout_s = self.ae_policy.timeout_s or 15.0
        digests: Dict[str, Tuple[dict, dict]] = {}
        for nid in sorted(self.membership.healthy_ids()):
            try:
                resp = self.links[nid].request(
                    {"op": "replog.digests"}, timeout_s)
            except NodeBusy:
                continue  # saturated link: catch up next beat
            except _LINK_FAULTS as e:
                self.membership.note_failure(nid, e)
                continue
            if resp.get("ok") and isinstance(resp.get("digests"), dict):
                digests[nid] = (dict(resp["digests"]),
                                dict(resp.get("absorbed") or {}))
        union: Dict[str, str] = {}   # segment name -> an owner node
        for nid, (dig, _ab) in sorted(digests.items()):
            for name in dig:
                union.setdefault(name, nid)
        shipped = rows = subsumed = 0
        covers_cache: Dict[str, Optional[dict]] = {}
        for nid, (dig, ab) in sorted(digests.items()):
            missing = [n for n in sorted(union)
                       if n not in dig and n not in ab]
            for name in missing[:self.ae_max_segments]:
                if time.monotonic() >= sweep_deadline:
                    break
                owner = union[name]
                # pull and push legs blamed SEPARATELY: a dead lacker
                # must not accrue failures to the healthy owner it was
                # being caught up from (and vice versa)
                try:
                    cov = self._ae_covers(owner, name, covers_cache,
                                          timeout_s)
                except NodeBusy:
                    break  # saturated link: finish this node next beat
                except _LINK_FAULTS as e:
                    self.membership.note_failure(owner, e)
                    break
                if cov is not None and cov.get("keys"):
                    # row-level subsumption: the LACKER's own live set
                    # decides whether the rows need to move at all — a
                    # compacted segment it effectively holds is marked
                    # covered without one row line crossing the wire
                    try:
                        sub = self.links[nid].request(
                            {"op": "replog.subsumed", "name": name,
                             "fingerprint": cov.get("fingerprint"),
                             "keys": cov["keys"]}, timeout_s)
                    except NodeBusy:
                        break
                    except _LINK_FAULTS as e:
                        self.membership.note_failure(nid, e)
                        break
                    if sub.get("subsumed"):
                        subsumed += 1
                        continue
                try:
                    pulled = self.links[owner].request(
                        {"op": "replog.pull", "segments": [name]},
                        timeout_s)
                except NodeBusy:
                    break
                except _LINK_FAULTS as e:
                    self.membership.note_failure(owner, e)
                    break
                segs = pulled.get("segments") or []
                if not segs:
                    continue
                try:
                    pushed = self.links[nid].request(
                        {"op": "replog.push", "segments": segs},
                        timeout_s)
                except NodeBusy:
                    break
                except _LINK_FAULTS as e:
                    self.membership.note_failure(nid, e)
                    break
                shipped += int(pushed.get("adopted", 0))
                rows += int(pushed.get("rows", 0))
        with self._lock:
            self.ae_sweeps += 1
            self.ae_segments_shipped += shipped
            self.ae_segments_subsumed += subsumed
            self.ae_rows_shipped += rows
        if shipped or subsumed:
            self.obs.event("fleet.anti_entropy", nodes=len(digests),
                           segments=shipped, rows=rows,
                           subsumed=subsumed)
        return {"nodes": len(digests), "segments_shipped": shipped,
                "segments_subsumed": subsumed, "rows_shipped": rows}

    def _ae_covers(self, owner: str, name: str,
                   cache: Dict[str, Optional[dict]],
                   timeout_s: float) -> Optional[dict]:
        """One segment's row-key coverage from its owner, fetched once
        per sweep however many lackers need it.  None = the owner
        cannot say (old node, unreadable segment): the ship proceeds —
        subsumption is an optimization, never a correctness gate."""
        if name in cache:
            return cache[name]
        resp = self.links[owner].request(
            {"op": "replog.covers", "segments": [name]}, timeout_s)
        cov = None
        for c in resp.get("covers") or []:
            if c.get("name") == name:
                cov = c
        cache[name] = cov
        return cov

    # -- fleet observability: collection / federation / health ---------
    def collect_sweep(self) -> dict:
        """One span-collection sweep (obs/collect.py): pull bounded
        cursor pages of every reachable node's span log into the
        collected log.  Public so tests and the bench drive it
        synchronously; the beat loop runs it every ``collect_s``."""
        if self.collector is None:
            return {}
        timeout_s = self.membership.policy.timeout_s or 5.0
        routable = self.membership.routable_ids()
        nodes = [nid for nid in self.membership.all_ids()
                 if nid in routable]

        def fetch(nid: str, cursor, max_events: int) -> dict:
            return self.links[nid].request(
                {"op": "obs.spans", "cursor": cursor,
                 "max_events": max_events}, timeout_s)

        res = self.collector.sweep(nodes, fetch)
        if res.get("events") or res.get("gaps"):
            self.obs.event("obs.collect", **res)
        return res

    def _collect_loop(self) -> None:
        while not self._stop.wait(self.collect_s):
            try:
                self.collect_sweep()
            except Exception:  # noqa: BLE001 — the loop must survive
                pass

    def _handle_obs(self, conn: socket.socket, op: str,
                    req: dict) -> None:
        """The router's observability ops: ``obs.trace`` answers from
        the COLLECTED fleet log merged with the router's own span log
        (causal closure — the cross-process tree `qsm-tpu trace <id>
        --addr ROUTER` renders); ``obs.spans`` pages the router's own
        log; ``obs.metrics`` returns the full federated sample set;
        ``health`` folds the router's SLO with every node's."""
        if op == "health":
            self._send(conn, {"id": req.get("id"), "ok": True,
                              **self.health_doc()})
            return
        if op == "obs.metrics":
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "samples": [list(s) for s in
                                          self.obs.metrics.collect()]})
            return
        if op == "obs.spans":
            from ..obs.collect import span_page_response

            self._send(conn, span_page_response(self.obs.tracer, req))
            return
        # obs.trace: own log + the collected fleet log, one closure
        from ..obs import load_events, trace_closure

        path = self.obs.tracer.path
        trace_id = str(req.get("trace") or "")
        events: List[dict] = []
        if path is not None:
            self.obs.tracer.flush()
            events.extend(load_events(path))
        if self.collector is not None:
            events.extend(load_events(self.collector.out_path))
        self._send(conn, {"id": req.get("id"), "ok": True,
                          "trace": trace_id,
                          "enabled": path is not None,
                          "collected": self.collector is not None,
                          "events": trace_closure(events, trace_id)})

    def _fan_out_nodes(self, fn, timeout_s: float) -> List[str]:
        """Run ``fn(nid)`` for every ROUTABLE node in parallel daemon
        threads with a bounded join — the one fan-out shape behind
        per-node stats, metrics federation and fleet health (a wedged
        node costs the caller ONE timeout, never one per node).
        Returns the live ids attempted; nodes membership already
        knows are down are skipped (the caller reports the hole)."""
        routable = self.membership.routable_ids()
        live = [nid for nid in self.membership.all_ids()
                if nid in routable]
        threads = [threading.Thread(target=fn, args=(nid,),
                                    daemon=True) for nid in live[1:]]
        for t in threads:
            t.start()
        if live:
            fn(live[0])
        for t in threads:
            t.join(timeout_s + 1.0)
        return live

    def _federated_samples(self):
        """Scrape-time metrics federation: every node's own collectors
        re-labeled with ``node`` (bounded label set — node ids come
        from the fleet config), plus a per-node staleness gauge so a
        down node shows as a hole, never as a hang or silence."""
        timeout_s = self.membership.policy.timeout_s or 5.0
        results: Dict[str, Optional[tuple]] = {}

        def fetch(nid: str) -> None:
            t0 = time.perf_counter()
            try:
                resp = self.links[nid].request({"op": "obs.metrics"},
                                               timeout_s)
            except (NodeBusy, *_LINK_FAULTS):
                results[nid] = None
                return
            if not resp.get("ok"):
                results[nid] = None
                return
            results[nid] = (resp.get("samples") or [],
                            time.perf_counter() - t0)

        self._fan_out_nodes(fetch, timeout_s)
        out = []
        for nid in self.membership.all_ids():
            got = results.get(nid)
            stale = got is None
            out.append(("qsm_fleet_node_scrape_stale", "gauge",
                        "1 when the node's metrics could not be "
                        "scraped (down, busy, or unreachable)",
                        {"node": nid}, 1.0 if stale else 0.0))
            if stale:
                continue
            samples, dt = got
            out.append(("qsm_fleet_node_scrape_seconds", "gauge",
                        "last federated scrape round-trip",
                        {"node": nid}, round(dt, 4)))
            for s in samples:
                try:
                    name, mtype, help_, labels, value = s
                    out.append((str(name), str(mtype), str(help_),
                                {**dict(labels), "node": nid},
                                float(value)))
                except (TypeError, ValueError):
                    continue  # one malformed sample, not the scrape
        return out

    def _shed_total(self) -> float:
        adm = self.admission.snapshot()
        with self._lock:
            ha = self.ha_sheds
        return float(adm["shed_queue"] + adm["shed_deadline"] + ha)

    def _on_slo_breach(self, row: dict) -> None:
        self.obs.event("slo.breach", objective=row["objective"],
                       burn=row["burn_rate"], value=row["value"],
                       target=row["target"])

    def health_doc(self, timeout_s: float = 5.0) -> dict:
        """The fleet ``health`` payload: the router's own SLO status
        folded with every node's health answer (parallel, bounded) —
        an unreachable node degrades the fleet, it never hangs the
        op.  Overall status drives `qsm-tpu health`'s exit code."""
        from ..obs import worst_status

        if self.slo is None:
            own = {"status": "ok", "slo": {"configured": False}}
        else:
            doc = self.slo.evaluate()
            own = {"status": doc["status"],
                   "slo": {"configured": True,
                           "window_s": doc["window_s"],
                           "window_actual_s": doc["window_actual_s"],
                           "objectives": doc["objectives"]}}
        fleet: Dict[str, dict] = {}

        def fetch(nid: str) -> None:
            try:
                resp = self.links[nid].request({"op": "health"},
                                               timeout_s)
            except (NodeBusy, *_LINK_FAULTS) as e:
                fleet[nid] = {"status": "unreachable",
                              "error": f"{type(e).__name__}: {e}"[:200]}
                return
            fleet[nid] = ({"status": str(resp.get("status", "ok")),
                           "slo": resp.get("slo")}
                          if resp.get("ok") else
                          {"status": "unreachable",
                           "error": str(resp.get("error"))[:200]})

        live = self._fan_out_nodes(fetch, timeout_s)
        for nid in self.membership.all_ids():
            if nid not in live and nid not in fleet:
                fleet[nid] = {"status": "unreachable",
                              "error": "down (membership)"}
        overall = worst_status(
            [own["status"]] + [n["status"] for n in fleet.values()])
        return {"status": overall, "router": own, "fleet": fleet,
                "role": self.ha_role, "term": self.term,
                "uptime_s": round(time.monotonic() - self._t0, 1)}

    # -- observability -------------------------------------------------
    def node_stats(self, timeout_s: float = 5.0) -> Dict[str, dict]:
        """Best-effort live per-node ``stats`` blocks (down nodes get
        an ``error`` entry — the fleet view must show the hole, not
        hide it).  Nodes the membership already knows are down are
        answered from that knowledge, and the live fetches run in
        parallel: one wedged node must cost the stats op ONE timeout,
        not one per node."""
        out: Dict[str, dict] = {}

        def fetch(nid: str) -> None:
            try:
                resp = self.links[nid].request({"op": "stats"},
                                               timeout_s)
                out[nid] = (resp.get("stats")
                            if resp.get("ok") else
                            {"error": resp.get("error", "bad answer")})
            except (NodeBusy, *_LINK_FAULTS) as e:
                out[nid] = {"error": f"{type(e).__name__}: {e}"[:200]}

        live = self._fan_out_nodes(fetch, timeout_s)
        for nid in self.membership.all_ids():
            if nid not in live:
                out[nid] = {"error": "down (membership)"}
        return out

    def stats(self) -> dict:
        with self._lock:
            counters = {
                "requests": self.requests,
                "histories": self.histories,
                "shrink_requests": self.shrink_requests,
                "node_faults": self.node_faults,
                "node_sheds": self.node_sheds,
                "redispatches": self.redispatches,
                "ladder_batches": self.ladder_batches,
                "ladder_lanes": self.ladder_lanes,
            }
            with self._sessions_lock:
                sessions = {
                    "live": len(self._sessions),
                    "requests": self.session_requests,
                    "replays": self.session_replays,
                    "evicted": self.session_evicted,
                    "ladder": self.session_ladder,
                    "migrated": self.session_migrations,
                    "rehydrated": self.session_rehydrated,
                    "durable": self._session_store is not None,
                    "max_sessions": self.max_sessions,
                    "event_cap": self.session_event_cap,
                }
            ae = {"sweeps": self.ae_sweeps,
                  "segments_shipped": self.ae_segments_shipped,
                  "segments_subsumed": self.ae_segments_subsumed,
                  "rows_shipped": self.ae_rows_shipped,
                  "interval_s": self.anti_entropy_s,
                  "policy": self.ae_policy.name}
            lease = {"enabled": self.lease is not None,
                     "role": self.ha_role,
                     "term": self.term,
                     "holder": self.node_id,
                     "takeovers": self.takeovers,
                     "ha_sheds": self.ha_sheds,
                     "lease_faults": self.lease_faults}
        if self.lease is not None:
            lease["path"] = self.lease.path
            lease["store"] = type(self.lease.store).__name__
            lease["ttl_s"] = self.lease.ttl_s
            if self.ha_role == "active":
                lease["expires_in_s"] = round(
                    self._lease_expires - time.time(), 2)
            else:
                lease["active_term"] = self._observed.get("term")
                lease["active_holder"] = self._observed.get("holder")
        return {
            "address": self.address,
            "role": "router",
            "node": self.node_id,
            "uptime_s": round(time.monotonic() - self._t0, 1),
            "lease": lease,
            **counters,
            # routed monitor sessions: live journals, replays performed
            # on failover, and the journal bounds (docs/MONITOR.md)
            "session": sessions,
            "policy": self.policy.name,
            "admission": self.admission.snapshot(),
            "membership": self.membership.snapshot(),
            "anti_entropy": ae,
            "fleet_nodes": self.node_stats(),
            "obs": self.obs.snapshot(),
            # fleet-wide span collection (obs/collect.py): sweeps,
            # events pulled, gaps and per-node cursor inventory —
            # None unless collect_dir configured collection
            "collect": (self.collector.snapshot()
                        if self.collector is not None else None),
            # the SLO plane (obs/slo.py) — None unless --slo declared
            # objectives for this router
            "slo": (self.slo.snapshot()
                    if self.slo is not None else None),
            "faults": fired_snapshot(),
        }

    def _metric_samples(self):
        """Per-node scrape-time collectors: the fleet's live health and
        traffic, labeled by node id (bounded label set — node ids come
        from the static fleet config)."""
        adm = self.admission.snapshot()
        mem = self.membership.snapshot()
        with self._lock:
            c, g = "counter", "gauge"
            out = [
                ("qsm_fleet_requests_total", c, "router requests", {},
                 float(self.requests)),
                ("qsm_fleet_histories_total", c, "router history lanes",
                 {}, float(self.histories)),
                ("qsm_fleet_node_faults_total", c,
                 "node exchanges lost (death/wedge/partition)", {},
                 float(self.node_faults)),
                ("qsm_fleet_redispatches_total", c,
                 "lane groups moved to another node", {},
                 float(self.redispatches)),
                ("qsm_fleet_ladder_lanes_total", c,
                 "lanes decided on the router's in-process ladder", {},
                 float(self.ladder_lanes)),
                ("qsm_fleet_ae_segments_shipped_total", c,
                 "anti-entropy segments replicated", {},
                 float(self.ae_segments_shipped)),
                ("qsm_fleet_ae_segments_subsumed_total", c,
                 "anti-entropy ships skipped (rows already held)", {},
                 float(self.ae_segments_subsumed)),
                ("qsm_fleet_in_flight", g, "router admitted lanes",
                 {}, float(adm["in_flight"])),
                ("qsm_fleet_lease_term", g,
                 "lease term this router last held", {},
                 float(self.term)),
                ("qsm_fleet_takeovers_total", c,
                 "lease takeovers won by this router", {},
                 float(self.takeovers)),
                ("qsm_fleet_ha_sheds_total", c,
                 "check/shrink refused while not the active router",
                 {}, float(self.ha_sheds)),
            ]
        out.append(("qsm_fleet_active", "gauge",
                    "1 while this router's term is live", {},
                    1.0 if self._active_now() else 0.0))
        out += [
            ("qsm_fleet_node_healthy", "gauge",
             "node health (1 healthy, 0 down/quarantined)",
             {"node": n["node"]},
             1.0 if n["healthy"] and not n["quarantined"] else 0.0)
            for n in mem["nodes"]]
        out += [
            ("qsm_fleet_node_probe_failures_total", "counter",
             "membership probe failures", {"node": n["node"]},
             float(n["failures"])) for n in mem["nodes"]]
        return out
