"""Peer-to-peer anti-entropy — verdict convergence without the router.

PR 12's anti-entropy was hubbed on the router: the router pulled
segment digests from every node and shipped diffs owner→lacker.  That
made the ROUTER a replication single point of failure — kill it and
banked verdicts stop converging, so the rolling-restart guarantee
silently depended on router liveness.  This module moves the exchange
onto the nodes themselves: each node runs a :class:`GossipAgent` that,
once per beat, picks a small RANDOM fan-out of peers and reconciles
replogs directly over the existing ``replog.*`` wire ops:

* ``replog.digests`` — what the peer holds (and has absorbed or
  subsumed: covered either way, never re-shipped);
* ``replog.covers``  — the row-key coverage of segments this node is
  about to pull, checked against the LOCAL live set first: a segment
  whose rows are all already held (a peer's compaction of rows we
  replicated long ago) is recorded as *subsumed* and never shipped —
  the bounded-catch-up half of ISSUE 13;
* ``replog.pull`` / ``replog.push`` — whole-segment transfer,
  fingerprint-verified and idempotent, push gated by the peer's own
  ``replog.subsumed`` answer so the wire never carries rows the
  receiver already has.

Work per beat is bounded three ways: ``fanout`` peers, ``max_segments``
per direction per peer, and the ``gossip``
:data:`~qsm_tpu.resilience.policy.PRESETS` entry's per-exchange
timeout and per-sweep deadline.  Convergence: one exchange merges two
nodes' sealed sets completely (both directions), so a fleet of N
nodes converges in O(diameter) beats — with ``fanout >= peers`` every
node pairs with every other each beat and the fleet converges in at
most 2 beats (tests/test_fleet_ha.py pins the bound).  Peers that
fail an exchange are excluded for the rest of the sweep (the
``tried`` discipline lint family (j) gates) and retried next beat.

Wiring: :class:`~qsm_tpu.serve.server.CheckServer` owns one agent when
started with ``peers=``/``gossip_s=`` (CLI ``serve --peers a,b
--gossip-s 2``), and the ``gossip.peers`` server op (re)configures the
peer set at runtime — ``qsm-tpu fleet`` uses it to wire spawned nodes
whose addresses are only known after their banners.  The router's own
sweep remains as a second, optional reconciliation path; with every
router dead, gossip alone keeps the fleet's banks converging
(tools/bench_fleet.py ``gossip_router_dead`` cell)."""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..resilience.policy import RetryPolicy, preset


class GossipAgent:
    """One node's peer-exchange loop (see module docstring).

    ``peers`` is a sequence of ``(peer_id, address)`` pairs (or bare
    address strings — the address then doubles as the id); the node's
    own id is filtered out so a config listing the whole fleet can be
    handed to every member verbatim."""

    def __init__(self, node_id: str, replog, cache, *,
                 peers: Optional[Sequence] = None,
                 policy: Optional[RetryPolicy] = None,
                 fanout: int = 2,
                 interval_s: float = 2.0,
                 max_segments: int = 16,
                 obs=None,
                 devq=None,
                 rng: Optional[random.Random] = None):
        self.node_id = str(node_id)
        self.replog = replog
        self.cache = cache
        # the device-work queue (qsm_tpu/devq): when this node runs
        # one, every exchange reconciles its segment log too over the
        # devq.* ops — banked work AND done tombstones converge
        # fleet-wide, so any node's window drains everyone's backlog
        self.devq = devq
        self.policy = policy or preset("gossip")
        self.fanout = max(1, int(fanout))
        self.interval_s = float(interval_s)
        self.max_segments = max(1, int(max_segments))
        self._obs = obs
        # entropy-seeded by default (decorrelating peer choice across
        # the fleet is the point); tests inject a seeded rng
        self._rng = rng if rng is not None else random.Random()
        self._links: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sweeps = 0
        self.exchanges = 0           # peer exchanges completed
        self.peer_faults = 0         # peer exchanges lost
        self.segments_pulled = 0
        self.segments_pushed = 0
        self.segments_subsumed = 0   # ships skipped: rows already held
        self.rows_pulled = 0
        self.devq_pulled = 0         # devq segments adopted from peers
        self.devq_pushed = 0         # devq segments shipped to peers
        if peers:
            self.set_peers(peers)

    # -- peer set ------------------------------------------------------
    def set_peers(self, peers: Sequence) -> List[str]:
        """Replace the peer set (idempotent; self excluded).  Returns
        the resulting peer ids."""
        from .router import NodeLink

        pairs: List[Tuple[str, str]] = []
        for p in peers:
            if isinstance(p, str):
                pairs.append((p, p))
            else:
                pid, addr = p
                pairs.append((str(pid), str(addr)))
        with self._lock:
            old = self._links
            self._links = {
                pid: (old.get(pid)
                      if old.get(pid) is not None
                      and old[pid].address == addr
                      else NodeLink(pid, addr))
                for pid, addr in pairs if pid != self.node_id}
            for pid, link in old.items():
                if pid not in self._links:
                    link.close_all()
            return sorted(self._links)

    def peer_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._links)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "GossipAgent":
        """Idempotent: spawns the beat thread iff the interval is
        positive and no live thread exists — callable again after a
        ``gossip.peers`` op raises the interval on an agent that was
        created dormant (interval 0)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        if self.interval_s and self.interval_s > 0 \
                and not self._stop.is_set():
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="qsm-gossip")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
        with self._lock:
            links = list(self._links.values())
        for link in links:
            link.close_all()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 — the beat must survive
                continue

    # -- one beat ------------------------------------------------------
    def sweep(self) -> dict:
        """One reconciliation beat: exchange with ``fanout`` random
        peers, both directions, bounded per the gossip preset.  Public
        so tests and the bench drive convergence synchronously."""
        import time as _time

        from .router import NodeBusy, _LINK_FAULTS

        deadline = _time.monotonic() + (self.policy.deadline_s or 30.0)
        with self._lock:
            ids = sorted(self._links)
        if not ids:
            return {"peers": 0, "pulled": 0, "pushed": 0, "subsumed": 0}
        pick = (list(ids) if len(ids) <= self.fanout
                else self._rng.sample(ids, self.fanout))
        pulled = pushed = subsumed = rows = 0
        exchanged = faults = 0
        tried: Set[str] = set()
        for pid in pick:
            if self._stop.is_set() or _time.monotonic() >= deadline:
                break
            if pid in tried:
                continue
            tried.add(pid)
            with self._lock:
                link = self._links.get(pid)
            if link is None:
                continue
            try:
                got = self._exchange(link, deadline)
            except NodeBusy:
                continue       # backpressure: next beat
            except _LINK_FAULTS:
                faults += 1    # excluded via tried; retried next beat
                continue
            pulled += got[0]
            pushed += got[1]
            subsumed += got[2]
            rows += got[3]
            exchanged += 1
        # counters shared with stats() readers on connection threads
        with self._lock:
            self.sweeps += 1
            self.exchanges += exchanged
            self.peer_faults += faults
            self.segments_pulled += pulled
            self.segments_pushed += pushed
            self.segments_subsumed += subsumed
            self.rows_pulled += rows
        if (pulled or pushed or subsumed) and self._obs is not None \
                and self._obs.on:
            self._obs.event("fleet.gossip", node=self.node_id,
                            peers=len(tried), pulled=pulled,
                            pushed=pushed, subsumed=subsumed,
                            rows=rows)
        return {"peers": len(tried), "pulled": pulled, "pushed": pushed,
                "subsumed": subsumed, "rows": rows}

    def _exchange(self, link, deadline: float) -> Tuple[int, int, int, int]:
        """Both directions with ONE peer: pull what we lack (subsuming
        segments whose rows we already hold), push what it lacks
        (gated by its own subsumption answer)."""
        import time as _time

        def t() -> float:
            return max(0.5, min(self.policy.timeout_s or 10.0,
                                deadline - _time.monotonic()))

        resp = link.request({"op": "replog.digests"}, t())
        if not resp.get("ok"):
            return 0, 0, 0, 0
        theirs = dict(resp.get("digests") or {})
        their_cov = dict(resp.get("absorbed") or {})
        pulled = pushed = subsumed = rows = 0

        # pull leg — coverage-checked before any row line moves
        want = self.replog.missing(theirs)[:self.max_segments]
        to_pull: List[str] = []
        if want:
            cov = link.request({"op": "replog.covers",
                                "segments": want}, t())
            covers = {c.get("name"): c
                      for c in (cov.get("covers") or [])}
            for name in want:
                c = covers.get(name)
                keys = list((c or {}).get("keys") or [])
                if c is not None and keys \
                        and self.cache.holds_all(keys):
                    if self.replog.note_subsumed(
                            name, str(c.get("fingerprint", ""))):
                        subsumed += 1
                        continue
                to_pull.append(name)
        if to_pull:
            got = link.request({"op": "replog.pull",
                                "segments": to_pull}, t())
            for seg in got.get("segments") or []:
                try:
                    adopted = self.replog.adopt(
                        str(seg.get("name")),
                        str(seg.get("fingerprint")),
                        list(seg.get("lines") or []))
                except (ValueError, OSError):
                    continue  # a bad payload is skipped, never adopted
                if adopted:
                    pulled += 1
                    rows += self.cache.adopt_rows(adopted)

        # push leg — the peer's own live set decides subsumption
        mine = self.replog.digests()
        lack = [n for n in sorted(mine)
                if n not in theirs and n not in their_cov]
        for name in lack[:self.max_segments]:
            if _time.monotonic() >= deadline:
                break
            got = self.replog.read_segment(name)
            if got is None:
                continue
            fp, lines = got
            keys = self.replog.row_keys_of(lines)  # one read, reused
            sub = link.request({"op": "replog.subsumed", "name": name,
                                "fingerprint": fp, "keys": keys}, t())
            if sub.get("subsumed"):
                subsumed += 1
                continue
            ack = link.request(
                {"op": "replog.push",
                 "segments": [{"name": name, "fingerprint": fp,
                               "lines": lines}]}, t())
            pushed += int(ack.get("adopted", 0))

        # devq leg (qsm_tpu/devq): same digest→pull shape over the
        # queue's own segment log, push via idempotent devq.put/
        # drain_report row payloads (item keys dedupe, done absorbs).
        if self.devq is not None:
            dq_pulled, dq_pushed = self._exchange_devq(link, t, deadline)
            with self._lock:
                self.devq_pulled += dq_pulled
                self.devq_pushed += dq_pushed
        return pulled, pushed, subsumed, rows

    def _exchange_devq(self, link, t, deadline: float) -> Tuple[int, int]:
        """Reconcile the device-work queue's segment log with one peer:
        pull devq segments we lack (fingerprint-verified adopt folds
        items/tombstones into the live queue), then push the ones the
        peer lacks via ``devq.put`` of their row payloads — put dedupes
        by item key, so the push is idempotent.  A peer that runs no
        devq answers an error; skipped, not a fault."""
        import time as _time

        resp = link.request({"op": "devq.digests"}, t())
        if not resp.get("ok"):
            return 0, 0
        theirs = dict(resp.get("digests") or {})
        pulled = pushed = 0
        want = self.devq.missing(theirs)[:self.max_segments]
        if want:
            got = link.request({"op": "devq.pull",
                                "segments": want}, t())
            for seg in got.get("segments") or []:
                try:
                    if self.devq.adopt(str(seg.get("name")),
                                       str(seg.get("fingerprint")),
                                       list(seg.get("lines") or [])):
                        pulled += 1
                except (ValueError, OSError):
                    continue
        mine = self.devq.digests()
        lack = [n for n in sorted(mine) if n not in theirs]
        for name in lack[:self.max_segments]:
            if _time.monotonic() >= deadline:
                break
            try:
                fp, lines = self.devq.read_segment(name)
            except (KeyError, TypeError):
                continue
            if lines is None:
                continue
            import json as _json

            items, done = [], []
            for line in lines:
                try:
                    row = _json.loads(line)
                except ValueError:
                    continue
                if row.get("done"):
                    done.append(str(row.get("key")))
                elif isinstance(row.get("item"), dict):
                    items.append(row["item"])
            if items:
                ack = link.request({"op": "devq.put", "items": items},
                                   t())
                pushed += int(ack.get("banked", 0) or 0)
            if done:
                link.request({"op": "devq.drain_report", "done": done},
                             t())
        return pulled, pushed

    # -- observability -------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"node": self.node_id, "peers": sorted(self._links),
                    "fanout": self.fanout,
                    "interval_s": self.interval_s,
                    "sweeps": self.sweeps, "exchanges": self.exchanges,
                    "peer_faults": self.peer_faults,
                    "segments_pulled": self.segments_pulled,
                    "segments_pushed": self.segments_pushed,
                    "segments_subsumed": self.segments_subsumed,
                    "rows_pulled": self.rows_pulled,
                    "devq_pulled": self.devq_pulled,
                    "devq_pushed": self.devq_pushed,
                    "policy": self.policy.name}
