"""Mesh-sharded dispatch substrate: ONE NamedSharding lane axis under every
check plane (plain batches, pcomp sub-lanes, shrink frontiers, monitor
re-checks, serve fan-out).  Topology (construction + the sharding contract)
in :mod:`.topology`; dispatch policy (divisible bucket ladders, the one-call
:func:`sharded_backend`) in :mod:`.dispatch`.  docs/MESH.md is the prose
contract; ``qsm_tpu.parallel`` is the deprecated former home.
"""

from .dispatch import (backend_sharding, mesh_bucket_ladder,
                       mesh_slots_table, sharded_backend)
from .topology import (LANE_AXIS, batch_sharding, init_distributed,
                       lane_sharding_of, make_mesh, make_mesh_2d,
                       mesh_device_count, mesh_from_devices,
                       mesh_shape_key, replicated_sharding)

__all__ = [
    "LANE_AXIS",
    "backend_sharding",
    "batch_sharding",
    "init_distributed",
    "lane_sharding_of",
    "make_mesh",
    "make_mesh_2d",
    "mesh_bucket_ladder",
    "mesh_device_count",
    "mesh_from_devices",
    "mesh_shape_key",
    "mesh_slots_table",
    "replicated_sharding",
    "sharded_backend",
]
