"""Mesh construction + the batch-axis sharding contract, in ONE place.

The reference's distribution story is actor messaging (distributed-process
over network-transport-*, SURVEY.md §5 comm backend); its checker is pure and
single-threaded.  Our checker plane instead scales the *batch axis* of the
linearisation kernel over a ``jax.sharding.Mesh``: trials, per-key
sub-histories, shrink candidates, and monitor frontier re-checks are
independent (SURVEY.md §2b "trial/batch parallelism"), so the natural mapping
is data parallelism — shard histories over devices, replicate the (tiny) spec
state, and let XLA place everything with zero collectives in the hot loop
(verdict gather rides the ICI at the end of the batch).

Single chip needs none of this; the helpers here exist so the SAME kernel
runs unchanged from v5e-1 to a full pod slice: ``pjit``-style sharding comes
entirely from ``NamedSharding`` annotations on the inputs.

This module is the promotion of the dormant ``qsm_tpu/parallel/mesh.py``
(which is now a deprecation re-export): construction (:func:`make_mesh`,
:func:`make_mesh_2d`, :func:`init_distributed`), placement
(:func:`batch_sharding`, :func:`replicated_sharding`,
:func:`lane_sharding_of`), and identity (:func:`mesh_device_count`,
:func:`mesh_shape_key` — what compile-bucket keys must include so a 1-chip
executable never serves an 8-chip mesh).  docs/MESH.md is the prose contract.
"""

from __future__ import annotations

from typing import Optional, Sequence

#: The canonical name of the lane (history-batch) axis on 1-D meshes.
LANE_AXIS = "batch"


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Multi-host entry: initialize ``jax.distributed`` when configured.

    The reference scales out via Cloud Haskell actor messaging over TCP
    (SURVEY.md §5 comm backend); our checker plane scales out via JAX's
    multi-process runtime instead — each host runs this same program,
    ``jax.devices()`` then spans ALL hosts, and the batch axis shards over a
    (host, device) mesh with DCN between hosts and ICI within (the hot loop
    is collective-free, so DCN only carries the final verdict gather).

    Reads ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` when args are omitted; returns False (no-op) when
    unset, so single-host runs need no configuration.  Exercised for real
    (2 OS processes, localhost coordinator, CPU platform, sharded kernel
    over the global mesh) by tests/test_distributed.py; the same program
    shape on a TPU pod replaces localhost TCP with DCN.
    ``__graft_entry__.dryrun_multichip`` additionally validates the 2-D
    (host, device) mesh sharding single-process.
    """
    import os

    import jax

    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if not coordinator_address:
        return False
    # explicit args win over env even when falsy: process_id=0 IS the
    # coordinator's valid rank, `or` would silently hand it the env value
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes),
        process_id=int(process_id))
    return True


def make_mesh(n_devices: Optional[int] = None, axis: str = LANE_AXIS):
    """A 1-D device mesh over the first ``n_devices`` devices (all by
    default).  The single axis is the history-batch (lane) axis."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.asarray(devs), (axis,))


def mesh_from_devices(devices: Sequence, axis: str = LANE_AXIS):
    """A 1-D lane mesh over an EXPLICIT device list — the constructor a
    live seized window needs.

    ``make_mesh(n)`` slices ``jax.devices()[:n]``: correct when the
    caller owns the whole process, wrong for a window drain, where the
    devices that actually answered the probe are the only ones safe to
    schedule on (a snatched-away chip must not be in the mesh at all).
    The drain scheduler therefore derives its mesh from the window's
    probed device SET, never from a forced count (ISSUE 20 bugfix;
    pinned by tests/test_mesh.py::test_mesh_from_devices_*).

    Accepts jax Device objects (preserved in order, duplicates refused —
    a mesh with one chip twice would double-count lanes silently)."""
    import numpy as np
    from jax.sharding import Mesh

    devs = list(devices)
    if not devs:
        raise ValueError("mesh_from_devices: empty device set "
                         "(a window with no probed devices has no mesh)")
    if len({id(d) for d in devs}) != len(devs):
        raise ValueError("mesh_from_devices: duplicate devices")
    return Mesh(np.asarray(devs), (axis,))


def make_mesh_2d(n_hosts: int, per_host: int,
                 axes: Sequence[str] = ("host", LANE_AXIS)):
    """A (host, device) mesh: dim 0 maps hosts (DCN between real hosts),
    dim 1 the devices within a host (ICI).  Works identically over virtual
    CPU devices, which is how the dryrun validates the multi-host program
    shape without a pod."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    need = n_hosts * per_host
    if len(devs) < need:
        raise ValueError(f"requested {n_hosts}x{per_host} devices, "
                         f"have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(n_hosts, per_host),
                tuple(axes))


def batch_sharding(mesh, axis: Optional[str] = None):
    """NamedSharding placing dim 0 (the batch) over the mesh axis — or over
    ALL mesh axes for a multi-axis (host, device) mesh: the batch divides
    into n_hosts x per_host shards, hierarchically."""
    import jax
    from jax.sharding import PartitionSpec as P

    if axis is None and len(mesh.axis_names) > 1:
        return jax.NamedSharding(mesh, P(tuple(mesh.axis_names)))
    return jax.NamedSharding(mesh, P(axis or mesh.axis_names[0]))


def replicated_sharding(mesh):
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.NamedSharding(mesh, P())


def lane_sharding_of(sharding):
    """THE lane-axis derivation: the NamedSharding that places dim 0 of a
    batch-leading array the same way ``sharding`` places its dim 0.

    Every sharded dispatch site (kernel args, the carry, compaction
    outputs) needs exactly this — the mesh and first-dim placement of the
    caller's sharding, regardless of what trailing dims that sharding also
    names.  Before this helper existed the derivation lived as two
    near-identical blocks inside ``ops/jax_kernel.py``; one definition
    means one place to extend when the lane axis ever becomes 2-D
    (host, device)."""
    import jax
    from jax.sharding import PartitionSpec as P

    axis = sharding.spec[0] if sharding.spec else None
    return jax.NamedSharding(sharding.mesh, P(axis))


def mesh_device_count(obj=None) -> int:
    """Device count under a ``Mesh``, a ``NamedSharding``, or None (= the
    process-global ``jax.device_count()``).  This is the number that must
    appear in every compile-bucket identity (:func:`mesh_shape_key`) and
    that batch widths must divide by (``qsm_tpu.mesh.dispatch``)."""
    if obj is None:
        import jax

        return jax.device_count()
    mesh = getattr(obj, "mesh", obj)  # NamedSharding -> its mesh
    size = getattr(mesh, "size", None)
    return int(size) if size is not None else len(mesh.devices.flat)


def mesh_shape_key(sharding) -> tuple:
    """Hashable identity of a sharding's mesh SHAPE for compile caches:
    ``(device_count, axis_names...)`` — or ``(1,)`` for unsharded.

    Why device_count and not just the axis names: two meshes named
    ("batch",) over 1 vs 8 chips produce executables with different SPMD
    partitioning; a cache keyed without the count would serve the 1-chip
    executable to the 8-chip mesh (ISSUE 19's bucket-identity clause).
    Axis names ride along so a flat ("batch",) mesh and a ("host",
    "batch") mesh of equal size never collide either."""
    if sharding is None:
        return (1,)
    mesh = sharding.mesh
    return (mesh_device_count(mesh),) + tuple(mesh.axis_names)
