"""Mesh-sharded dispatch: divisible bucket ladders + the one-call backend.

The chunked kernel driver (ops/jax_kernel.py) pads every batch into a bucket
ladder and compiles one executable per (n_ops, bucket, slots, chunk, unroll)
shape.  Under a mesh the lane axis of every bucket must divide by the device
count — an uneven bucket leaves devices holding ragged shards and XLA falls
back to slower non-uniform partitioning — and the compile cache must key on
the mesh shape (:func:`qsm_tpu.mesh.topology.mesh_shape_key`) so a 1-chip
executable never serves an 8-chip mesh.  This module owns both policies:

* :func:`mesh_bucket_ladder` / :func:`mesh_slots_table` — restrict a plan's
  bucket ladder (and its per-bucket memo-slot caps) to mesh-divisible widths.
* :func:`sharded_backend` — the one-call constructor every consumer rides:
  plain check batches, pcomp per-key sub-lanes, shrink frontiers, monitor
  frontier re-checks, and the serve dispatch all take the backend this
  returns (a planner-built engine whose ``sharding`` spans the mesh).

Soundness contract: sharding is ONLY a placement change.  Verdicts and
witnesses are bit-identical across mesh shapes (tests/test_mesh.py pins
1x/2x/8x on every registered family, pcomp + shrink + monitor included).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from .topology import batch_sharding, make_mesh, mesh_device_count


def mesh_bucket_ladder(buckets: Sequence[int],
                       n_devices: int) -> Tuple[int, ...]:
    """Restrict a batch-bucket ladder to widths divisible by the mesh.

    Keeps the ladder's shape (ascending, deduped) and guarantees a
    non-empty result: when every bucket is narrower than the mesh the
    ladder collapses to ``(n_devices,)`` — one lane per device is the
    narrowest batch a mesh can hold evenly.  ``n_devices <= 1`` is the
    identity (unsharded callers never pay a ladder change)."""
    n = max(1, int(n_devices))
    if n == 1:
        return tuple(buckets)
    kept = tuple(b for b in buckets if b % n == 0)
    return kept or (n,)


def mesh_slots_table(slots_for_batch: Dict[int, int],
                     buckets: Sequence[int]) -> Dict[int, int]:
    """Per-bucket memo-slot caps for a (possibly filtered) ladder: known
    buckets keep their cap, new ones (the ``(n_devices,)`` collapse case)
    get the driver's default of 32 (``JaxTPU._slots_for``)."""
    return {b: slots_for_batch.get(b, 32) for b in buckets}


def sharded_backend(spec, *, devices: Optional[int] = None, mesh=None,
                    budget: int = 2_000, profile=None, plan=None,
                    **device_kw):
    """Planner-built check backend whose lane axis spans a mesh.

    The ONE constructor for mesh-sharded dispatch: builds (or takes) the
    mesh, derives the batch-axis :func:`~qsm_tpu.mesh.topology
    .batch_sharding`, plans with mesh-divisible buckets
    (``plan_search(mesh_devices=...)``), and hands both to
    ``search.planner.build_backend`` — so pcomp key-splitting, SegDC
    segmentation, ordering, and every other plan decision compose with
    sharding instead of each consumer re-deriving placement.

    ``devices=None`` with ``mesh=None`` spans all addressable devices
    (``jax.device_count()``); pass ``devices=1`` for an explicitly
    single-device backend (parity baselines).  Extra ``device_kw``
    forwards to the engine constructor exactly as ``build_backend`` does.
    Returns the backend; the mesh is reachable via
    ``backend_sharding(backend).mesh`` when introspection is needed.
    """
    from ..search.planner import build_backend, plan_search

    if mesh is None:
        mesh = make_mesh(devices)
    n = mesh_device_count(mesh)
    if plan is None:
        plan = plan_search(spec, profile=profile, mesh_devices=n)
    sharding = batch_sharding(mesh) if n > 1 else None
    return build_backend(spec, plan, budget=budget, sharding=sharding,
                         **device_kw)


def backend_sharding(backend):
    """The NamedSharding a (possibly combinator-wrapped) backend dispatches
    under, or None.  Unwraps pcomp/segdc layers via their ``inner``
    attribute — combinators delegate dispatch, so the innermost engine
    owns placement."""
    seen = set()
    while backend is not None and id(backend) not in seen:
        seen.add(id(backend))
        sh = getattr(backend, "sharding", None)
        if sh is not None:
            return sh
        backend = getattr(backend, "inner", None)
    return None
