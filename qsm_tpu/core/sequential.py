"""Sequential execution path — the reference's ``prop_sequential`` analogue.

Runs a generated program one command at a time against a sequential SUT,
checking ``precondition → execute → postcondition → transition`` at every
step (SURVEY.md §3.4).  No scheduler, no lineariser; this is milestone M1 and
stays the debugging baseline for every spec/SUT pair.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional, Protocol

from .generator import (Program, dedupe, generate_program,
                        shrink_candidates)
from .history import History, Op
from .spec import Spec


class SequentialSUT(Protocol):
    """A system under test driven one atomic command at a time."""

    def reset(self) -> None: ...
    def apply(self, cmd: int, arg: int) -> int: ...


class ModelSUT:
    """The spec's own model run as an SUT (always linearisable by
    construction) — used to validate specs and the checker itself."""

    def __init__(self, spec: Spec):
        self.spec = spec
        self.reset()

    def reset(self) -> None:
        self.state = [int(v) for v in self.spec.initial_state()]

    def apply(self, cmd: int, arg: int) -> int:
        for resp in self.spec.resp_domain(cmd):
            new_state, ok = self.spec.step_py(list(self.state), cmd, arg, resp)
            if ok:
                self.state = [int(v) for v in new_state]
                return resp
        raise AssertionError(
            f"model has no valid response for cmd={cmd} arg={arg} "
            f"state={self.state}")


@dataclasses.dataclass
class SequentialResult:
    ok: bool
    history: History
    failed_at: Optional[int] = None  # index of first postcondition failure


def run_sequential(spec: Spec, sut: SequentialSUT, program: Program
                   ) -> SequentialResult:
    """Execute ``program`` sequentially; verify each response against the
    model inline.  Returns the (sequential) history for regression dumps."""
    sut.reset()
    state = [int(v) for v in spec.initial_state()]
    t = 0
    ops = []
    for idx, op in enumerate(program.ops):
        resp = sut.apply(op.cmd, op.arg)
        ops.append(Op(pid=op.pid, cmd=op.cmd, arg=op.arg, resp=resp,
                      invoke_time=t, response_time=t + 1))
        t += 2
        new_state, ok = spec.step_py(state, op.cmd, op.arg, resp)
        if not ok:
            return SequentialResult(False, History(ops), failed_at=idx)
        state = [int(v) for v in new_state]
    return SequentialResult(True, History(ops))


@dataclasses.dataclass
class SequentialPropertyResult:
    ok: bool
    trials_run: int
    counterexample: Optional[Program] = None
    history: Optional[History] = None
    failed_at: Optional[int] = None
    trial_seed: Optional[str] = None  # replay key, same contract as the
    # concurrent Counterexample.trial_seed: regenerate the program with
    # random.Random(key).randrange(1 << 62)
    shrink_steps: int = 0


def _shrink_sequential(spec: Spec, sut: SequentialSUT, program: Program,
                       result: SequentialResult, rounds: int = 200):
    """Greedy QC-style shrink for the sequential property: re-run each
    candidate (sequential execution is cheap) and step to the first one
    still failing.  ``result`` is the caller's already-failing run of
    ``program`` (no redundant re-execution)."""
    steps = 0
    for _ in range(rounds):
        nxt = None
        for cand in dedupe(shrink_candidates(spec, program), 256):
            res = run_sequential(spec, sut, cand)
            if not res.ok:
                nxt = (cand, res)
                break
        if nxt is None:
            break
        program, result = nxt
        steps += 1
    return program, result, steps


def prop_sequential(spec: Spec, sut: SequentialSUT, n_trials: int = 100,
                    n_pids: int = 1, max_ops: int = 12, seed: int = 0
                    ) -> SequentialPropertyResult:
    """The reference's ``prop_sequential`` (SURVEY.md §3.4): generate →
    run sequentially with inline postcondition checks → shrink failures.
    Deterministic from ``seed``; no scheduler, no lineariser.  Seed keys
    come from the SAME per-trial derivation as the concurrent property —
    but programs only coincide across the two paths when the op counts
    do: the concurrent property RAMPS sizes over the trial sequence by
    default (``PropertyConfig.ramp_sizes``) while this path uses
    ``max_ops`` throughout, so cross-referencing a trial seed between
    the two replays the same generator stream at possibly different
    lengths."""
    # function-local: property.py sits above this module in the layer
    # order (it imports sched/ops); a module-level import would invert it
    from .property import trial_seed

    for t in range(n_trials):
        key = trial_seed(seed, t)
        prog = generate_program(
            spec, seed=random.Random(key).randrange(1 << 62),
            n_pids=n_pids, max_ops=max_ops)
        res = run_sequential(spec, sut, prog)
        if not res.ok:
            mp, mres, steps = _shrink_sequential(spec, sut, prog, res)
            return SequentialPropertyResult(
                ok=False, trials_run=t + 1, counterexample=mp,
                history=mres.history, failed_at=mres.failed_at,
                trial_seed=key, shrink_steps=steps)
    return SequentialPropertyResult(ok=True, trials_run=n_trials)
