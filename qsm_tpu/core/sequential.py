"""Sequential execution path — the reference's ``prop_sequential`` analogue.

Runs a generated program one command at a time against a sequential SUT,
checking ``precondition → execute → postcondition → transition`` at every
step (SURVEY.md §3.4).  No scheduler, no lineariser; this is milestone M1 and
stays the debugging baseline for every spec/SUT pair.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Protocol

from .generator import Program
from .history import History, Op
from .spec import Spec


class SequentialSUT(Protocol):
    """A system under test driven one atomic command at a time."""

    def reset(self) -> None: ...
    def apply(self, cmd: int, arg: int) -> int: ...


class ModelSUT:
    """The spec's own model run as an SUT (always linearisable by
    construction) — used to validate specs and the checker itself."""

    def __init__(self, spec: Spec):
        self.spec = spec
        self.reset()

    def reset(self) -> None:
        self.state = [int(v) for v in self.spec.initial_state()]

    def apply(self, cmd: int, arg: int) -> int:
        for resp in self.spec.resp_domain(cmd):
            new_state, ok = self.spec.step_py(list(self.state), cmd, arg, resp)
            if ok:
                self.state = [int(v) for v in new_state]
                return resp
        raise AssertionError(
            f"model has no valid response for cmd={cmd} arg={arg} "
            f"state={self.state}")


@dataclasses.dataclass
class SequentialResult:
    ok: bool
    history: History
    failed_at: Optional[int] = None  # index of first postcondition failure


def run_sequential(spec: Spec, sut: SequentialSUT, program: Program
                   ) -> SequentialResult:
    """Execute ``program`` sequentially; verify each response against the
    model inline.  Returns the (sequential) history for regression dumps."""
    sut.reset()
    state = [int(v) for v in spec.initial_state()]
    t = 0
    ops = []
    for idx, op in enumerate(program.ops):
        resp = sut.apply(op.cmd, op.arg)
        ops.append(Op(pid=op.pid, cmd=op.cmd, arg=op.arg, resp=resp,
                      invoke_time=t, response_time=t + 1))
        t += 2
        new_state, ok = spec.step_py(state, op.cmd, op.arg, resp)
        if not ok:
            return SequentialResult(False, History(ops), failed_at=idx)
        state = [int(v) for v in new_state]
    return SequentialResult(True, History(ops))
