"""Seeded program generation and shrinking.

The reference generates random command sequences with QuickCheck's ``Gen`` and
minimizes failures with ``shrink`` — dropping/simplifying commands and
re-checking, which produces the "thousands of shrunk histories" workload the
TPU kernel batches (SURVEY.md §2 Generator/shrinker, §3.5; BASELINE.json:5).

A *program* here is a prefix-free parallel program: every op is assigned to a
pid, and each pid executes its ops in order.  All nondeterminism flows from an
explicit seed, so (seed, config) reproduces any program exactly — the
determinism contract shrinking soundness depends on (SURVEY.md §7 hard-parts
#4).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Iterator, List, Tuple

from .spec import Spec


@dataclasses.dataclass(frozen=True)
class ProgOp:
    """One generated command, assigned to a logical process."""

    pid: int
    cmd: int
    arg: int


@dataclasses.dataclass(frozen=True)
class Program:
    """A parallel program: ops in global generation order; per-pid order is
    the subsequence with that pid."""

    ops: Tuple[ProgOp, ...]
    n_pids: int

    def per_pid(self) -> List[List[ProgOp]]:
        out: List[List[ProgOp]] = [[] for _ in range(self.n_pids)]
        for op in self.ops:
            out[op.pid].append(op)
        return out

    def __len__(self) -> int:
        return len(self.ops)


def generate_program(
    spec: Spec, seed: int, n_pids: int, max_ops: int, min_ops: int = 1
) -> Program:
    """Seeded, precondition-respecting program generation.

    Commands come from ``spec.gen_cmd`` (uniform by default); sizes ramp the
    way QuickCheck sizes do — smaller programs early in a trial sequence are
    handled by the caller passing a smaller ``max_ops``.
    """
    rng = random.Random(seed)
    n_ops = rng.randint(min_ops, max_ops)
    ops = []
    # Track an approximate model state so preconditions can be respected:
    # advance with the model's first valid response, the way the reference
    # generates against the advancing model (SURVEY.md §3.4).  For concurrent
    # programs this is heuristic (the real interleaving differs), which is
    # why preconditions must be *generation-time* restrictions only.
    state = [int(v) for v in spec.initial_state()]
    for _ in range(n_ops):
        pid = rng.randrange(n_pids)
        cmd, arg = spec.gen_cmd(rng, state)
        ops.append(ProgOp(pid=pid, cmd=cmd, arg=arg))
        for resp in spec.resp_domain(cmd):
            new_state, ok = spec.step_py(list(state), cmd, arg, resp)
            if ok:
                state = [int(v) for v in new_state]
                break
    return Program(ops=tuple(ops), n_pids=n_pids)


# ---------------------------------------------------------------------------
# Shrinking
# ---------------------------------------------------------------------------

def shrink_candidates(spec: Spec, prog: Program) -> Iterator[Program]:
    """Yield smaller candidate programs, most-aggressive first.

    Mirrors QuickCheck's list shrinking: drop halves, then single ops, then
    shrink individual args toward zero (SURVEY.md §3.5).  Candidates preserve
    per-pid ordering of the surviving ops.  Deduplication/ordering is the
    caller's concern; this is a pure enumeration.
    """
    ops = list(prog.ops)
    n = len(ops)
    # 1. drop contiguous chunks (halving sizes, like QC's shrinkList)
    k = n // 2
    while k >= 1:
        for start in range(0, n - k + 1, k):
            rest = ops[:start] + ops[start + k:]
            if rest:
                yield Program(tuple(rest), prog.n_pids)
        k //= 2
    # 2. shrink individual args
    for i, op in enumerate(ops):
        for smaller in spec.shrink_arg(op.cmd, op.arg):
            cand = list(ops)
            cand[i] = ProgOp(op.pid, op.cmd, smaller)
            yield Program(tuple(cand), prog.n_pids)
    # 3. move ops onto fewer pids (pid renumber toward 0)
    used = sorted({op.pid for op in ops})
    if len(used) > 1:
        drop = used[-1]
        cand = [ProgOp(0 if op.pid == drop else op.pid, op.cmd, op.arg)
                for op in ops]
        yield Program(tuple(cand), prog.n_pids)


def dedupe(programs: Iterator[Program], limit: int) -> List[Program]:
    """Collect up to ``limit`` distinct candidates preserving order."""
    seen = set()
    out = []
    for p in programs:
        key = (p.n_pids, p.ops)
        if key in seen:
            continue
        seen.add(key)
        out.append(p)
        if len(out) >= limit:
            break
    return out
