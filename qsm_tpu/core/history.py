"""Histories: invocation/response event sequences and their array encoding.

The reference collects a ``History cmd resp`` — a sequence of per-pid
invocation and response events — from ``runCommands`` and feeds it to the
lineariser (SURVEY.md §0 items 3-4, names anchored on BASELINE.json:5).

TPU-first redesign: a history is encoded to **fixed-shape int arrays** so that
thousands of histories batch into one device call (BASELINE.json:9):

    ops[B, N, 4]      = (pid, cmd, arg, resp) per operation
    interval[B, N, 2] = (invoke_time, response_time) logical timestamps
    valid[B, N]       = operation exists (histories are ragged; N is a bucket)
    pending[B, N]     = invoked but never responded (crash/fault injection);
                        the checker may prune or complete these (SURVEY.md §3.2)

``N`` (MAX_OPS) is bucketed to ``OP_BUCKETS`` below (12…128; 96/128 go
past the largest milestone config) to bound XLA recompilation
(BASELINE.json:7-11).

The real-time precedence partial order needed by Wing-Gong is derived, not
stored: op *i* precedes op *j* iff ``response_time[i] < invoke_time[j]``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

# 96/128 extend PAST the reference's largest config (64×16 —
# BASELINE.json:11): the device kernel and the host oracles take any
# bucket; the native C++ checker's 64-bit taken mask caps at 64 and
# routes longer histories to the Python oracle (qsm_tpu/native/oracle.py)
OP_BUCKETS = (12, 24, 32, 48, 64, 96, 128)

# Sentinel response for pending operations (no response observed).
NO_RESP = -1


@dataclasses.dataclass(frozen=True)
class Op:
    """One completed or pending operation in a concurrent history."""

    pid: int
    cmd: int
    arg: int
    resp: int  # NO_RESP if pending
    invoke_time: int
    response_time: int  # large sentinel (>= any time) if pending

    @property
    def is_pending(self) -> bool:
        return self.resp == NO_RESP


@dataclasses.dataclass
class History:
    """A single concurrent history plus its provenance.

    ``ops`` are in invocation order.  ``seed`` / ``program_id`` make every
    failure replayable from (seed, config) alone — the reference's
    checkpoint/resume philosophy (SURVEY.md §5).
    """

    ops: List[Op]
    seed: Optional[int] = None
    program_id: Optional[int] = None

    def __len__(self) -> int:
        return len(self.ops)

    @property
    def n_pending(self) -> int:
        return sum(1 for o in self.ops if o.is_pending)

    def completed(self) -> "History":
        """Drop pending ops (prune-all completion)."""
        return History([o for o in self.ops if not o.is_pending],
                       seed=self.seed, program_id=self.program_id)

    def fingerprint(self) -> tuple:
        """Hashable identity of the observable history (one canonical site:
        distinct-schedule counting, replay bit-identity checks, and tests
        all compare THIS, so an Op field added later changes them together).
        """
        return tuple((o.pid, o.cmd, o.arg, o.resp, o.invoke_time,
                      o.response_time) for o in self.ops)

    def subhistory(self, keep: Sequence[int]) -> "History":
        """The history restricted to op indices ``keep`` (sorted, original
        timestamps preserved).  Dropping ops can only RELAX the real-time
        precedence partial order on the survivors — the shrink plane's
        op-subset candidates (qsm_tpu/shrink) are built from exactly this,
        so a candidate's constraints are always a sub-order of the
        original's."""
        idx = sorted(set(keep))
        return History([self.ops[i] for i in idx], seed=self.seed,
                       program_id=self.program_id)

    def precedes_matrix(self) -> np.ndarray:
        """bool[n, n]: strict real-time precedence (resp_i < inv_j)."""
        n = len(self.ops)
        inv = np.array([o.invoke_time for o in self.ops], np.int64)
        ret = np.array([o.response_time for o in self.ops], np.int64)
        pend = np.array([o.is_pending for o in self.ops], bool)
        # A pending op precedes nothing (its response never happened).
        mat = ret[:, None] < inv[None, :]
        mat[pend, :] = False
        np.fill_diagonal(mat, False)
        return mat


def bucket_for(n_ops: int) -> int:
    for b in OP_BUCKETS:
        if n_ops <= b:
            return b
    raise ValueError(f"history of {n_ops} ops exceeds largest bucket "
                     f"{OP_BUCKETS[-1]}")


@dataclasses.dataclass
class EncodedBatch:
    """A batch of histories encoded to fixed-shape arrays (host-side numpy;
    the backend moves them to device)."""

    ops: np.ndarray        # int32[B, N, 4]  (pid, cmd, arg, resp)
    interval: np.ndarray   # int32[B, N, 2]  (invoke_time, response_time)
    valid: np.ndarray      # bool[B, N]
    pending: np.ndarray    # bool[B, N]
    init_state: np.ndarray  # int32[STATE_DIM]  (shared across the batch)

    @property
    def batch_size(self) -> int:
        return self.ops.shape[0]

    @property
    def max_ops(self) -> int:
        return self.ops.shape[1]

    def precedes(self) -> np.ndarray:
        """bool[B, N, N] strict precedence matrices."""
        inv = self.interval[:, :, 0].astype(np.int64)
        ret = self.interval[:, :, 1].astype(np.int64)
        mat = ret[:, :, None] < inv[:, None, :]
        mat &= self.valid[:, :, None] & self.valid[:, None, :]
        mat &= ~self.pending[:, :, None]  # pending ops precede nothing
        b, n, _ = mat.shape
        mat[:, np.arange(n), np.arange(n)] = False
        return mat


def encode_batch(
    histories: Sequence[History],
    init_state: np.ndarray,
    max_ops: Optional[int] = None,
) -> EncodedBatch:
    """Pad a list of histories into one fixed-shape batch.

    ``max_ops`` defaults to the smallest bucket that fits the longest history;
    callers that want a stable shape across calls (to reuse a compiled kernel)
    pass it explicitly.
    """
    longest = max((len(h) for h in histories), default=1)
    n = max_ops if max_ops is not None else bucket_for(max(longest, 1))
    if longest > n:
        raise ValueError(f"history of {longest} ops does not fit max_ops={n}")
    b = len(histories)
    ops = np.zeros((b, n, 4), np.int32)
    interval = np.zeros((b, n, 2), np.int32)
    valid = np.zeros((b, n), bool)
    pending = np.zeros((b, n), bool)
    for i, h in enumerate(histories):
        for j, o in enumerate(h.ops):
            ops[i, j] = (o.pid, o.cmd, o.arg, max(o.resp, 0))
            interval[i, j] = (o.invoke_time, o.response_time)
            valid[i, j] = True
            pending[i, j] = o.is_pending
    return EncodedBatch(ops=ops, interval=interval, valid=valid,
                        pending=pending,
                        init_state=np.asarray(init_state, np.int32))


def sequential_history(
    steps: Sequence[Tuple[int, int, int, int]],
) -> History:
    """Build a (trivially sequential) history from (pid, cmd, arg, resp)
    tuples — handy for golden-history unit tests (SURVEY.md §4)."""
    ops = []
    t = 0
    for pid, cmd, arg, resp in steps:
        ops.append(Op(pid=pid, cmd=cmd, arg=arg, resp=resp,
                      invoke_time=t, response_time=t + 1))
        t += 2
    return History(ops)


def overlapping_history(
    spans: Sequence[Tuple[int, int, int, int, int, int]],
) -> History:
    """Build a history from explicit (pid, cmd, arg, resp, inv_t, ret_t)
    tuples, for hand-written concurrent golden tests."""
    ops = [Op(pid=p, cmd=c, arg=a, resp=r, invoke_time=i, response_time=t)
           for (p, c, a, r, i, t) in spans]
    ops.sort(key=lambda o: o.invoke_time)
    return History(ops)
