"""Property layer — the reference's ``prop_concurrent`` / ``prop_sequential``
QuickCheck combinators (SURVEY.md §2 Property layer; BASELINE.json:5).

Flow per trial: generate → execute under the deterministic scheduler →
linearise → on failure, shrink.  The shrink loop is where the reference pays
"thousands of shrunk histories, one at a time on CPU" (SURVEY.md §3.5); here
every shrink round executes all candidates host-side and decides them in ONE
backend batch — the end-to-end speedup path (BASELINE.json:5,9).

Budget-exceeded device verdicts are resolved by the CPU oracle so the
property's verdicts are always exact (SURVEY.md §7 hard-parts #5).
"""

from __future__ import annotations

import dataclasses
import math
import random
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..ops.backend import LineariseBackend, Verdict
from ..ops.wing_gong_cpu import WingGongCPU
from ..sched.runner import ConcurrentSUT, run_concurrent
from ..sched.scheduler import FaultPlan
from .generator import Program, dedupe, generate_program, shrink_candidates
from .history import History
from .spec import Spec


@dataclasses.dataclass
class PropertyConfig:
    n_trials: int = 100
    n_pids: int = 2
    max_ops: int = 12
    seed: int = 0
    shrink_rounds: int = 200
    shrink_batch: int = 256  # candidates decided per backend batch
    faults: Optional[FaultPlan] = None
    ramp_sizes: bool = True  # QC-style size ramp across trials
    max_steps: int = 100_000
    # Schedules explored per generated program.  One program admits many
    # interleavings; running k seeded schedules multiplies race exposure at
    # trivial cost (the scheduler is host-side and cheap) and all k
    # histories are decided in ONE backend batch (VERDICT.md round 1,
    # "What's weak" #4: one schedule per program needed 155 trials to find
    # the racy-register violation under some seeds).
    schedules_per_program: int = 4
    # Trials whose histories are decided in ONE backend batch.  At the
    # default 1 each trial's k schedules are checked alone — fine for the
    # host oracle, but a batched device backend then pays per-call dispatch
    # for a 4-lane batch (the e2e measurement that motivated this:
    # VERDICT.md round 2, "Next round" #8).  Grouping G trials makes the
    # device see G×k-lane batches; verdict semantics are unchanged (the
    # first failing trial in canonical order shrinks, exactly as ungrouped —
    # later trials in its group were merely also checked).
    # The group size RAMPS 1→2→4→…→trial_batch rather than starting at
    # trial_batch: generating + executing a full group is host-side work
    # paid before the batch is checked, so an early violation inside a
    # 64-trial group wasted ~60 trials of execution — the measured
    # regression on violating SUTs (BENCH_E2E_r04: hybrid/racy 48.9 h/s at
    # trial_batch=64 vs 75.5 at 1; VERDICT.md round 4, "Next round" #7).
    # Ramping bounds the waste to < the trials already run while keeping
    # the steady-state (no-violation) batch at full width.
    # DEVICE-ONLY KNOB: leave at 1 for every host backend.  The BENCH_E2E
    # evidence through r05 shows grouping only ever paying on a real
    # accelerator's per-call dispatch; on host backends (and the CPU
    # fallback) the wider padded batch measures strictly SLOWER
    # (BENCH_E2E_r03/r04), so 1 stays the default until on-chip e2e rows
    # settle a better value.
    trial_batch: int = 1
    # message transport for the scheduler plane: "memory" (default) or
    # "tcp" (real loopback sockets, sched/transport.py).  Histories are
    # bit-identical across transports — the scheduler owns ordering.
    transport: str = "memory"
    # After the program-level shrink, additionally minimize the failing
    # HISTORY itself through the batched shrink plane (qsm_tpu/shrink,
    # docs/SHRINK.md): op-subset + schedule shrinks decided frontier-at-
    # once on the run's own backend.  The result lands in
    # ``Counterexample.minimized_history`` (the program-level
    # counterexample is untouched — it is what replays), and the
    # shrink_* counters ride ``PropertyResult.timings``.  Off by
    # default: the artifact is a second, smaller violation of the
    # history, not a replayable (program, schedule).
    minimize_history: bool = False
    # Worker processes for schedule execution (sched/pool.py).  0 = serial.
    # Histories are pure functions of (sut, program, seed, faults), so
    # fan-out changes wall-clock only — results stay bit-identical.
    # Requires a picklable sut factory (prop_concurrent's ``sut_factory``,
    # e.g. ``qsm_tpu.models.registry.SutFactory``); ignored without one.
    executor_workers: int = 0


@dataclasses.dataclass
class Counterexample:
    program: Program
    history: History
    trial: int
    trial_seed: str  # replay key
    shrink_steps: int
    # 1-minimal history from the batched shrink plane when
    # ``PropertyConfig.minimize_history`` asked for it (qsm_tpu/shrink):
    # a sub-history/reschedule of ``history`` that still violates —
    # smaller to read, but NOT a (program, schedule) replay artifact
    minimized_history: Optional[History] = None


@dataclasses.dataclass
class PropertyResult:
    ok: bool
    trials_run: int
    histories_checked: int
    counterexample: Optional[Counterexample] = None
    # histories the backend AND oracle both failed to decide within budget;
    # a nonzero count means ok=True is not a sound verdict (surfaced, never
    # silently swallowed)
    undecided: int = 0
    # schedule-coverage stats (SURVEY.md §5 race-detection row): how many
    # seeded schedules ran, and how many produced *distinct* histories —
    # low diversity means the extra schedules bought little race exposure
    schedules_run: int = 0
    distinct_histories: int = 0
    # wall-clock split of the property run (seconds): where does end-to-end
    # time actually go?  The 100× story is about the checking workload
    # (SURVEY.md §3.5) — this is the honest measurement of whether checking
    # (vs host-side execution/generation) is the bottleneck being solved
    # (VERDICT.md round 2, "Next round" #8).  Keys: generate, execute,
    # check, resolve, shrink_execute, shrink_check; plus the resilience
    # plane's fault-handling record when anything degraded —
    # resilience_degradations / resilience_retries (qsm_tpu/resilience).
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def schedule_diversity(self) -> float:
        return (self.distinct_histories / self.schedules_run
                if self.schedules_run else 0.0)

    def __bool__(self) -> bool:
        return self.ok and self.undecided == 0


def _default_oracle(spec: Spec):
    """Native C++ checker when buildable, else the memoised Python oracle
    (identical verdicts; CppOracle routes anything it can't decide
    natively to the Python oracle itself)."""
    try:
        from ..native import CppOracle, native_available

        if native_available():
            return CppOracle(spec)
    except Exception:  # noqa: BLE001 — the oracle must always exist
        pass
    return WingGongCPU(memo=True)


def trial_seed(base_seed: int, trial: int) -> str:
    """Stable per-trial seed key (str-seeded Random uses sha512 — stable
    across processes, unlike hash())."""
    return f"{base_seed}:{trial}"


def schedule_seed(trial_seed_key: str, j: int) -> str:
    """Seed key of the j-th schedule of a trial.  Schedule 0 reuses the
    trial key itself so single-schedule runs and old regression files keep
    their exact histories."""
    return trial_seed_key if j == 0 else f"{trial_seed_key}#{j}"


def program_key(seed_key: str) -> str:
    """Strip a schedule suffix: the program is generated from the TRIAL key
    (all schedules of a trial share one program)."""
    return seed_key.split("#", 1)[0]




def _trial_ops(cfg: PropertyConfig, trial: int) -> int:
    if not cfg.ramp_sizes or cfg.n_trials <= 1:
        return cfg.max_ops
    frac = (trial + 1) / cfg.n_trials
    return max(2, math.ceil(cfg.max_ops * frac))


def _resolve(spec: Spec, verdicts: np.ndarray, histories: Sequence[History],
             backend: LineariseBackend, oracle: WingGongCPU,
             timings: Optional[Dict[str, float]] = None) -> np.ndarray:
    """Resolve BUDGET_EXCEEDED device verdicts via the CPU oracle.

    Skipped when the backend IS the oracle (re-running the identical search
    with the identical budget can only repeat the verdict), including a
    failover wrapper already degraded onto the oracle.  Verdicts still
    undecided afterwards stay BUDGET_EXCEEDED and are surfaced by the caller.
    """
    if backend is oracle or (getattr(backend, "degraded", False)
                             and getattr(backend, "fallback", None)
                             is oracle):
        return verdicts
    out = verdicts.copy()
    todo = [i for i, v in enumerate(out) if v == Verdict.BUDGET_EXCEEDED]
    if todo:
        t0 = time.perf_counter()
        resolved = oracle.check_histories(spec, [histories[i] for i in todo])
        if timings is not None:
            timings["resolve"] = (timings.get("resolve", 0.0)
                                  + time.perf_counter() - t0)
        for i, v in zip(todo, resolved):
            out[i] = v
    return out


def _execute(sut: ConcurrentSUT, prog: Program, sched_seed: str,
             cfg: PropertyConfig, transport=None) -> History:
    return run_concurrent(sut, prog, seed=sched_seed, faults=cfg.faults,
                          max_steps=cfg.max_steps, transport=transport)


def _execute_many(sut: ConcurrentSUT, jobs, cfg: PropertyConfig,
                  transport=None, executor=None) -> List[History]:
    """Execute [(program, seed), ...] in job order — serially, or fanned
    over the worker pool (order-preserving, bit-identical histories)."""
    if executor is not None:
        return executor.run_many(jobs, cfg.faults, cfg.max_steps)
    return [_execute(sut, p, s, cfg, transport) for p, s in jobs]


def shrink_failure(
    spec: Spec,
    sut: ConcurrentSUT,
    backend: LineariseBackend,
    oracle: WingGongCPU,
    cfg: PropertyConfig,
    program: Program,
    history: History,
    sched_seed: str,
    timings: Optional[Dict[str, float]] = None,
    transport=None,
    executor=None,
) -> tuple[Program, History, int, int]:
    """Greedy shrink: each round, decide ALL candidates in one backend batch
    and step to the first (canonical order) still-failing one.

    Returns (min_program, min_history, shrink_steps, histories_checked)."""
    steps = 0
    checked = 0
    timings = timings if timings is not None else {}
    for _ in range(cfg.shrink_rounds):
        cands = dedupe(shrink_candidates(spec, program), cfg.shrink_batch)
        if not cands:
            break
        t0 = time.perf_counter()
        hists = _execute_many(sut, [(c, sched_seed) for c in cands],
                              cfg, transport, executor)
        t1 = time.perf_counter()
        timings["shrink_execute"] = (timings.get("shrink_execute", 0.0)
                                     + t1 - t0)
        raw = backend.check_histories(spec, hists)
        timings["shrink_check"] = (timings.get("shrink_check", 0.0)
                                   + time.perf_counter() - t1)
        verdicts = _resolve(spec, raw, hists, backend, oracle, timings)
        checked += len(hists)
        fail = next((i for i, v in enumerate(verdicts)
                     if v == Verdict.VIOLATION), None)
        if fail is None:
            break
        program, history = cands[fail], hists[fail]
        steps += 1
    return program, history, steps, checked


def _minimize_history(spec, backend, history, timings):
    """The opt-in batched history minimization pass (qsm_tpu/shrink):
    run on the property's OWN backend (frontier candidates are just
    another batch to it), counters merged into the per-run timings.
    Returns (minimized_history | None, lanes_checked)."""
    from ..shrink.shrinker import shrink_history as _shrink_history

    t0 = time.perf_counter()
    res = _shrink_history(spec, history, backend=backend,
                          certificate=False)
    timings["shrink_minimize"] = (timings.get("shrink_minimize", 0.0)
                                  + time.perf_counter() - t0)
    if res.ok:
        # flat str -> float by the timings contract; ONLY the shrink_*
        # keys merge here — the search_* entries stay owned by the
        # backend-wrapper delta prop_concurrent computes at the end
        # (which already includes the frontier dispatches' cost)
        timings.update({k: v
                        for k, v in res.search_stats().to_timings().items()
                        if k.startswith("shrink_")})
        return res.history, res.lanes_checked
    return None, res.lanes_checked


def prop_concurrent(
    spec: Spec,
    sut: ConcurrentSUT,
    cfg: Optional[PropertyConfig] = None,
    backend: Optional[LineariseBackend] = None,
    oracle: Optional[WingGongCPU] = None,
    sut_factory=None,
) -> PropertyResult:
    """Generate → execute → linearise → shrink; the reference's main entry
    point (SURVEY.md §3.1).  ``sut_factory`` (picklable, zero-arg — e.g.
    ``qsm_tpu.models.registry.SutFactory``) enables the parallel execution
    plane when ``cfg.executor_workers > 0``."""
    cfg = cfg or PropertyConfig()
    # Default resolution oracle: the native C++ checker when the toolchain
    # is available (same verdict contract — it falls back to the Python
    # oracle internally for anything outside its native coverage), else
    # the memoised Python oracle.  Identical verdicts either way; only
    # the wall-clock of BUDGET_EXCEEDED resolution changes.
    if oracle is None:
        oracle = _default_oracle(spec)
    backend = backend or oracle
    timings: Dict[str, float] = {}
    transport = None
    executor = None

    def _bump(key: str, t0: float) -> float:
        now = time.perf_counter()
        timings[key] = timings.get(key, 0.0) + now - t0
        return now

    # everything that opens resources lives INSIDE the try so a failure in
    # any construction step still closes the ones already open
    try:
        use_pool = cfg.executor_workers > 0 and sut_factory is not None
        if cfg.transport != "memory" and not use_pool:
            # ONE transport for the whole property run: TCP endpoint
            # connections persist across every trial/schedule/shrink
            # execution instead of churning ephemeral ports per history
            # (sched/transport.py).  With a worker pool every execution
            # happens in the workers, which build their own transports —
            # a parent-side one would carry zero bytes.
            from ..sched.transport import make_transport

            transport = make_transport(cfg.transport)
        if use_pool:
            from ..sched.pool import PoolExecutor

            executor = PoolExecutor(sut_factory, cfg.executor_workers,
                                    transport=cfg.transport)
        # search-cost accounting rides the timings dict (flat str → float
        # by contract): iterations-per-history and host nodes from
        # whichever engines this run actually used (search/stats.py).
        # Engines count cumulatively per instance, so snapshot before and
        # report the delta: timings entries are per-run by contract.
        from ..resilience.failover import FailoverBackend
        from ..search.stats import collect_search_stats, stats_delta

        # mid-run device loss degrades dispatch to the resolution oracle
        # instead of crashing the run — one-way, watchdog-bounded,
        # counted.  The SAME combinator the CLI's --failover uses
        # (resilience/failover.py): a second private implementation here
        # would let the two degradation semantics drift apart.  An
        # already-wrapped backend keeps its own (possibly different)
        # fallback ladder.
        if backend is not oracle \
                and not isinstance(backend, FailoverBackend):
            backend = FailoverBackend(spec, backend, fallback=oracle)
        st0 = collect_search_stats(backend)
        res = _prop_concurrent_body(
            spec, sut, cfg, backend, oracle, transport, executor,
            timings, _bump)
        # the delta is computed on the WRAPPER, so the resilience
        # counters (degradations/retries) ride the same per-run snapshot
        # discipline as every other search stat
        st = stats_delta(collect_search_stats(backend), st0)
        if st is not None:
            res.timings.update(st.to_timings())
        return res
    finally:
        if transport is not None:
            transport.close()
        if executor is not None:
            executor.close()


def _prop_concurrent_body(spec, sut, cfg, backend, oracle, transport,
                          executor, timings, _bump) -> PropertyResult:
    checked = 0
    undecided = 0
    schedules_run = 0
    distinct = 0
    k = max(1, cfg.schedules_per_program)
    group_target = max(1, cfg.trial_batch)
    # geometric ramp toward the configured width (see PropertyConfig):
    # early violations stop the run having wasted at most as many trials
    # as already ran; violation-free runs reach full width in log2 steps
    group_n = 1
    t = 0
    while t < cfg.n_trials:
        group = list(range(t, min(t + group_n, cfg.n_trials)))
        progs: List[Program] = []
        seeds_all: List[List[str]] = []
        spans: List[int] = []
        jobs: List[tuple] = []
        for ti in group:
            s = trial_seed(cfg.seed, ti)
            t0 = time.perf_counter()
            prog = generate_program(
                spec, seed=random.Random(s).randrange(1 << 62),
                n_pids=cfg.n_pids, max_ops=_trial_ops(cfg, ti))
            _bump("generate", t0)
            # k seeded schedules of the SAME program; the whole group's
            # histories are executed in one (possibly fanned-out) batch
            # and decided in ONE backend batch below
            seeds = [schedule_seed(s, j) for j in range(k)]
            progs.append(prog)
            seeds_all.append(seeds)
            spans.append(len(jobs))
            jobs.extend((prog, sk) for sk in seeds)
        t0 = time.perf_counter()
        hists_all = _execute_many(sut, jobs, cfg, transport, executor)
        _bump("execute", t0)
        t0 = time.perf_counter()
        check_hists = hists_all
        if len(hists_all) < group_target * k:
            # ramp-phase AND truncated-final-group batches are padded to
            # the full configured width with empty (instantly-SUCCESS)
            # histories so every call hits the SAME compiled executable
            # as the steady state — without this the 1,2,4,… groups (and
            # the n_trials remainder) touch extra batch buckets and a
            # device backend pays extra compile sets inside the run
            # (measured: device/atomic e2e fell 70 → 39 h/s from exactly
            # that).  Padding lanes freeze at init, so the extra device
            # work is bounded by the batch width, not the search.
            pad = group_target * k - len(hists_all)
            check_hists = hists_all + [History([])] * pad
        raw = np.asarray(
            backend.check_histories(spec, check_hists))[:len(hists_all)]
        _bump("check", t0)
        verdicts = _resolve(spec, raw, hists_all, backend, oracle, timings)
        checked += len(hists_all)
        schedules_run += len(hists_all)
        undecided += int(sum(v == Verdict.BUDGET_EXCEEDED for v in verdicts))
        for gi, ti in enumerate(group):
            hists = hists_all[spans[gi]:spans[gi] + k]
            distinct += len({h.fingerprint() for h in hists})
        # first failing trial in canonical order shrinks — identical choice
        # to the ungrouped loop
        fail_at = next((i for i, v in enumerate(verdicts)
                        if v == Verdict.VIOLATION), None)
        if fail_at is not None:
            gi = max(i for i, start in enumerate(spans) if start <= fail_at)
            ti = group[gi]
            j = fail_at - spans[gi]
            mp, mh, steps, c2 = shrink_failure(
                spec, sut, backend, oracle, cfg, progs[gi],
                hists_all[fail_at], seeds_all[gi][j], timings, transport,
                executor)
            minimized = None
            if cfg.minimize_history:
                minimized, c3 = _minimize_history(spec, backend, mh,
                                                  timings)
                c2 += c3
            return PropertyResult(
                ok=False, trials_run=ti + 1,
                histories_checked=checked + c2,
                undecided=undecided, schedules_run=schedules_run,
                distinct_histories=distinct, timings=timings,
                counterexample=Counterexample(
                    program=mp, history=mh, trial=ti,
                    trial_seed=seeds_all[gi][j], shrink_steps=steps,
                    minimized_history=minimized))
        t += len(group)
        group_n = min(group_target, group_n * 2)
    return PropertyResult(ok=True, trials_run=cfg.n_trials,
                          histories_checked=checked, undecided=undecided,
                          schedules_run=schedules_run,
                          distinct_histories=distinct, timings=timings)


def replay(
    spec: Spec,
    sut: ConcurrentSUT,
    trial_seed_key: str,
    cfg: Optional[PropertyConfig] = None,
) -> History:
    """Reproduce a trial's history exactly from its seed key — the
    checkpoint/resume story: every artifact derivable from (seed, config)
    (SURVEY.md §5)."""
    cfg = cfg or PropertyConfig()
    # the program comes from the TRIAL key; a "#j" suffix only selects the
    # schedule seed (see schedule_seed)
    prog_key = program_key(trial_seed_key)
    _, t = prog_key.rsplit(":", 1)
    prog = generate_program(
        spec, seed=random.Random(prog_key).randrange(1 << 62),
        n_pids=cfg.n_pids, max_ops=_trial_ops(cfg, int(t)))
    # a single run: pass the transport SPEC so run_concurrent owns and
    # closes it (histories are transport-independent either way)
    return _execute(sut, prog, trial_seed_key, cfg,
                    None if cfg.transport == "memory" else cfg.transport)
