"""State-machine specification protocol.

The reference frames a spec as a record of ``initialModel`` / ``transition`` /
``precondition`` / ``postcondition`` plus a command generator and shrinker
(reference: the state-machine record described in SURVEY.md §2, names anchored
on BASELINE.json:5 — the mount at /root/reference was empty, so module-level
citations are to the survey, not file:line).

TPU-first redesign
------------------
Instead of an arbitrary Haskell record over rich types, a spec here is a small
class over **integer domains** so that every spec compiles to a pure, branchless
``step(state, cmd, arg, resp) -> (state', ok)`` function usable in three forms:

* ``step_py``  — pure-Python ints, used by the CPU oracle (``WingGongCPU``) and
  the sequential runner.  This is the parity reference.
* ``step_jax`` — the same function written against ``jax.numpy``; traced once
  inside the TPU kernel's ``lax.while_loop`` and vmapped over ops/batches.
* an optional dense **step table** (``compile_step_table``) for small specs,
  used in tests to cross-check ``step_py`` == ``step_jax`` exhaustively.

Model state is a fixed-length ``int32[STATE_DIM]`` vector (packed-int encoding,
SURVEY.md §7 "hard parts" #2), so queue/KV-style specs whose state space is too
big to tabulate still trace to static shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class KeyProj:
    """Declarative per-key projection of one command (P-compositionality,
    PAPERS.md:5).  A command whose integer argument packs ``key * stride +
    projected_arg`` declares how to unpack it:

        key           = arg // stride
        projected op  = (pcmd, arg % stride, resp)   — resp passes through

    ``pcmd`` indexes the PROJECTED spec's alphabet (``projected_spec()``).
    Declared next to the step tables so the split the checker performs is
    visible in the same place the semantics live, and so
    :func:`projection_report` can validate totality and faithfulness once
    at compile time instead of trusting a hand-written ``partition_key``.
    """

    pcmd: int    # command index in the projected (per-key) spec
    stride: int  # key = arg // stride; projected arg = arg % stride


@dataclasses.dataclass(frozen=True)
class CmdSig:
    """Signature of one command in a spec's alphabet.

    ``n_args``/``n_resps`` bound the integer domains so generators and the
    pending-op completion logic (fault injection) can enumerate them.
    ``proj`` (optional) declares the command's per-key projection for
    P-compositional decomposition; a spec is decomposable iff EVERY
    command declares one (totality) and :func:`projection_report` finds
    the projected spec faithful.
    """

    name: str
    n_args: int  # args drawn from [0, n_args); 1 means "no argument"
    n_resps: int  # responses live in [0, n_resps)
    proj: Optional[KeyProj] = None  # per-key projection (P-compositionality)


class Spec:
    """Base class for state-machine specifications.

    Subclasses define:
      * ``CMDS``        — tuple of :class:`CmdSig` (the command alphabet)
      * ``STATE_DIM``   — length of the packed int32 model-state vector
      * ``initial_state()``
      * ``step_py(state, cmd, arg, resp)``   (list[int] -> (list[int], bool))
      * ``step_jax(state, cmd, arg, resp)``  (jnp arrays, branchless)
      * optionally ``gen_cmd(rng, hint)``    (seeded command generation)
      * optionally ``partition_key(cmd, arg)`` for P-compositionality
        (per-key linearizability split; see ops/pcomp.py and PAPERS.md:5).

    ``step`` fuses the reference's ``transition`` and ``postcondition`` into a
    single function: ``ok`` is the postcondition verdict, ``state'`` the
    transition result.  Preconditions are enforced at *generation* time only
    (the reference does the same for the concurrent path — SURVEY.md §3.1).
    """

    name: str = "spec"
    CMDS: Tuple[CmdSig, ...] = ()
    STATE_DIM: int = 1

    # -- model ------------------------------------------------------------
    def initial_state(self) -> np.ndarray:
        raise NotImplementedError

    def step_py(
        self, state: Sequence[int], cmd: int, arg: int, resp: int
    ) -> Tuple[Sequence[int], bool]:
        raise NotImplementedError

    def step_jax(self, state, cmd, arg, resp):
        raise NotImplementedError

    # -- generation -------------------------------------------------------
    def precondition(self, state: Sequence[int], cmd: int, arg: int) -> bool:
        """May ``cmd(arg)`` be issued when the model is in ``state``?

        Enforced at generation time (the reference checks ``precondition``
        during generation and sequential execution — SURVEY.md §3.4); the
        generator tracks an approximate model state and rejection-samples
        against this.  Default: always true.
        """
        return True

    def gen_cmd(self, rng, state: Optional[Sequence[int]] = None
                ) -> Tuple[int, int]:
        """Return a random (cmd, arg) whose precondition holds in ``state``.

        Default: uniform over the alphabet, rejection-sampled against
        :meth:`precondition` (bounded tries; falls back to the last sample
        so generation always terminates).
        """
        cmd = arg = 0
        for _ in range(32):
            cmd = rng.randrange(len(self.CMDS))
            arg = rng.randrange(self.CMDS[cmd].n_args)
            if state is None or self.precondition(state, cmd, arg):
                break
        return cmd, arg

    def shrink_arg(self, cmd: int, arg: int):
        """Candidate smaller args for shrinking (toward 0)."""
        out = []
        if arg > 0:
            out.append(0)
        if arg > 1:
            out.append(arg // 2)
        return out

    # -- kernel acceleration ----------------------------------------------
    def scalar_state_bound(self, n_ops: int) -> Optional[int]:
        """Exclusive upper bound on reachable scalar model states, or None.

        Only meaningful for ``STATE_DIM == 1`` specs.  When a bound ``S`` is
        declared, every state reachable through an ok step from the initial
        state must lie in ``[0, S)`` — for histories whose **args** are in
        the declared command domains but whose **resps** are arbitrary ints
        (SUTs can return anything; args come from the generator, which
        respects the domains).  ``JaxTPU`` enforces the arg side host-side
        and defers out-of-domain histories to the oracle.  The device kernel
        precomputes a per-history ``[S, n_ops]`` step table ONCE and
        replaces the per-iteration vmapped ``step_jax`` sweep with a single
        dynamic row gather (VERDICT.md round 1, "Next round" #2).  ``n_ops``
        is provided for specs whose state grows with history length (ticket
        dispenser: bound ``n_ops + 1`` — an ok-TAKE chain gains 1 per op).
        """
        return None

    def state_elem_bounds(self) -> Optional[Sequence[int]]:
        """Per-element EXCLUSIVE upper bounds on the state vector, or None.

        The contract: from any state whose elements are within bounds,
        any ok step whose ARG is in the declared command domains (resps
        arbitrary) yields a state whose elements are within bounds, and
        the initial state is within bounds.  Declaring this lets the
        device backend pack small vector states into one scalar
        (ops/scalarize.py) and ride the step-table gather fast path the
        scalar specs use; the packing is a bijection, so verdicts are
        unchanged (iteration counts agree up to memo hash-collision
        luck — the cache key width changes).
        """
        return None

    def native_kernel(self) -> Optional[Tuple[int, int, int]]:
        """(kind, p0, p1) selecting a built-in C++ step kernel in
        qsm_tpu/native/wg.cpp, or None.  Scalar-table specs need none (the
        native checker drives them through the compiled domain table);
        vector-state specs opt in by returning their kernel id + params —
        the C++ side reimplements ``step_py`` exactly, and the parity
        suite (tests/test_native.py) pins the equivalence."""
        return None

    # -- decomposition ----------------------------------------------------
    def partition_key(self, cmd: int, arg: int) -> Optional[int]:
        """Key for P-compositionality decomposition, or None if the spec is
        not per-key decomposable.  Sound only when sub-histories for distinct
        keys are independent (PAPERS.md:5).

        Derived from the ``CmdSig.proj`` declarations: a spec that tags
        every command with a :class:`KeyProj` gets the split for free and
        — more importantly — gets it VALIDATED (:func:`projection_report`)
        instead of trusted.  A command without a declaration answers None,
        which every consumer treats as "refuse to decompose"."""
        p = self.CMDS[cmd].proj
        return None if p is None else arg // p.stride

    def project_op(self, cmd: int, arg: int, resp: int
                   ) -> Tuple[int, int, int]:
        """Map a whole-spec op onto the projected (per-key) spec's
        ``(cmd, arg, resp)``.  Responses pass through unchanged — the
        validator pins the projected command's response domain equal to
        the original's, so a stitched witness's completion choices stay
        in-domain for the WHOLE spec too (ops/backend.py verify_witness).
        """
        p = self.CMDS[cmd].proj
        if p is None:
            raise ValueError(
                f"{self.name}: command {cmd} declares no KeyProj; "
                "partition_key is not total")
        return p.pcmd, arg % p.stride, resp

    def project_state(self, state: Sequence[int], key: int) -> list:
        """The per-key component of a whole model state — the state the
        projected spec sees for ``key``.  Default: element ``key`` of the
        packed vector (a product-of-scalars state layout, which every
        in-tree decomposable spec uses).  Specs with a different packing
        override; :func:`projection_report` validates the choice against
        ``step_py`` either way."""
        return [int(state[key])]

    # -- persistence ------------------------------------------------------
    def spec_kwargs(self) -> dict:
        """Constructor kwargs that reproduce this spec exactly.

        Persisted in regression files so a failure captured against a
        non-default spec (e.g. ``KvSpec(n_keys=8)``) replays against the
        SAME spec instead of silently rebuilding registry defaults
        (ADVICE.md round 1).  Subclasses with constructor parameters MUST
        override.
        """
        return {}

    # -- derived ----------------------------------------------------------
    @property
    def n_cmds(self) -> int:
        return len(self.CMDS)

    @property
    def max_resps(self) -> int:
        return max(c.n_resps for c in self.CMDS)

    def resp_domain(self, cmd: int) -> range:
        return range(self.CMDS[cmd].n_resps)


def compile_step_table(spec: Spec, n_states: int):
    """Tabulate ``step_py`` for specs whose packed state fits one scalar.

    Returns ``(trans, ok)`` with shapes ``[n_states, n_cmds, max_args,
    max_resps]``; used by tests to cross-check the py/jax step functions
    exhaustively (SURVEY.md §7 design stance: the step-table compiler).
    Requires ``STATE_DIM == 1`` and state values in ``[0, n_states)``.
    """
    assert spec.STATE_DIM == 1, "step tables only for scalar-state specs"
    max_args = max(c.n_args for c in spec.CMDS)
    max_resps = spec.max_resps
    trans = np.zeros((n_states, spec.n_cmds, max_args, max_resps), np.int32)
    ok = np.zeros((n_states, spec.n_cmds, max_args, max_resps), bool)
    for s in range(n_states):
        for c, sig in enumerate(spec.CMDS):
            for a in range(sig.n_args):
                for r in range(sig.n_resps):
                    ns, good = spec.step_py([s], c, a, r)
                    trans[s, c, a, r] = ns[0]
                    ok[s, c, a, r] = good
    return trans, ok


# Selectivity probing caps at this many states: the table is a search
# HEURISTIC (candidate try-order — qsm_tpu/search/ordering.py), never a
# soundness input, so a deterministic stride sample of a huge packed
# domain (stack/queue shadows reach 10⁴–10⁵ states) estimates the same
# ranks at a bounded compile cost.
MAX_SELECTIVITY_PROBE_STATES = 512


def compile_selectivity_table(
    spec: Spec, n_states: int,
    max_probe_states: int = MAX_SELECTIVITY_PROBE_STATES,
) -> np.ndarray:
    """Postcondition selectivity per (cmd, arg, resp): the fraction of
    scalar states in ``[0, n_states)`` whose ``step_py`` accepts the op.

    Compiled alongside :func:`compile_step_table` (same scalar-domain
    contract, same ``step_py`` source of truth) and consumed by the
    search plane's candidate ordering: low selectivity = the op's
    postcondition holds almost nowhere = trying it first either prunes
    hardest or exposes the dead branch at depth 1.  Domains larger than
    ``max_probe_states`` are stride-sampled deterministically — the
    result is a rank estimate, which is all ordering needs (verdicts
    never depend on it).
    """
    assert spec.STATE_DIM == 1, \
        "selectivity tables only for scalar-state specs"
    if n_states <= max_probe_states:
        # the canonical tabulation IS the selectivity source: one loop to
        # maintain, and the ordering heuristic can never disagree with
        # the ok-table the kernels' gather path is built from
        _, ok = compile_step_table(spec, n_states)
        return ok.mean(axis=0, dtype=np.float64)
    max_args = max(c.n_args for c in spec.CMDS)
    max_resps = spec.max_resps
    stride = -(-n_states // max_probe_states)
    states = range(0, n_states, stride)
    sel = np.zeros((spec.n_cmds, max_args, max_resps), np.float64)
    n_probed = 0
    for s in states:
        n_probed += 1
        for c, sig in enumerate(spec.CMDS):
            for a in range(sig.n_args):
                for r in range(sig.n_resps):
                    _, good = spec.step_py([s], c, a, r)
                    if good:
                        sel[c, a, r] += 1.0
    return sel / max(n_probed, 1)


# ---------------------------------------------------------------------------
# P-compositionality projection validation (compile time, once per spec)
# ---------------------------------------------------------------------------

# Sampling caps: faithfulness is checked over every (cmd, arg, resp)
# tuple (arg domains stride-sampled past the cap) from a seeded set of
# reachable states — exhaustive state enumeration is out of the question
# for product states (n_values ** n_keys), and a projection bug is a
# per-command packing mistake, visible from almost any state.
_PROJ_PROBE_STATES = 24
_PROJ_PROBE_ARGS = 64


def projection_report(spec: Spec, seed: int = 0) -> list:
    """Validate a spec's declared per-key projection; [] means sound.

    Returns a list of human-readable problem strings (the planner's
    refusal ``why`` stamps and qsmlint's QSM-SPEC-PCOMP findings both
    render these verbatim).  Checks, in order:

    * **declaration** — ``projected_spec()`` exists and every command
      carries a :class:`KeyProj` (totality: a history can only be split
      if EVERY op maps to a key);
    * **domains** — projected cmd indexes the projected alphabet, the
      projected arg domain ``[0, stride)`` fits it, and the projected
      command's response domain EQUALS the original's (a pending op's
      completion is chosen in the projected domain and must replay
      in-domain against the whole spec — verify_witness);
    * **faithfulness + independence** — from seeded reachable whole
      states: a step changes ONLY its key's component, and the projected
      spec's ``step_py`` on the projected op from the projected state
      agrees (same ok, same per-key next state).  This is the
      P-compositionality soundness obligation: the whole object IS the
      product of the per-key objects.

    Cached on the spec instance (``_projection_report``): the planner,
    PComp construction, the serve plane and qsmlint all consult it, and
    it must stay a compile-time cost, not a per-batch one.
    """
    cached = spec.__dict__.get("_projection_report")
    if cached is not None:
        return list(cached)
    report = _projection_report_uncached(spec, seed)
    spec.__dict__["_projection_report"] = tuple(report)
    return report


def _projection_report_uncached(spec: Spec, seed: int) -> list:
    problems: list = []
    if not hasattr(spec, "projected_spec"):
        if any(c.proj is not None for c in spec.CMDS):
            return [f"{spec.name}: CmdSig declares KeyProj but the spec "
                    "has no projected_spec()"]
        return [f"{spec.name}: no per-key projection declared"]
    missing = [c.name for c in spec.CMDS if c.proj is None]
    if missing:
        # non-total: some ops have no key — decomposition would have to
        # drop or guess them, which is exactly the unsound split the
        # refusal path exists to prevent
        return [f"{spec.name}: partition_key is not total — commands "
                f"{missing} declare no KeyProj"]
    try:
        proj = spec.projected_spec()
    except Exception as e:  # noqa: BLE001 — a failing factory is a report
        return [f"{spec.name}: projected_spec() raised "
                f"{type(e).__name__}: {e}"]
    for c, sig in enumerate(spec.CMDS):
        p = sig.proj
        if p.stride <= 0:
            problems.append(f"{sig.name}: KeyProj stride {p.stride} <= 0")
            continue
        if not 0 <= p.pcmd < proj.n_cmds:
            problems.append(f"{sig.name}: projected cmd {p.pcmd} outside "
                            f"{proj.name}'s alphabet [0, {proj.n_cmds})")
            continue
        psig = proj.CMDS[p.pcmd]
        if p.stride > psig.n_args:
            problems.append(
                f"{sig.name}: projected args [0, {p.stride}) exceed "
                f"{proj.name}.{psig.name} domain [0, {psig.n_args})")
        if sig.n_resps != psig.n_resps:
            problems.append(
                f"{sig.name}: response domain {sig.n_resps} != projected "
                f"{proj.name}.{psig.name} domain {psig.n_resps} (pending "
                "completions must replay in-domain on both)")
    if problems:
        return problems
    problems += _check_faithful(spec, proj, seed)
    return problems


def _check_faithful(spec: Spec, proj: Spec, seed: int) -> list:
    """Sampled step-level faithfulness/independence (docstring above)."""
    import random

    rng = random.Random(f"pcomp-faithful:{spec.name}:{seed}")
    states = [[int(v) for v in spec.initial_state()]]
    # seeded ok-walks from the initial state gather a reachable sample
    for _ in range(_PROJ_PROBE_STATES - 1):
        st = list(rng.choice(states))
        for _ in range(8):
            cmd = rng.randrange(spec.n_cmds)
            arg = rng.randrange(spec.CMDS[cmd].n_args)
            resp = rng.randrange(spec.CMDS[cmd].n_resps)
            nxt, ok = spec.step_py(list(st), cmd, arg, resp)
            if ok:
                st = [int(v) for v in nxt]
        states.append(st)
    problems: list = []
    # key universe: every key any in-domain arg can map to
    n_keys = max((sig.n_args - 1) // sig.proj.stride + 1
                 for sig in spec.CMDS)
    for cmd, sig in enumerate(spec.CMDS):
        p = sig.proj
        args = range(sig.n_args)
        if sig.n_args > _PROJ_PROBE_ARGS:
            stride = -(-sig.n_args // _PROJ_PROBE_ARGS)
            args = range(0, sig.n_args, stride)
        for arg in args:
            key = arg // p.stride
            if spec.partition_key(cmd, arg) != key:
                # a hand-written partition_key override that disagrees
                # with the declaration would split one way and project
                # another — the split itself becomes unsound
                problems.append(
                    f"{sig.name}(arg={arg}): partition_key() answers "
                    f"{spec.partition_key(cmd, arg)} but KeyProj derives "
                    f"{key}")
                break
            for resp in range(sig.n_resps):
                for st in states:
                    try:
                        whole, ok = spec.step_py(list(st), cmd, arg, resp)
                        sub_st = spec.project_state(st, key)
                        want_sub = spec.project_state(whole, key)
                        got_sub, got_ok = proj.step_py(
                            list(sub_st), p.pcmd, arg % p.stride, resp)
                        # independence through the projection itself
                        # (layout-agnostic: project_state overrides
                        # validate too): every OTHER key's projected
                        # state must be untouched
                        leaked = [
                            k2 for k2 in range(n_keys) if k2 != key
                            and ([int(v)
                                  for v in spec.project_state(whole, k2)]
                                 != [int(v)
                                     for v in spec.project_state(st, k2)])
                        ]
                    except Exception as e:  # noqa: BLE001 — report, not crash
                        # a projection that derives out-of-range keys or
                        # states is exactly what this validator exists
                        # to refuse — report it, never crash the caller
                        problems.append(
                            f"{sig.name}(arg={arg}, resp={resp}): "
                            f"{type(e).__name__}: {e}")
                        break
                    if leaked:
                        problems.append(
                            f"{sig.name}(arg={arg}): step leaks into "
                            f"keys {leaked} beyond its own key {key} — "
                            "keys are not independent")
                        break
                    if (bool(got_ok) != bool(ok)
                            or [int(v) for v in got_sub]
                            != [int(v) for v in want_sub]):
                        problems.append(
                            f"{sig.name}(arg={arg}, resp={resp}): projected "
                            f"{proj.name} step disagrees with the whole "
                            f"spec (ok {bool(ok)} vs {bool(got_ok)})")
                        break
                else:
                    continue
                break  # one problem per (cmd, arg) family is enough
            else:
                continue
            break  # and one per command keeps the report readable
    return problems
