"""State-machine specification protocol.

The reference frames a spec as a record of ``initialModel`` / ``transition`` /
``precondition`` / ``postcondition`` plus a command generator and shrinker
(reference: the state-machine record described in SURVEY.md §2, names anchored
on BASELINE.json:5 — the mount at /root/reference was empty, so module-level
citations are to the survey, not file:line).

TPU-first redesign
------------------
Instead of an arbitrary Haskell record over rich types, a spec here is a small
class over **integer domains** so that every spec compiles to a pure, branchless
``step(state, cmd, arg, resp) -> (state', ok)`` function usable in three forms:

* ``step_py``  — pure-Python ints, used by the CPU oracle (``WingGongCPU``) and
  the sequential runner.  This is the parity reference.
* ``step_jax`` — the same function written against ``jax.numpy``; traced once
  inside the TPU kernel's ``lax.while_loop`` and vmapped over ops/batches.
* an optional dense **step table** (``compile_step_table``) for small specs,
  used in tests to cross-check ``step_py`` == ``step_jax`` exhaustively.

Model state is a fixed-length ``int32[STATE_DIM]`` vector (packed-int encoding,
SURVEY.md §7 "hard parts" #2), so queue/KV-style specs whose state space is too
big to tabulate still trace to static shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class CmdSig:
    """Signature of one command in a spec's alphabet.

    ``n_args``/``n_resps`` bound the integer domains so generators and the
    pending-op completion logic (fault injection) can enumerate them.
    """

    name: str
    n_args: int  # args drawn from [0, n_args); 1 means "no argument"
    n_resps: int  # responses live in [0, n_resps)


class Spec:
    """Base class for state-machine specifications.

    Subclasses define:
      * ``CMDS``        — tuple of :class:`CmdSig` (the command alphabet)
      * ``STATE_DIM``   — length of the packed int32 model-state vector
      * ``initial_state()``
      * ``step_py(state, cmd, arg, resp)``   (list[int] -> (list[int], bool))
      * ``step_jax(state, cmd, arg, resp)``  (jnp arrays, branchless)
      * optionally ``gen_cmd(rng, hint)``    (seeded command generation)
      * optionally ``partition_key(cmd, arg)`` for P-compositionality
        (per-key linearizability split; see ops/pcomp.py and PAPERS.md:5).

    ``step`` fuses the reference's ``transition`` and ``postcondition`` into a
    single function: ``ok`` is the postcondition verdict, ``state'`` the
    transition result.  Preconditions are enforced at *generation* time only
    (the reference does the same for the concurrent path — SURVEY.md §3.1).
    """

    name: str = "spec"
    CMDS: Tuple[CmdSig, ...] = ()
    STATE_DIM: int = 1

    # -- model ------------------------------------------------------------
    def initial_state(self) -> np.ndarray:
        raise NotImplementedError

    def step_py(
        self, state: Sequence[int], cmd: int, arg: int, resp: int
    ) -> Tuple[Sequence[int], bool]:
        raise NotImplementedError

    def step_jax(self, state, cmd, arg, resp):
        raise NotImplementedError

    # -- generation -------------------------------------------------------
    def precondition(self, state: Sequence[int], cmd: int, arg: int) -> bool:
        """May ``cmd(arg)`` be issued when the model is in ``state``?

        Enforced at generation time (the reference checks ``precondition``
        during generation and sequential execution — SURVEY.md §3.4); the
        generator tracks an approximate model state and rejection-samples
        against this.  Default: always true.
        """
        return True

    def gen_cmd(self, rng, state: Optional[Sequence[int]] = None
                ) -> Tuple[int, int]:
        """Return a random (cmd, arg) whose precondition holds in ``state``.

        Default: uniform over the alphabet, rejection-sampled against
        :meth:`precondition` (bounded tries; falls back to the last sample
        so generation always terminates).
        """
        cmd = arg = 0
        for _ in range(32):
            cmd = rng.randrange(len(self.CMDS))
            arg = rng.randrange(self.CMDS[cmd].n_args)
            if state is None or self.precondition(state, cmd, arg):
                break
        return cmd, arg

    def shrink_arg(self, cmd: int, arg: int):
        """Candidate smaller args for shrinking (toward 0)."""
        out = []
        if arg > 0:
            out.append(0)
        if arg > 1:
            out.append(arg // 2)
        return out

    # -- kernel acceleration ----------------------------------------------
    def scalar_state_bound(self, n_ops: int) -> Optional[int]:
        """Exclusive upper bound on reachable scalar model states, or None.

        Only meaningful for ``STATE_DIM == 1`` specs.  When a bound ``S`` is
        declared, every state reachable through an ok step from the initial
        state must lie in ``[0, S)`` — for histories whose **args** are in
        the declared command domains but whose **resps** are arbitrary ints
        (SUTs can return anything; args come from the generator, which
        respects the domains).  ``JaxTPU`` enforces the arg side host-side
        and defers out-of-domain histories to the oracle.  The device kernel
        precomputes a per-history ``[S, n_ops]`` step table ONCE and
        replaces the per-iteration vmapped ``step_jax`` sweep with a single
        dynamic row gather (VERDICT.md round 1, "Next round" #2).  ``n_ops``
        is provided for specs whose state grows with history length (ticket
        dispenser: bound ``n_ops + 1`` — an ok-TAKE chain gains 1 per op).
        """
        return None

    def state_elem_bounds(self) -> Optional[Sequence[int]]:
        """Per-element EXCLUSIVE upper bounds on the state vector, or None.

        The contract: from any state whose elements are within bounds,
        any ok step whose ARG is in the declared command domains (resps
        arbitrary) yields a state whose elements are within bounds, and
        the initial state is within bounds.  Declaring this lets the
        device backend pack small vector states into one scalar
        (ops/scalarize.py) and ride the step-table gather fast path the
        scalar specs use; the packing is a bijection, so verdicts are
        unchanged (iteration counts agree up to memo hash-collision
        luck — the cache key width changes).
        """
        return None

    def native_kernel(self) -> Optional[Tuple[int, int, int]]:
        """(kind, p0, p1) selecting a built-in C++ step kernel in
        qsm_tpu/native/wg.cpp, or None.  Scalar-table specs need none (the
        native checker drives them through the compiled domain table);
        vector-state specs opt in by returning their kernel id + params —
        the C++ side reimplements ``step_py`` exactly, and the parity
        suite (tests/test_native.py) pins the equivalence."""
        return None

    # -- decomposition ----------------------------------------------------
    def partition_key(self, cmd: int, arg: int) -> Optional[int]:
        """Key for P-compositionality decomposition, or None if the spec is
        not per-key decomposable.  Sound only when sub-histories for distinct
        keys are independent (PAPERS.md:5)."""
        return None

    # -- persistence ------------------------------------------------------
    def spec_kwargs(self) -> dict:
        """Constructor kwargs that reproduce this spec exactly.

        Persisted in regression files so a failure captured against a
        non-default spec (e.g. ``KvSpec(n_keys=8)``) replays against the
        SAME spec instead of silently rebuilding registry defaults
        (ADVICE.md round 1).  Subclasses with constructor parameters MUST
        override.
        """
        return {}

    # -- derived ----------------------------------------------------------
    @property
    def n_cmds(self) -> int:
        return len(self.CMDS)

    @property
    def max_resps(self) -> int:
        return max(c.n_resps for c in self.CMDS)

    def resp_domain(self, cmd: int) -> range:
        return range(self.CMDS[cmd].n_resps)


def compile_step_table(spec: Spec, n_states: int):
    """Tabulate ``step_py`` for specs whose packed state fits one scalar.

    Returns ``(trans, ok)`` with shapes ``[n_states, n_cmds, max_args,
    max_resps]``; used by tests to cross-check the py/jax step functions
    exhaustively (SURVEY.md §7 design stance: the step-table compiler).
    Requires ``STATE_DIM == 1`` and state values in ``[0, n_states)``.
    """
    assert spec.STATE_DIM == 1, "step tables only for scalar-state specs"
    max_args = max(c.n_args for c in spec.CMDS)
    max_resps = spec.max_resps
    trans = np.zeros((n_states, spec.n_cmds, max_args, max_resps), np.int32)
    ok = np.zeros((n_states, spec.n_cmds, max_args, max_resps), bool)
    for s in range(n_states):
        for c, sig in enumerate(spec.CMDS):
            for a in range(sig.n_args):
                for r in range(sig.n_resps):
                    ns, good = spec.step_py([s], c, a, r)
                    trans[s, c, a, r] = ns[0]
                    ok[s, c, a, r] = good
    return trans, ok


# Selectivity probing caps at this many states: the table is a search
# HEURISTIC (candidate try-order — qsm_tpu/search/ordering.py), never a
# soundness input, so a deterministic stride sample of a huge packed
# domain (stack/queue shadows reach 10⁴–10⁵ states) estimates the same
# ranks at a bounded compile cost.
MAX_SELECTIVITY_PROBE_STATES = 512


def compile_selectivity_table(
    spec: Spec, n_states: int,
    max_probe_states: int = MAX_SELECTIVITY_PROBE_STATES,
) -> np.ndarray:
    """Postcondition selectivity per (cmd, arg, resp): the fraction of
    scalar states in ``[0, n_states)`` whose ``step_py`` accepts the op.

    Compiled alongside :func:`compile_step_table` (same scalar-domain
    contract, same ``step_py`` source of truth) and consumed by the
    search plane's candidate ordering: low selectivity = the op's
    postcondition holds almost nowhere = trying it first either prunes
    hardest or exposes the dead branch at depth 1.  Domains larger than
    ``max_probe_states`` are stride-sampled deterministically — the
    result is a rank estimate, which is all ordering needs (verdicts
    never depend on it).
    """
    assert spec.STATE_DIM == 1, \
        "selectivity tables only for scalar-state specs"
    if n_states <= max_probe_states:
        # the canonical tabulation IS the selectivity source: one loop to
        # maintain, and the ordering heuristic can never disagree with
        # the ok-table the kernels' gather path is built from
        _, ok = compile_step_table(spec, n_states)
        return ok.mean(axis=0, dtype=np.float64)
    max_args = max(c.n_args for c in spec.CMDS)
    max_resps = spec.max_resps
    stride = -(-n_states // max_probe_states)
    states = range(0, n_states, stride)
    sel = np.zeros((spec.n_cmds, max_args, max_resps), np.float64)
    n_probed = 0
    for s in states:
        n_probed += 1
        for c, sig in enumerate(spec.CMDS):
            for a in range(sig.n_args):
                for r in range(sig.n_resps):
                    _, good = spec.step_py([s], c, a, r)
                    if good:
                        sel[c, a, r] += 1.0
    return sel / max(n_probed, 1)
