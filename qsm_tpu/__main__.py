from .utils.cli import main

raise SystemExit(main())
