"""Wing–Gong linearisability checking on CPU — the parity oracle.

Faithful reimplementation of the interleaving search in the reference's
``Test.StateMachine.Linearise`` (BASELINE.json:5; algorithm shape per
SURVEY.md §3.2): build every real-time-precedence-respecting interleaving of
the concurrent history lazily, stepping ``transition`` and checking
``postcondition`` at each node, succeeding iff SOME root-to-leaf path is
all-ok.  Worst case O(n!).

This backend is (a) the verdict oracle the TPU kernel must match bit-for-bit
and (b) the benchmark denominator for the ≥100× target (BASELINE.md).  It is
deliberately a direct DFS like the reference's; an optional Lowe-style
memoisation cache (``memo=True``) is provided for *testing at larger sizes*
but is off for baseline measurement.

Pending operations (invoked, no response — produced by fault injection) are
handled the way the reference's complete/prune step is described (SURVEY.md
§3.2): a pending op may be linearised with ANY response in its domain (it took
effect, the response was lost) or never linearised at all (it did not take
effect).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec
from .backend import Verdict

_DEFAULT_NODE_BUDGET = 50_000_000


class WingGongCPU:
    """Pure-Python Wing–Gong DFS backend (the oracle)."""

    name = "wing_gong_cpu"

    def __init__(self, node_budget: int = _DEFAULT_NODE_BUDGET,
                 memo: bool = False, ordering: bool = False):
        self.node_budget = node_budget
        self.memo = memo
        # Postcondition-aware candidate try order (search/ordering.py):
        # rank ops by selectivity so branches that must fail their
        # postcondition die at depth 1.  Verdicts are invariant under try
        # order (the DFS explores the same tree, differently); only
        # nodes_explored changes.  Off by default — the canonical index
        # order is the parity reference every kernel is pinned against.
        self.ordering = ordering
        self._ordering_tables: dict = {}  # (name, kwargs) -> OrderingTable|None
        self.nodes_explored = 0  # cumulative, for stats/benchmarks
        self.histories_checked = 0

    # ------------------------------------------------------------------
    def check_histories(
        self, spec: Spec, histories: Sequence[History]
    ) -> np.ndarray:
        out = np.empty(len(histories), np.int8)
        for i, h in enumerate(histories):
            out[i] = self._check(spec, h)
        return out

    # ------------------------------------------------------------------
    def check_from(self, spec: Spec, history: History,
                   init_state) -> Verdict:
        """Linearizability from an explicit model state (used by the
        decrease-and-conquer segmentation combinator, which threads frontier
        states through quiescent cuts — ops/segdc.py)."""
        return self._check(spec, history, init_state=init_state)

    # ------------------------------------------------------------------
    def check_witness(self, spec: Spec, history: History):
        """(verdict, witness) — the witness is the successful
        linearization as a list of ``(op_index, resp)`` pairs in
        linearization order (op_index into ``history.ops``; resp is the
        chosen completion for pending ops, the op's own otherwise), or
        None when the verdict is not LINEARIZABLE.  A LINEARIZABLE
        verdict thus carries its own proof: ``verify_witness``
        (ops/backend.py) replays it independently of any search."""
        witness: List[tuple] = []
        v = self._check(spec, history, witness_out=witness)
        return v, (list(reversed(witness))
                   if v == Verdict.LINEARIZABLE else None)

    # ------------------------------------------------------------------
    def search_stats(self):
        """Host-search cost record (search/stats.py): oracle node count
        per history is the denominator the device's iters-per-history is
        judged against."""
        from ..search.stats import SearchStats

        return SearchStats(
            engine=self.name + ("_memo" if self.memo else ""),
            histories=self.histories_checked,
            nodes_explored=self.nodes_explored,
            ordering=self.ordering,
        )

    def _try_order(self, spec: Spec, history: History) -> Sequence[int]:
        if not self.ordering:
            return range(len(history.ops))
        # cache key includes the constructor kwargs: two
        # differently-parameterized specs sharing a name (CasSpec
        # n_values=2 vs 8) must not reuse each other's table
        key = (spec.name, repr(spec.spec_kwargs()))
        if key not in self._ordering_tables:
            from ..search.ordering import ordering_table

            self._ordering_tables[key] = ordering_table(spec)
        from ..search.ordering import order_indices

        return order_indices(self._ordering_tables[key], history)

    # ------------------------------------------------------------------
    def _check(self, spec: Spec, history: History,
               init_state=None, witness_out=None) -> Verdict:
        ops = history.ops
        n = len(ops)
        self.histories_checked += 1
        if n == 0:
            return Verdict.LINEARIZABLE
        prec = history.precedes_matrix()
        # blockers[j] = list of i that must be linearised before j may be.
        blockers: List[List[int]] = [
            [i for i in range(n) if prec[i, j]] for j in range(n)
        ]
        pending = [o.is_pending for o in ops]
        n_required = sum(1 for p in pending if not p)
        init = tuple(int(v) for v in (spec.initial_state()
                                      if init_state is None else init_state))

        taken = [False] * n
        budget = [self.node_budget]
        seen = set() if self.memo else None
        order = self._try_order(spec, history)

        def eligible(j: int) -> bool:
            if taken[j]:
                return False
            for i in blockers[j]:
                if not taken[i]:
                    return False
            return True

        def dfs(state, got_required: int) -> Verdict:
            if got_required == n_required:
                return Verdict.LINEARIZABLE
            if budget[0] <= 0:
                return Verdict.BUDGET_EXCEEDED
            if seen is not None:
                key = (state, tuple(taken))
                if key in seen:
                    return Verdict.VIOLATION
            saw_budget = False
            for j in order:
                if not eligible(j):
                    continue
                op = ops[j]
                resps = (spec.resp_domain(op.cmd) if pending[j]
                         else (op.resp,))
                for resp in resps:
                    budget[0] -= 1
                    self.nodes_explored += 1
                    if budget[0] <= 0:
                        return Verdict.BUDGET_EXCEEDED
                    new_state, ok = spec.step_py(list(state), op.cmd,
                                                 op.arg, resp)
                    if not ok:
                        continue
                    taken[j] = True
                    sub = dfs(tuple(int(v) for v in new_state),
                              got_required + (0 if pending[j] else 1))
                    taken[j] = False
                    if sub == Verdict.LINEARIZABLE:
                        if witness_out is not None:
                            # success unwinds deepest-first; caller
                            # reverses into linearization order
                            witness_out.append((j, resp))
                        return sub
                    if sub == Verdict.BUDGET_EXCEEDED:
                        saw_budget = True
            if saw_budget:
                return Verdict.BUDGET_EXCEEDED
            if seen is not None:
                seen.add((state, tuple(taken)))
            return Verdict.VIOLATION

        return dfs(init, 0)
