"""``PallasTPU`` — Pallas (Mosaic) prototype of the scalar-table search.

WHY THIS EXISTS (VERDICT.md round 4, "Next round" #4; SURVEY.md §7 M8):
both banked real-TPU windows showed the XLA ``lax.while_loop`` driver
paying ~5 ms per sequential loop TRIP on the axon tunnel — a dispatch
floor that neither lane width nor the freeze-guarded UNROLL measurably
cut on-chip.  SURVEY.md names Pallas as the escalation when XLA
while-loop behavior caps the kernel: a Pallas kernel runs its WHOLE
iteration chunk inside one Mosaic kernel launch, so per-trip cost is VPU
arithmetic, not XLA loop-trip overhead.  This module is the measured
A/B, not a replacement: scope is deliberately the scalar-table fast path
only (CAS / register / ticket / set — ``scalar_state_bound`` specs, the
headline configuration), ≤32 ops (one-word bitmasks), with a per-lane
memo cache matching the XLA kernel's pruning economics (below).

Design — the same branchless DFS as ops/jax_kernel.py, transposed:

* lanes ride the MINOR axis (``[…, L]`` with L a multiple of 128) so
  every per-op / per-depth sweep is an (8,128)-tiled VPU op;
* the per-lane DFS state is the same explicit stack (``taken``,
  ``chosen``, ``states``, depth/status/iters), selected and updated with
  one-hot mask arithmetic — no scatters, no per-lane dynamic slices;
* precedence is packed into one uint32 word per op (``prec_word[j]`` =
  bitmask of ops that must precede j), so the minimality mask is a
  single word-AND against the untaken bitmask (N ≤ 32 makes W = 1);
* the step table is precomputed per lane OUTSIDE the kernel (one jitted
  ``vmap`` of ``spec.step_jax`` over lanes × states × ops) and gathered
  in-kernel by a one-hot sweep over the S = ``scalar_state_bound``
  states (S ≤ ~8 for every table spec in-tree);
* one ``pallas_call`` advances every lane by exactly ``chunk``
  iterations via ``jax.lax.fori_loop``; decided lanes no-op through the
  remaining trips (the same freeze-guard contract as the XLA kernel's
  UNROLL micro-steps);
* a per-lane memoisation cache (Lowe-style, the same contract as the
  XLA kernel's: configurations proven non-linearizable-from are
  inserted on subtree exhaustion, child configurations already present
  are pruned without descending) lives in VMEM as three
  ``[slots, L]`` planes — key word 0 the taken bitmask, key word 1 the
  scalar state, plus occupancy.  Lookup/insert are one-hot sweeps over
  ``slots`` (≤64), soundness-safe under collision exactly like the XLA
  cache: a lost entry only loses a pruning opportunity.  Without it a
  violating history must exhaust its whole tree and the A/B against the
  cache-equipped XLA kernel would compare different search economics.

Verdict semantics are identical to ``JaxTPU``: SUCCESS / FAILURE /
BUDGET_EXCEEDED (honest indecision), pending ops expanded host-side,
out-of-domain histories deferred to the oracle.  The host driver (class
``PallasTPU``) subclasses ``JaxTPU`` so all of that host logic is
inherited; only ``_run_device`` is replaced.

On the CPU platform the kernel runs in Pallas interpret mode (Mosaic
compiles only on a real TPU) — correct but slow, so tests keep corpora
tiny; the measured A/B lives in tools/bench_scale.py's ``pallas``
variant cell, which only a real device window runs.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import History, bucket_for, encode_batch
from .jax_kernel import BUDGET, FAILURE, RUNNING, SUCCESS, JaxTPU

MAX_PALLAS_OPS = 32     # one-word taken/precedence bitmasks
MAX_PALLAS_STATES = 64  # the in-kernel state gather is a one-hot sweep
# over S rows (O(S·N) VPU work per trip) and the step table lives in VMEM
# as [S, N, L] — S=1280 (the queue/stack scalarized shadows) would blow
# both; every non-vector spec in-tree is ≤49


def build_pallas_chunk(spec, n_ops: int, state_bound: int, lanes: int,
                       chunk: int, budget: int, interpret: bool,
                       cache_slots: int = 0):
    """One compiled pallas_call advancing ``lanes``-wide blocks by
    ``chunk`` DFS iterations.  Returns ``fn(tables, carry) -> carry`` over
    lane-minor arrays (see module docstring for layouts).
    ``cache_slots`` > 0 (a power of two) enables the per-lane VMEM memo
    cache; the carry then grows ``ck0``/``ck1``/``occ`` planes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    N, S, L = n_ops, state_bound, lanes
    use_cache = cache_slots > 0
    if cache_slots < 0 or (cache_slots & (cache_slots - 1)) != 0:
        raise ValueError(
            f"cache_slots must be 0 or a power of two, got {cache_slots}")

    # ALL word/bitmask math below is int32, not uint32, and NO
    # jnp.sum/any/min reductions appear inside the kernel: the pinned
    # Mosaic lowering implements no integer reductions AT ALL
    # ("Reductions over integers not implemented" — caught by the
    # cross-platform lowering check, tests/test_pallas.py; the first
    # version assumed only unsigned reductions were missing and would
    # have failed its first real-chip window).  Every one-hot
    # select/pack below therefore reduces via the statically unrolled
    # helpers `_sum0`/`_any0`/`_min0` — elementwise adds/ors/mins over
    # the small static leading axis (N+1 ≤ 33, S ≤ 64, slots ≤ 64),
    # bit-identical to the reduction form: packed-word sums have one
    # distinct bit per term (sum == or, no carries), XLA integer ops
    # wrap two's-complement, and right-shifts use shift_right_logical
    # explicitly.
    def _sum0(x):
        acc = x[0]
        for i in range(1, x.shape[0]):
            acc = acc + x[i]
        return acc

    def _any0(x):
        acc = x[0]
        for i in range(1, x.shape[0]):
            acc = acc | x[i]
        return acc

    def _min0(x):
        acc = x[0]
        for i in range(1, x.shape[0]):
            acc = jnp.minimum(acc, x[i])
        return acc

    def _i32(x):
        return jnp.asarray(np.int64(x).astype(np.int32) if x > 0x7FFFFFFF
                           else x, jnp.int32)

    def _hash(word, state):
        """Per-lane slot hash over the (taken-word, state) key — a word
        mixer in the same spirit as the XLA kernel's (independent table,
        no cross-kernel bit-compat needed; only distribution matters)."""
        srl = jax.lax.shift_right_logical
        h = _i32(0x9E3779B9) ^ word
        h = h * _i32(0x85EBCA6B)
        h = h ^ srl(h, 16)
        h = h ^ state
        h = h * _i32(0xC2B2AE35)
        h = h ^ srl(h, 13)
        return h & jnp.int32(cache_slots - 1)

    def kernel(nxt_ref, ok_ref, prec_ref, valid_ref, nreq_ref,
               taken_ref, chosen_ref, states_ref, dsi_ref,
               ck0_ref, ck1_ref, occ_ref,
               taken_o, chosen_o, states_o, dsi_o,
               ck0_o, ck1_o, occ_o):
        nxt_tab = nxt_ref[:]        # [S, N, L] int32
        ok_tab = ok_ref[:]          # [S, N, L] int32 (0/1)
        prec = prec_ref[:]          # [N, L] int32 (packed predecessor bits)
        valid = valid_ref[:]        # [N, L] int32 (0/1)
        nreq = nreq_ref[0, :]       # [L]

        nio = jax.lax.broadcasted_iota(jnp.int32, (N, L), 0)
        kio = jax.lax.broadcasted_iota(jnp.int32, (N + 1, L), 0)
        sio = jax.lax.broadcasted_iota(jnp.int32, (S, L), 0)
        shift = nio
        cio = (jax.lax.broadcasted_iota(jnp.int32, (cache_slots, L), 0)
               if use_cache else None)

        def body(_, c):
            taken, chosen, states, d, status, iters, ck0, ck1, occ = c
            active = status == RUNNING                       # [L]
            dm = (kio == d[None, :]).astype(jnp.int32)       # [N+1, L]
            state = _sum0(states * dm)                       # [L]
            cur = _sum0(chosen * dm)                         # [L]
            untaken = valid * (1 - taken)                    # [N, L]
            uw = _sum0(untaken << shift)                     # [L] int32
            blocked = (prec & uw[None, :]) != 0              # [N, L]
            sm = (sio == state[None, :]).astype(jnp.int32)   # [S, L]
            ok_row = _sum0(ok_tab * sm[:, None, :])          # [N, L]
            nxt_row = _sum0(nxt_tab * sm[:, None, :])        # [N, L]
            cand = ((untaken == 1) & ~blocked & (ok_row == 1)
                    & (nio > cur[None, :]))                  # [N, L]
            has = _any0(cand)                                # [L]
            jstar = _min0(jnp.where(cand, nio, N))
            jm = (nio == jstar[None, :]).astype(jnp.int32)   # [N, L]
            child = _sum0(nxt_row * jm)                      # [L]
            success = has & (d + 1 == nreq)

            if use_cache:
                taken_word = _sum0(taken << shift)               # [L]
                child_word = taken_word | (
                    jnp.int32(1) << jnp.minimum(jstar, N - 1))
                slot_c = _hash(child_word, child)                # [L]
                sel_c = cio == slot_c[None, :]                   # [slots, L]
                hit = _any0(sel_c & (occ == 1)
                            & (ck0 == child_word[None, :])
                            & (ck1 == child[None, :]))
                prune = has & hit & ~success & active
            else:
                prune = jnp.zeros_like(has)  # all-False (has is bool)
            descend = has & active & ~prune
            d_back = jnp.maximum(d - 1, 0)
            dbm = (kio == d_back[None, :]).astype(jnp.int32)
            prev = jnp.maximum(_sum0(chosen * dbm), 0)
            back = active & ~has & (d > 0)

            taken_n = jnp.where(
                descend[None, :], jnp.maximum(taken, jm),
                jnp.where(back[None, :] & (nio == prev[None, :]),
                          0, taken))
            # descend: chosen[d]=j, chosen[d+1]=-1; prune: cursor moves
            # past j at the SAME depth (chosen[d]=j, nothing else)
            chosen_n = jnp.where(
                (descend | prune)[None, :] & (kio == d[None, :]),
                jstar[None, :],
                jnp.where(descend[None, :] & (kio == d[None, :] + 1),
                          -1, chosen))
            states_n = jnp.where(
                descend[None, :] & (kio == d[None, :] + 1),
                child[None, :], states)
            d_n = jnp.where(descend, d + 1,
                            jnp.where(active & ~prune, d_back, d))
            iters_n = iters + active.astype(jnp.int32)
            status_n = jnp.where(
                active & success, SUCCESS,
                jnp.where(active & ~has & (d == 0), FAILURE, status))
            status_n = jnp.where(
                (status_n == RUNNING) & (iters_n >= budget),
                BUDGET, status_n)
            if use_cache:
                # exhausted (no candidates): this configuration is proven
                # non-linearizable-from — insert before backtracking
                exhausted = active & ~has
                slot_i = _hash(taken_word, state)
                wmask = (cio == slot_i[None, :]) & exhausted[None, :]
                ck0_n = jnp.where(wmask, taken_word[None, :], ck0)
                ck1_n = jnp.where(wmask, state[None, :], ck1)
                occ_n = jnp.where(wmask, 1, occ)
            else:
                ck0_n, ck1_n, occ_n = ck0, ck1, occ
            return (taken_n, chosen_n, states_n, d_n, status_n, iters_n,
                    ck0_n, ck1_n, occ_n)

        init = (taken_ref[:], chosen_ref[:], states_ref[:],
                dsi_ref[0, :], dsi_ref[1, :], dsi_ref[2, :],
                ck0_ref[:], ck1_ref[:], occ_ref[:])
        (taken, chosen, states, d, status, iters,
         ck0, ck1, occ) = jax.lax.fori_loop(0, chunk, body, init)
        taken_o[:] = taken
        chosen_o[:] = chosen
        states_o[:] = states
        dsi_o[0, :] = d
        dsi_o[1, :] = status
        dsi_o[2, :] = iters
        ck0_o[:] = ck0
        ck1_o[:] = ck1
        occ_o[:] = occ

    CS = max(cache_slots, 1)  # shape floor: slots=0 rides 1-row dummies

    def fn(nxt, ok, prec, valid, nreq, taken, chosen, states, dsi,
           ck0, ck1, occ):
        B = nxt.shape[-1]
        grid = (B // L,)
        lane2 = lambda rows: pl.BlockSpec(  # noqa: E731
            (rows, L), lambda i: (0, i))
        out_shape = (
            jax.ShapeDtypeStruct((N, B), jnp.int32),
            jax.ShapeDtypeStruct((N + 1, B), jnp.int32),
            jax.ShapeDtypeStruct((N + 1, B), jnp.int32),
            jax.ShapeDtypeStruct((3, B), jnp.int32),
            jax.ShapeDtypeStruct((CS, B), jnp.int32),
            jax.ShapeDtypeStruct((CS, B), jnp.int32),
            jax.ShapeDtypeStruct((CS, B), jnp.int32),
        )
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((S, N, L), lambda i: (0, 0, i)),
                pl.BlockSpec((S, N, L), lambda i: (0, 0, i)),
                lane2(N),
                lane2(N),
                lane2(1),
                lane2(N),
                lane2(N + 1),
                lane2(N + 1),
                lane2(3),
                lane2(CS),
                lane2(CS),
                lane2(CS),
            ],
            out_specs=(
                lane2(N),
                lane2(N + 1),
                lane2(N + 1),
                lane2(3),
                lane2(CS),
                lane2(CS),
                lane2(CS),
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(nxt, ok, prec, valid, nreq, taken, chosen, states, dsi,
          ck0, ck1, occ)

    return jax.jit(fn)


class PallasTPU(JaxTPU):
    """Pallas-kernel backend for scalar-table specs (prototype).

    Inherits every host-side contract from :class:`JaxTPU` (pending
    expansion, domain gating, scalarized shadows, witness plumbing) and
    replaces only the device driver.  Raises at construction for specs
    outside the prototype's scope — use ``JaxTPU`` there."""

    name = "pallas_tpu"

    LANES = 256          # lanes per Mosaic block (minor axis; 128-mult)
    PALLAS_CHUNK = 1024  # DFS iterations per pallas_call
    # Per-lane memo cache slots (power of two; 0 disables).  64 slots ≈
    # 192 KB VMEM per 256-lane block — the economics leveller vs the
    # cache-equipped XLA kernel (module docstring); pruning-only effect,
    # verdicts identical (tests/test_pallas.py pins both).
    PALLAS_CACHE_SLOTS = 64

    def __init__(self, spec, budget: int = 2_000, interpret=None, **kw):
        super().__init__(spec, budget=budget, **kw)
        if not self._uses_table:
            raise ValueError(
                "PallasTPU covers scalar-table specs only (CAS / register "
                "/ ticket / set — scalar_state_bound); use JaxTPU")
        bound = self.kspec.scalar_state_bound(MAX_PALLAS_OPS)
        if bound is None or bound > MAX_PALLAS_STATES:
            raise ValueError(
                f"PallasTPU covers scalar-table specs with state bound "
                f"<= {MAX_PALLAS_STATES} (got {bound}); use JaxTPU")
        self.interpret = interpret  # None = auto (interpret off-TPU)
        self._pallas_fns: Dict[Tuple, object] = {}
        self._table_fns: Dict[int, object] = {}
        self.pallas_calls = 0
        self.pallas_trips = 0  # chunk iterations dispatched (per lane)

    def _interpret(self) -> bool:
        if self.interpret is not None:
            return self.interpret
        import jax

        return jax.default_backend() != "tpu"

    def _chunk_kernel(self, n_ops: int, state_bound: int):
        key = (n_ops, state_bound, self.PALLAS_CHUNK, self._interpret(),
               self.PALLAS_CACHE_SLOTS)
        fn = self._pallas_fns.get(key)
        if fn is None:
            fn = build_pallas_chunk(self.kspec, n_ops, state_bound,
                                    self.LANES, self.PALLAS_CHUNK,
                                    self.total_budget, self._interpret(),
                                    cache_slots=self.PALLAS_CACHE_SLOTS)
            self._pallas_fns[key] = fn
        return fn

    def _table_fn(self, n_ops: int):
        """Jitted per-lane step-table builder:
        (cmd[B,N], arg, resp) -> (nxt[B,S,N], ok[B,S,N])."""
        fn = self._table_fns.get(n_ops)
        if fn is None:
            import jax
            import jax.numpy as jnp

            S = self.kspec.scalar_state_bound(n_ops)
            spec = self.kspec

            def one(cmd, arg, resp):
                def row(s):
                    st = jnp.full((1,), s, jnp.int32)
                    nxt, ok = jax.vmap(
                        lambda cc, aa, rr: spec.step_jax(st, cc, aa, rr),
                        out_axes=(0, 0))(cmd, arg, resp)
                    return (nxt.reshape(-1).astype(jnp.int32),
                            ok.reshape(-1).astype(jnp.int32))

                return jax.vmap(row)(jnp.arange(S, dtype=jnp.int32))

            fn = jax.jit(jax.vmap(one))
            self._table_fns[n_ops] = fn
        return fn

    # -- the pallas driver: flat batch in, statuses out -------------------
    def _run_device(self, flat: Sequence[History],
                    flat_inits: Optional[List] = None,
                    collect_chosen: bool = False):
        import jax.numpy as jnp

        top = self.MAX_BATCH
        if len(flat) > top:
            parts = [
                self._run_device(
                    flat[i:i + top],
                    flat_inits[i:i + top] if flat_inits else None,
                    collect_chosen=collect_chosen)
                for i in range(0, len(flat), top)]
            if collect_chosen:
                width = max(p[1].shape[1] for p in parts)
                padded = [np.pad(p[1], ((0, 0), (0, width - p[1].shape[1])),
                                 constant_values=-1) for p in parts]
                return (np.concatenate([p[0] for p in parts]),
                        np.concatenate(padded))
            return np.concatenate(parts)

        # Postcondition-aware try order, same host-side permutation as the
        # XLA driver (search/ordering.py): the in-kernel `_min0` candidate
        # pick then tries the most constrained ops first.  Witness indices
        # are mapped back through the permutation below.
        perms = None
        if self._ordering_table is not None:
            from ..search.ordering import permute_history

            perms = [self._ordering_table.permutation(h) for h in flat]
            flat = [permute_history(h, p) for h, p in zip(flat, perms)]

        n_ops = bucket_for(max(len(h) for h in flat) or 1)
        if n_ops > MAX_PALLAS_OPS:
            raise ValueError(
                f"PallasTPU covers ≤{MAX_PALLAS_OPS} ops (one-word "
                f"bitmasks); got bucket {n_ops} — use JaxTPU")
        S = self.kspec.scalar_state_bound(n_ops)
        enc = encode_batch(flat, self.kspec.initial_state(), max_ops=n_ops)
        b = len(flat)
        B = ((b + self.LANES - 1) // self.LANES) * self.LANES  # lane pad
        N = n_ops

        cmd = enc.ops[:, :, 1].astype(np.int32)
        arg = enc.ops[:, :, 2].astype(np.int32)
        resp = enc.ops[:, :, 3].astype(np.int32)
        valid = enc.valid.astype(bool)
        prec = enc.precedes().astype(bool)          # [b, N, N] i precedes j
        inits = np.tile(np.asarray(enc.init_state, np.int32), (b, 1))
        if flat_inits is not None:
            for i, s in enumerate(flat_inits):
                inits[i] = (np.asarray([self._shadow.pack(s)], np.int32)
                            if self._shadow is not None
                            else np.asarray(s, np.int32))

        # per-lane step tables (one jitted call), then lane-minor layout
        nxt_t, ok_t = self._table_fn(n_ops)(
            jnp.asarray(cmd), jnp.asarray(arg), jnp.asarray(resp))
        nxt = np.zeros((S, N, B), np.int32)
        ok = np.zeros((S, N, B), np.int32)
        nxt[:, :, :b] = np.transpose(np.asarray(nxt_t), (1, 2, 0))
        ok[:, :, :b] = np.transpose(np.asarray(ok_t), (1, 2, 0))
        prec_word = np.zeros((N, B), np.int32)
        pw = (prec.astype(np.uint64)
              << np.arange(N, dtype=np.uint64)[None, :, None]).sum(axis=1)
        prec_word[:, :b] = pw.astype(np.uint32).view(np.int32).T
        valid_lm = np.zeros((N, B), np.int32)
        valid_lm[:, :b] = valid.T
        nreq = np.zeros((1, B), np.int32)
        nreq[0, :b] = valid.sum(axis=1)

        taken = np.zeros((N, B), np.int32)
        chosen = np.full((N + 1, B), -1, np.int32)
        states = np.zeros((N + 1, B), np.int32)
        states[0, :b] = inits[:, 0]
        dsi = np.zeros((3, B), np.int32)
        # padding lanes (and genuinely empty histories) have n_req == 0:
        # immediately SUCCESS, frozen through every trip
        dsi[1] = np.where(nreq[0] == 0, SUCCESS, RUNNING)

        fn = self._chunk_kernel(n_ops, S)
        CS = max(self.PALLAS_CACHE_SLOTS, 1)  # dummy row when disabled
        tables = (jnp.asarray(nxt), jnp.asarray(ok),
                  jnp.asarray(prec_word), jnp.asarray(valid_lm),
                  jnp.asarray(nreq))
        carry = (jnp.asarray(taken), jnp.asarray(chosen),
                 jnp.asarray(states), jnp.asarray(dsi),
                 jnp.zeros((CS, B), jnp.int32),
                 jnp.zeros((CS, B), jnp.int32),
                 jnp.zeros((CS, B), jnp.int32))
        max_calls = -(-self.total_budget // self.PALLAS_CHUNK)
        for _ in range(max_calls):
            carry = fn(*tables, *carry)
            self.pallas_calls += 1
            self.pallas_trips += self.PALLAS_CHUNK
            status_h = np.asarray(carry[3][1])
            self.rounds_run += 1
            self.lockstep_cost += self.PALLAS_CHUNK * B
            if not (status_h == RUNNING).any():
                break
        status_h = np.asarray(carry[3][1])[:b].astype(np.int32)
        # any lane still RUNNING after the budget's worth of chunks is
        # honest indecision (belt and braces; in-kernel budget already
        # flips these to BUDGET)
        status_h = np.where(status_h == RUNNING, BUDGET, status_h)
        self.device_histories += b
        self.batches_run += 1
        if collect_chosen:
            chosen_h = np.asarray(carry[1]).T[:b]
            if perms is not None:
                for i, p in enumerate(perms):
                    row = chosen_h[i]
                    m = (row >= 0) & (row < len(p))
                    row[m] = p[row[m]]
            return status_h, chosen_h
        return status_h
