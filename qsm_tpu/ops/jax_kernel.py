"""``JaxTPU`` — the batched Wing–Gong branch-and-bound kernel.

This is the TPU replacement for the reference's pure, single-threaded
``Test.StateMachine.Linearise`` DFS (SURVEY.md §3.2; the north-star of
BASELINE.json:5): thousands of candidate histories are decided in ONE
``vmap``'d device call.

Mapping dynamic search onto static XLA shapes (SURVEY.md §7 hard-parts #1)
---------------------------------------------------------------------------
Wing–Gong is a backtracking DFS with data-dependent branching.  Here it runs
as a ``lax.while_loop`` over an explicit fixed-size stack:

* ``taken``   bool[N]        — ops already linearised on the current path
* ``chosen``  int32[N+1]     — op index picked at each depth; doubles as the
  sibling cursor (at depth ``d`` only indices ``> chosen[d]`` are tried, so
  backtracking resumes exactly where the recursion would)
* ``states``  int32[N+1, S]  — model-state stack (packed int vectors, so
  queue/KV specs avoid exponential step tables — hard-parts #2)

Each iteration is one DFS transition {descend | advance-sibling | backtrack},
chosen branchlessly:

* candidate mask = untaken ∧ precedence-minimal ∧ postcondition-ok ∧ beyond
  cursor; precedence-minimality is a masked any() over the precomputed strict
  precedes matrix (``resp_i < inv_j``), and postconditions for ALL ops are
  evaluated vectorised from the current state (one ``vmap`` of
  ``spec.step_jax`` — most branches die here, which is what keeps typical
  search trees tiny despite the O(n!) worst case)
* first candidate via ``argmax`` of the bool mask (same canonical op order as
  the CPU oracle, so explored trees — and therefore verdicts — agree)

Taming worst-case blowups — the CHUNKED, LANE-COMPACTING driver (round 3)
-------------------------------------------------------------------------
The DFS state above is a **resumable carry**: ``build_stepper`` exposes the
loop as ``init`` + ``run(carry, …, chunk=K)``, where one call advances every
lane by at most ``K`` iterations and returns the exact mid-search state.
:class:`JaxTPU` drives batches in escalating chunks:

1. run a chunk; lanes that decided leave the batch;
2. survivors are **compacted** into the smallest batch bucket that holds
   them (a vmapped while-loop is lockstep — decided lanes otherwise idle at
   full batch width while the worst lane spins; compaction is the fix the
   round-2 verdict demanded);
3. as the batch shrinks, the per-lane **memoisation cache** (Lowe-style:
   configurations ``(taken-set, state)`` proven non-linearizable-from,
   inserted on subtree exhaustion, pruned on re-entry) GROWS within the
   empirically verified-safe (batch × cache_slots) region; existing entries
   are re-hashed host-side into the larger table (``hash_slots_np`` is the
   numpy mirror of the in-kernel mixer), so no pruning knowledge is lost;
4. a lane whose cumulative iterations reach the total budget reports
   BUDGET_EXCEEDED honestly and the property layer resolves it via the CPU
   oracle, keeping CPU/TPU verdicts bit-identical (hard-parts #5).

Unlike the round-2 rescue ladder, a rescue never restarts a search from
iteration zero — the carry resumes exactly where the previous chunk
stopped, and the whole schedule wastes at most one chunk of lockstep
spinning per decided lane.

Pending (crash/fault) ops are expanded host-side into complete histories —
every prune/complete×response combination (SURVEY.md §3.2 complete/prune) —
so the kernel itself only ever sees complete histories with static shapes.

Batching: ``vmap`` over histories (≥1024 per call — BASELINE.json:9); batch
sizes and op counts are bucketed to bound recompilation.  Histories may
carry **per-lane initial states** (``check_histories(..., init_states=…)``,
or ``check_from`` for one) — that is what lets the decrease-and-conquer
segmentation combinator (ops/segdc.py) decide final segments from frontier
states on the device.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import History, bucket_for, encode_batch
from ..core.spec import Spec
from .backend import Verdict

RUNNING = 0
SUCCESS = 1  # == Verdict.LINEARIZABLE
FAILURE = 2
BUDGET = 3

_BATCH_BUCKETS = (8, 64, 256, 1024, 4096, 16384, 65536,
                  262144)


def _batch_bucket(b: int, buckets: tuple = _BATCH_BUCKETS) -> int:
    """Smallest bucket holding ``b`` rows; callers split batches larger than
    ``JaxTPU.MAX_BATCH`` into chunks that size so the compile cache stays
    bounded.  The buckets above 4096 exist for the real chip, where the
    first banked window (BENCH_TPU_r04.json) showed per-trip latency, not
    lane width, dominating the lockstep loop — wider batches amortize it;
    they are reachable only through an explicitly raised ``MAX_BATCH``.
    A :class:`~qsm_tpu.search.planner.SearchPlan` may substitute a finer
    ladder (``JaxTPU.BATCH_BUCKETS``): on the CPU platform single-lane
    buckets stop a straggler's exhaustion from paying padded width."""
    for s in buckets:
        if b <= s:
            return s
    return buckets[-1]


def make_hash_slot(key_words: int, cache_slots: int):
    """The kernel's slot hash: murmur3-fmix-style word mixer.

    NOT FNV-1a: FNV is byte-oriented and over 32-bit words its small
    multiplier never propagates high bits downward, so keys differing only
    in high taken-bits all collide in the low slot-index bits (regression:
    tests/test_cache.py).  The xor-shifts here fold high bits into the low
    bits each round.
    """
    import jax.numpy as jnp

    def hash_slot(key):
        h = jnp.uint32(0x9E3779B9)
        for i in range(key_words):  # static unroll
            h = h ^ key[i]
            h = h * jnp.uint32(0x85EBCA6B)
            h = h ^ (h >> 16)
            h = h * jnp.uint32(0xC2B2AE35)
            h = h ^ (h >> 13)
        return (h & jnp.uint32(cache_slots - 1)).astype(jnp.int32)

    return hash_slot


def hash_slots_np(keys: np.ndarray, cache_slots: int) -> np.ndarray:
    """Numpy mirror of :func:`make_hash_slot` over rows of ``keys``
    (uint32[M, key_words] -> int32[M]).  Used to re-hash surviving cache
    entries host-side when the compacting driver grows the table; MUST stay
    bit-identical to the kernel's mixer (tests/test_cache.py pins this)."""
    keys = np.asarray(keys, np.uint32)
    h = np.full(keys.shape[0], 0x9E3779B9, np.uint32)
    for i in range(keys.shape[1]):
        h = h ^ keys[:, i]
        h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
        h = h ^ (h >> np.uint32(16))
        h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
        h = h ^ (h >> np.uint32(13))
    return (h & np.uint32(cache_slots - 1)).astype(np.int32)


def build_stepper(spec: Spec, n_ops: int, budget: int,
                  cache_slots: int = 0, cache_write: str = "onehot",
                  unroll: int = 1):
    """Build the resumable single-history checker for one (spec, N) shape.

    Returns ``(init_one, run_one)``:

    * ``init_one(valid[N] bool, init_state[S]) -> carry`` — fresh DFS state
      (status SUCCESS immediately for empty histories);
    * ``run_one(carry, cmd[N], arg[N], resp[N], valid[N], precedes[N,N],
      chunk=None) -> carry`` — advance the search until it decides, the
      cumulative iteration count reaches ``budget`` (status BUDGET), or —
      when ``chunk`` is a static int — at most ``chunk`` more iterations
      ran.  Resuming with another ``run_one`` call continues the exact same
      search: the carry is the complete DFS state.

    ``cache_slots`` > 0 enables the in-kernel memoisation cache (Lowe-style,
    after the "just-in-time linearizability" cache): a per-history hash
    table of configurations ``(taken-set, model-state)`` proven
    non-linearizable-from.  A configuration is inserted when its subtree is
    exhausted without success, and a child configuration already in the
    table is pruned without descending.  Single-slot open addressing with
    FULL key comparison: collisions only lose pruning opportunities, never
    soundness.  This is what keeps violating histories (which must exhaust
    the whole tree) out of the exponential regime, exactly like the CPU
    oracle's ``memo=True``; verdicts are unchanged, only iteration counts.
    """
    import jax
    import jax.numpy as jnp

    iota = jnp.arange(n_ops, dtype=jnp.int32)
    iota1 = jnp.arange(n_ops + 1, dtype=jnp.int32)

    # Scalar-state specs declare a bound on reachable states; the kernel
    # then tabulates step(s, op_j) for every (state, op) pair ONCE per
    # chunk call (outside the while loop) and the loop body replaces the
    # vmapped step_jax sweep over all ops with a single dynamic row gather
    # — the dominant per-iteration cost in the v1 kernel (VERDICT.md round
    # 1, "Next round" #2).  Sound because ok-children of tabulated steps
    # are exactly the states the DFS can reach (the bound's contract).
    state_bound = (spec.scalar_state_bound(n_ops)
                   if spec.STATE_DIM == 1 else None)

    n_words = (n_ops + 31) // 32  # taken-bitmask words
    key_words = n_words + spec.STATE_DIM
    use_cache = cache_slots > 0
    # public-parameter validation: a non-power-of-two silently biases the
    # `h & (slots-1)` masking (dead slots), so refuse loudly — and not via
    # assert, which `python -O` strips (ADVICE.md round 1)
    if cache_slots < 0 or (cache_slots & (cache_slots - 1)) != 0:
        raise ValueError(
            f"cache_slots must be 0 or a power of two, got {cache_slots}")
    if cache_write not in ("onehot", "dus"):
        raise ValueError(
            f"cache_write must be 'onehot' or 'dus', got {cache_write!r}")
    shift = jnp.arange(32, dtype=jnp.uint32)

    def _pack_bool(vec):
        """bool[n_ops] -> uint32[n_words] bitmask — THE word layout, used
        by both the cache keys and the packed precedence masks (one
        definition so the layouts cannot drift apart)."""
        pad = jnp.concatenate(
            [vec, jnp.zeros(n_words * 32 - n_ops, bool)])
        return jnp.sum(
            pad.reshape(n_words, 32).astype(jnp.uint32) << shift, axis=1)

    def pack_key(taken, state):
        """(taken bool[N], state int32[S]) -> uint32[key_words], exact."""
        return jnp.concatenate([_pack_bool(taken),
                                state.astype(jnp.uint32)])

    hash_slot = make_hash_slot(key_words, cache_slots) if use_cache else None

    # NOTE: all stack updates below are branchless one-hot mask arithmetic,
    # deliberately avoiding jnp .at[].set scatters.  Besides being the
    # TPU-idiomatic form (masked selects fuse; scatters don't), this works
    # around an upstream JAX 0.9.0 bug where a *vmapped boolean* scatter
    # (bool_arr.at[j].set(True)) silently drops updates when the batch
    # dimension is >= 1024 — on both CPU and TPU backends.  Regression
    # coverage: tests/test_parity.py::test_large_batch_parity.

    def init_one(valid, init_state):
        n_req = jnp.sum(valid.astype(jnp.int32))
        carry = {
            "d": jnp.int32(0),
            "taken": jnp.zeros(n_ops, bool),
            "chosen": jnp.full(n_ops + 1, -1, jnp.int32),
            "states": jnp.zeros((n_ops + 1, spec.STATE_DIM),
                                jnp.int32).at[0].set(init_state),
            "status": jnp.where(n_req == 0, SUCCESS,
                                RUNNING).astype(jnp.int32),
            "iters": jnp.int32(0),
            # search-accounting counters (qsm_tpu/search/stats.py): memo
            # hits taken and configurations inserted.  Present even with
            # the cache off (constant 0) so the carry layout — and the
            # generic compaction gather over its leaves — is uniform
            # across the slots=0 and slots>0 steppers a lane migrates
            # between.
            "prunes": jnp.int32(0),
            "inserts": jnp.int32(0),
        }
        if use_cache:
            carry["keys"] = jnp.zeros((cache_slots, key_words), jnp.uint32)
            carry["occ"] = jnp.zeros(cache_slots, jnp.int32)
        return carry

    def run_one(carry, cmd, arg, resp, valid, precedes, chunk=None):
        n_req = jnp.sum(valid.astype(jnp.int32))
        # precedence as packed words: blocked[j] = ∃i untaken: i precedes j.
        # The naive form is an O(N²) bool matvec EVERY iteration; packed,
        # the per-iteration cost is O(N·W) with W = ⌈N/32⌉ (same bitmask
        # trick the native C++ checker uses).  Packed once per chunk call,
        # outside the while body.
        prec_pad = jnp.concatenate(
            [precedes, jnp.zeros((n_words * 32 - n_ops, n_ops), bool)],
            axis=0)
        prec_words = jnp.sum(
            prec_pad.reshape(n_words, 32, n_ops).astype(jnp.uint32)
            << shift[None, :, None], axis=1)  # [W, N]

        if state_bound is not None:
            # per-history step table: [state_bound, n_ops] next-state / ok
            def _tab_row(s):
                st = jnp.full((1,), s, jnp.int32)
                nxt_s, ok_s = jax.vmap(
                    lambda cc, aa, rr: spec.step_jax(st, cc, aa, rr),
                    out_axes=(0, 0))(cmd, arg, resp)
                return nxt_s.reshape(n_ops), ok_s.reshape(n_ops)

            nxt_tab, ok_tab = jax.vmap(_tab_row)(
                jnp.arange(state_bound, dtype=jnp.int32))

        def body(c):
            d, taken = c["d"], c["taken"]
            chosen, states = c["chosen"], c["states"]
            state = states[d]
            untaken = valid & ~taken
            # minimality: op j is blocked if some untaken op precedes it
            # (packed-word AND — see prec_words above)
            uw = _pack_bool(untaken)
            blocked = jnp.any(
                (prec_words & uw[:, None]) != jnp.uint32(0), axis=0)
            if state_bound is not None:
                # one dynamic row gather instead of n_ops step evaluations.
                # A state outside [0, bound) means the spec's
                # scalar_state_bound contract is broken (not true of the
                # current specs, all tested) — the gathered row would be
                # garbage, so flag it and degrade the lane to
                # BUDGET_EXCEEDED below: honest oracle deferral instead of
                # a silently wrong verdict (ADVICE.md round 2).
                oob = (state[0] < 0) | (state[0] >= state_bound)
                s0 = jnp.clip(state[0], 0, state_bound - 1)
                nxt = nxt_tab[s0][:, None]
                ok = ok_tab[s0]
            else:
                oob = jnp.bool_(False)
                # vectorised transition+postcondition from the current state
                nxt, ok = jax.vmap(
                    lambda cc, aa, rr: spec.step_jax(state, cc, aa, rr),
                    out_axes=(0, 0))(cmd, arg, resp)
                ok, nxt = ok.reshape(n_ops), nxt.reshape(n_ops, -1)
            cand = untaken & ~blocked & ok & (iota > chosen[d])
            has = jnp.any(cand)
            j = jnp.argmax(cand).astype(jnp.int32)
            child_state = nxt[j].astype(jnp.int32)
            success = has & (d + 1 == n_req)
            exhausted = ~has

            if use_cache:
                # child configuration already proven failed? prune: keep
                # depth, move the cursor past j.  (A success child can
                # never be cached — full configs never fail — so `success`
                # needs no priority carve-out; kept explicit for clarity.)
                key_child = pack_key(taken | (iota == j), child_state)
                slot_c = hash_slot(key_child)
                hit = (c["occ"][slot_c] == 1) & \
                    jnp.all(c["keys"][slot_c] == key_child)
                prune = has & hit & ~success
            else:
                prune = jnp.bool_(False)
            descend = has & ~prune

            # -- descend: take op j, push state, open cursor at d+1 ------
            # -- prune: cursor past j, stay put --------------------------
            # -- backtrack: untake op below, keep its cursor -------------
            d_back = jnp.maximum(d - 1, 0)
            prev = jnp.maximum(chosen[d_back], 0)
            taken_new = jnp.where(
                descend, taken | (iota == j),
                jnp.where(prune, taken,
                          taken & ~((iota == prev) & (d > 0))))
            chosen_desc = jnp.where(iota1 == d, j,
                                    jnp.where(iota1 == d + 1, -1, chosen))
            chosen_prune = jnp.where(iota1 == d, j, chosen)
            states_desc = jnp.where((iota1 == d + 1)[:, None],
                                    child_state[None, :], states)

            d_new = jnp.where(descend, d + 1, jnp.where(prune, d, d_back))
            status = jnp.where(
                success, SUCCESS,
                jnp.where((~has) & (d == 0), FAILURE, RUNNING))
            iters = c["iters"] + 1
            status = jnp.where((status == RUNNING) & (iters >= budget),
                               BUDGET, status)
            status = jnp.where(oob, BUDGET, status)
            out = {
                "d": d_new,
                "taken": taken_new,
                "chosen": jnp.where(descend, chosen_desc,
                                    jnp.where(prune, chosen_prune, chosen)),
                "states": jnp.where(descend, states_desc, states),
                "status": status.astype(jnp.int32),
                "iters": iters,
                # per-lane search accounting (read back by the driver when
                # the lane decides — SearchStats.memo_prunes/inserts)
                "prunes": c["prunes"] + prune.astype(jnp.int32),
                "inserts": (c["inserts"] + exhausted.astype(jnp.int32)
                            if use_cache else c["inserts"]),
            }
            if use_cache:
                # exhausted (no candidates left): this configuration is
                # proven non-linearizable-from — insert before backtracking.
                key_cur = pack_key(taken, state)
                slot_cur = hash_slot(key_cur)
                if cache_write == "dus":
                    # O(key_words) read-modify-write via dynamic_update_slice
                    # — the conditional insert is expressed by writing the
                    # existing row back when no insert happens, so no scatter
                    # and no O(slots) one-hot sweep per iteration.  Verdicts
                    # identical to onehot (tests/test_cache.py) but measured
                    # NO faster on the XLA CPU backend (the vmapped update
                    # becomes a full copy) and UNVERIFIED on the axon TPU
                    # stack, so it is opt-in, not the default.
                    cur_row = jax.lax.dynamic_slice(
                        c["keys"], (slot_cur, jnp.int32(0)), (1, key_words))
                    new_row = jnp.where(has, cur_row, key_cur[None, :])
                    out["keys"] = jax.lax.dynamic_update_slice(
                        c["keys"], new_row, (slot_cur, jnp.int32(0)))
                    cur_occ = jax.lax.dynamic_slice(
                        c["occ"], (slot_cur,), (1,))
                    new_occ = jnp.where(has, cur_occ, 1)
                    out["occ"] = jax.lax.dynamic_update_slice(
                        c["occ"], new_occ, (slot_cur,))
                else:
                    # O(slots) one-hot masked write — the DEFAULT: it is the
                    # form the round-1 safe-region points were verified with
                    # on the real chip (masked selects are the most
                    # conservative lowering; no scatter — module NOTE above)
                    row_mask = (jnp.arange(cache_slots) == slot_cur) & ~has
                    out["keys"] = jnp.where(row_mask[:, None],
                                            key_cur[None, :], c["keys"])
                    out["occ"] = jnp.where(row_mask, 1, c["occ"])
            return out

        if chunk is None:
            def cond(c):
                return c["status"] == RUNNING
        else:
            start = carry["iters"]

            def cond(c):
                return (c["status"] == RUNNING) & (c["iters"] - start < chunk)

        if unroll <= 1:
            return jax.lax.while_loop(cond, body, carry)

        # K micro-steps per while-loop trip, each behind the SAME guard
        # the loop cond applies, so a lane frozen (decided / budget /
        # chunk boundary) mid-trip no-ops through the remaining
        # micro-steps: verdicts AND per-lane iteration counts are
        # bit-identical to unroll=1 (tests/test_kernel_driver.py pins
        # this).  Why: the first banked real-TPU window measured ~5 ms
        # per sequential while-loop TRIP on the axon tunnel — if trip
        # overhead (not body compute) dominates, K-unrolling cuts trips
        # K× for the same lockstep work; on compute-bound platforms it is
        # neutral.  tools/bench_scale.py measures it on-chip.
        def micro(c):
            out = body(c)
            return jax.tree.map(
                lambda new, old: jnp.where(cond(c), new, old), out, c)

        def body_k(c):
            for _ in range(unroll):
                c = micro(c)
            return c

        return jax.lax.while_loop(cond, body_k, carry)

    return init_one, run_one


def build_kernel(spec: Spec, n_ops: int, budget: int,
                 cache_slots: int = 0, cache_write: str = "onehot"):
    """Build the run-to-completion single-history checker (one while-loop).

    Returned function signature (all jnp arrays):
        (cmd[N], arg[N], resp[N], valid[N], precedes[N,N], init_state[S])
        -> (status: int32, iters: int32)

    Thin composition of :func:`build_stepper` (init + unchunked run); kept
    as the stable entry point for tests and the driver's compile checks.
    """
    init_one, run_one = build_stepper(spec, n_ops, budget,
                                      cache_slots=cache_slots,
                                      cache_write=cache_write)

    def check_one(cmd, arg, resp, valid, precedes, init_state):
        carry = init_one(valid, init_state)
        out = run_one(carry, cmd, arg, resp, valid, precedes)
        return out["status"], out["iters"]

    return check_one


class JaxTPU:
    """Batched device backend implementing :class:`LineariseBackend`.

    One compiled executable per (max_ops bucket, batch bucket, cache slots,
    chunk); host code pads batches into those shapes.  ``check_histories``
    returns verdicts bit-compatible with ``WingGongCPU`` (BUDGET_EXCEEDED
    when the iteration budget ran out — never a guess).

    The driver is chunked and lane-compacting (module docstring): every
    batch starts in the largest needed bucket with a small cache, survivors
    are periodically compacted into smaller buckets with bigger caches, and
    each lane's total iterations are capped at ``budget + mid_budget +
    rescue_budget`` (the three knobs are kept for API compatibility with
    the round-2 rescue ladder; ``budget`` alone also still means "a lane
    decided after more than this many iterations counts as rescued").
    """

    name = "jax_tpu"

    # Empirical safe region for (batch x cache_slots) on the axon TPU
    # stack — NOT a pure lane-slot product (ADVICE.md round 1): 64x4096 and
    # 256x512 are verified fine, yet 256x1024 (same product as 64x4096)
    # crashes the worker.  Model it as a per-batch-bucket slot cap: the two
    # verified points stand as-is; unverified buckets are capped so that
    # batch*slots <= 1<<17, the largest product seen safe at batch >= 256.
    # A SearchPlan overrides this per instance: on the CPU platform there
    # is no crash region, so starving a wide batch at 32 slots (the
    # round-5 iters-per-history multiplier) is pure waste there.
    MAX_SLOTS_FOR_BATCH = {8: 8192, 64: 4096, 256: 512, 1024: 128, 4096: 32,
                           16384: 8, 65536: 2, 262144: 0}
    # Lane-compaction bucket ladder.  A SearchPlan substitutes a finer
    # ladder per instance (single-lane buckets on the CPU platform);
    # survivors' memo entries re-hash into the larger table at EVERY
    # bucket change (_compact_carry), which is what makes the planner's
    # small-first-chunk schedule an early-compaction policy: the starved
    # widest-bucket stage ends at the first compaction, not the last.
    BATCH_BUCKETS = _BATCH_BUCKETS
    # Micro-steps per while-loop trip (build_stepper unroll).  None =
    # auto: 8 on a real device backend, 1 on the CPU platform.  Per-TRIP
    # overhead dominates the loop on both the axon tunnel (~5 ms/trip,
    # BENCH_TPU_r04.json arithmetic) and the XLA CPU backend (unroll8
    # measured 5.2× there: 228→1189 h/s, bench_scale scan) — but tests
    # live on the CPU platform with tiny batches where the ~2.4× compile
    # cost of the unrolled body outweighs the win, so auto keeps CPU at
    # 1 and measurement surfaces (bench.py, tools/bench_*.py) opt in
    # explicitly.  Verdicts and per-lane iteration counts are
    # bit-identical at any value (tests/test_kernel_driver.py).
    UNROLL: Optional[int] = None
    # Split threshold for check_histories: batches beyond this run as
    # separate sequential device calls.  4096 is the round-1..4 behavior;
    # tools/bench_scale.py raises it per-backend to measure whether wider
    # lockstep batches amortize the per-trip latency the first real-TPU
    # window exposed (5 ms/trip at 4096 lanes — BENCH_TPU_r04.json), and
    # bench.py adopts a raised value only from a device-validated scale
    # artifact (zero wrong verdicts on the same corpus).
    MAX_BATCH = 4096
    # Chunk escalation: small first chunks harvest the easy majority with
    # little lockstep waste; later chunks grow so the hard tail is not
    # host-sync bound.  The last entry repeats until budget exhaustion.
    # Tuned on the CAS 32x8 bench corpus (CPU platform, 256 lanes):
    #   (512,2048,8192,32768,65536) -> 300k lockstep iters, 112 h/s
    #   (256,2048,16384,65536)      -> 235k lockstep iters, 140+ h/s
    # (the round-2 rescue ladder paid 3.77M on the same corpus).
    CHUNK_SCHEDULE = (256, 2048, 16384, 65536)

    def __init__(self, spec: Spec, budget: int = 2_000,
                 max_expansions: int = 128,
                 sharding=None,
                 rescue_budget: int = 500_000,
                 rescue_slots: int = 4096,
                 mid_budget: int = 50_000,
                 mid_slots: int = 512,
                 cache_write: str = "onehot",
                 plan=None,
                 ordering: Optional[bool] = None):
        self.spec = spec
        # A SearchPlan (qsm_tpu/search/planner.py) replaces the hand-tuned
        # class tuples PER INSTANCE — chunk schedule, bucket ladder, memo
        # slot policy, unroll — and switches the two search modes.  None
        # keeps the round-3..5 hand tuning exactly (every existing caller).
        self.plan = plan
        if plan is not None:
            self.CHUNK_SCHEDULE = tuple(plan.chunk_schedule)
            self.BATCH_BUCKETS = tuple(plan.batch_buckets)
            self.MAX_SLOTS_FOR_BATCH = dict(plan.slots_for_batch)
            if plan.unroll is not None:
                self.UNROLL = plan.unroll
        # Postcondition-aware candidate ordering (search/ordering.py):
        # host-side op permutation, applied per history in _run_device and
        # inverted on witness read-back.  None = from the plan; False
        # without one (the canonical order, as every prior round ran).
        if ordering is None:
            ordering = bool(plan.ordering) if plan is not None else False
        self._ordering_table = None
        if ordering:
            from ..search.ordering import ordering_table

            self._ordering_table = ordering_table(spec)
        self.budget = budget
        self.max_expansions = max_expansions
        self.sharding = sharding  # optional NamedSharding for the batch axis
        # Mesh placement is resolved ONCE here (qsm_tpu/mesh/ owns the
        # policy): the lane-axis sharding every dispatch site applies, the
        # mesh-shape key folded into every compile-cache identity (a
        # 1-chip executable must never serve an 8-chip mesh), and a bucket
        # ladder restricted to mesh-divisible widths (uneven buckets shard
        # raggedly).  Unsharded instances keep (1,) and untouched ladders.
        from ..mesh.dispatch import mesh_bucket_ladder, mesh_slots_table
        from ..mesh.topology import (lane_sharding_of, mesh_device_count,
                                     mesh_shape_key)

        self._mesh_key = mesh_shape_key(sharding)
        self._lane_sharding = (lane_sharding_of(sharding)
                               if sharding is not None else None)
        if sharding is not None:
            n_dev = mesh_device_count(sharding)
            if n_dev > 1:
                self.BATCH_BUCKETS = mesh_bucket_ladder(
                    self.BATCH_BUCKETS, n_dev)
                self.MAX_SLOTS_FOR_BATCH = mesh_slots_table(
                    self.MAX_SLOTS_FOR_BATCH, self.BATCH_BUCKETS)
        self.rescue_budget = rescue_budget
        self.rescue_slots = rescue_slots
        self.mid_budget = mid_budget
        self.mid_slots = mid_slots  # unused by the chunked driver; kept for
        # API compatibility with round-2 callers
        self.cache_write = cache_write
        # total per-lane iteration cap — the sum of what the round-2 ladder
        # would have granted across its three stages, so existing callers'
        # budget expectations (tests, bench) are preserved exactly
        self.total_budget = budget + mid_budget + rescue_budget
        self._steppers: Dict[Tuple[int, int], tuple] = {}
        self._compiled: Dict[Tuple, object] = {}
        # Vector specs with declared element bounds get a SCALARIZED
        # shadow (ops/scalarize.py): the kernel then runs the step-table
        # gather fast path with one-word memo keys instead of a vmapped
        # step sweep per iteration.  Bijective packing — verdicts and
        # iteration counts are identical either way (tests pin this).
        from .scalarize import scalar_shadow

        self._shadow = scalar_shadow(spec)
        self.kspec = self._shadow if self._shadow is not None else spec
        # Step-table specs guarantee their state bound only for histories
        # whose ARGS are in the declared command domains (resps may be
        # arbitrary — SUTs can return anything; args come from the
        # generator).  Out-of-domain histories are deferred to the oracle
        # (BUDGET_EXCEEDED) instead of risking a table/oracle divergence.
        self._uses_table = (self.kspec.STATE_DIM == 1
                            and self.kspec.scalar_state_bound(1) is not None)
        self.deferred_out_of_domain = 0
        self.batches_run = 0
        self.device_histories = 0
        self.rescued = 0
        self.rounds_run = 0
        self.compactions = 0   # batch-shrink / cache-growth events
        self.memo_prunes = 0   # in-kernel memo hits (subtrees skipped)
        self.memo_inserts = 0  # configurations proven non-linearizable
        # Σ (while-loop trip count × padded batch) over all chunk calls:
        # the honest lockstep cost of a batch (what every lane PAYS, not
        # what it needed) — the round-3 iteration-efficiency metric.
        self.lockstep_cost = 0
        self.effective_rescue_slots: Optional[int] = None  # largest cache
        # Double-buffered tail dispatch: None = auto (on when the default
        # backend is a real device, where async dispatch makes the overlap
        # real; off on the CPU platform, where a wasted speculative chunk
        # costs the same cores the real one needs).  Set True/False to
        # force either way (tests force True on CPU for semantics).
        self.DOUBLE_BUFFER: Optional[bool] = None
        self.speculated_chunks = 0
        self.wasted_chunks = 0
        self.host_sync_s = 0.0  # time blocked fetching chunk status

    def search_stats(self):
        """Cumulative :class:`~qsm_tpu.search.stats.SearchStats` — the
        engine's half of the iterations-per-history story.  ``histories``
        counts device LANES (post pending-expansion), which equals input
        histories on pending-free corpora; ``lockstep_iters`` is the
        honest trips × padded-width cost every lane pays."""
        from ..search.stats import SearchStats

        return SearchStats(
            engine=self.name,
            histories=self.device_histories,
            lockstep_iters=self.lockstep_cost,
            memo_prunes=self.memo_prunes,
            memo_inserts=self.memo_inserts,
            compactions=self.compactions,
            chunk_rounds=self.rounds_run,
            rescued=self.rescued,
            deferred=self.deferred_out_of_domain,
            ordering=self._ordering_table is not None,
            plan=self.plan.name if self.plan is not None else "",
        )

    def _double_buffer_on(self) -> bool:
        if self.DOUBLE_BUFFER is not None:
            return self.DOUBLE_BUFFER
        import jax

        return jax.default_backend() != "cpu"

    # -- compilation cache -------------------------------------------------
    def _slots_for(self, batch: int) -> int:
        slots = min(self.MAX_SLOTS_FOR_BATCH.get(batch, 32),
                    self.rescue_slots)
        if slots > 0:
            self.effective_rescue_slots = max(
                self.effective_rescue_slots or 0, slots)
        return slots

    def _unroll(self) -> int:
        if self.UNROLL is not None:
            return self.UNROLL
        import jax

        return 8 if jax.default_backend() != "cpu" else 1

    def _stepper(self, n_ops: int, slots: int):
        key = (n_ops, slots, self._unroll())
        fns = self._steppers.get(key)
        if fns is None:
            fns = build_stepper(self.kspec, n_ops, self.total_budget,
                                cache_slots=slots,
                                cache_write=self.cache_write,
                                unroll=self._unroll())
            self._steppers[key] = fns
        return fns

    def _init_fn(self, n_ops: int, batch: int, slots: int):
        import jax

        key = ("init", n_ops, batch, slots, self._unroll(),
               self._mesh_key)
        fn = self._compiled.get(key)
        if fn is None:
            init_one, _ = self._stepper(n_ops, slots)
            fn = jax.jit(jax.vmap(init_one, in_axes=(0, 0)))
            self._compiled[key] = fn
        return fn

    def _chunk_fn(self, n_ops: int, batch: int, slots: int, chunk: int,
                  donate: bool = True):
        import jax

        # mesh shape is part of every compile identity: executables are
        # SPMD-partitioned for a specific device count (mesh/topology.py
        # mesh_shape_key — a 1-chip build must never serve an 8-chip mesh)
        key = ("chunk", n_ops, batch, slots, chunk, donate,
               self._unroll(), self._mesh_key)
        fn = self._compiled.get(key)
        if fn is None:
            _, run_one = self._stepper(n_ops, slots)

            def run_chunk(carry, cmd, arg, resp, valid, precedes):
                return run_one(carry, cmd, arg, resp, valid, precedes,
                               chunk=chunk)

            # Donate the input carry: it is dead the moment the chunk call
            # returns (the driver only ever reads the RETURNED carry), and
            # the carry dominates the kernel's memory (stack + states +
            # memo cache per lane) — donation lets XLA update it in place
            # instead of double-buffering it in HBM every chunk.  The CPU
            # backend can't donate and warns per call site, so only donate
            # where it works (the carry is small enough either way there).
            # ``donate=False`` is the double-buffered tail's variant: the
            # speculative next chunk reads a carry whose status the host
            # has not fetched yet, so that carry must stay alive.
            dn = (0,) if donate and jax.default_backend() != "cpu" else ()
            fn = jax.jit(jax.vmap(run_chunk, in_axes=(0, 0, 0, 0, 0, 0)),
                         donate_argnums=dn)
            self._compiled[key] = fn
        return fn

    def _compact_fn(self, new_bucket: int, slots: int, old_slots: int):
        """Jitted lane compaction: gather surviving lanes of every carry
        leaf into the smaller padded batch ON DEVICE, re-hashing occupied
        cache entries into the new table size when it changes — no host
        round-trip of the dominant state (VERDICT.md round 3, "Next
        round" #6; the old path materialized the full carry on host per
        compaction, defeating donation and sharding)."""
        import jax
        import jax.numpy as jnp

        key = ("compact", new_bucket, slots, old_slots, self._mesh_key)
        fn = self._compiled.get(key)
        if fn is not None:
            return fn

        def compact(carry, idx, live):
            # idx: int32[new_bucket] source rows (0 for padding rows);
            # live: bool[new_bucket] marks real rows
            new = {}
            for k, v in carry.items():
                if k in ("keys", "occ"):
                    continue
                g = jnp.take(v, idx, axis=0)
                if k == "status":
                    # padding rows freeze immediately (cond sees SUCCESS)
                    g = jnp.where(live, g, SUCCESS)
                new[k] = g
            if slots > 0:
                keys = jnp.take(carry["keys"], idx, axis=0)
                occ = jnp.take(carry["occ"], idx, axis=0)
                occ = jnp.where(live[:, None], occ, 0)
                if old_slots == slots:
                    new["keys"] = keys
                    new["occ"] = occ
                else:
                    # re-hash occupied entries into the new table; slot
                    # collisions drop an entry (either one — pruning
                    # opportunity lost, soundness untouched, same
                    # contract as the host re-hash this replaces)
                    kw = keys.shape[2]
                    hash_one = make_hash_slot(kw, slots)
                    dest = jax.vmap(jax.vmap(hash_one))(keys)
                    dest = jnp.where(occ == 1, dest, slots)  # drop empties
                    bidx = jnp.broadcast_to(
                        jnp.arange(new_bucket)[:, None], dest.shape)
                    new["keys"] = (
                        jnp.zeros((new_bucket, slots, kw), jnp.uint32)
                        .at[bidx, dest].set(keys, mode="drop"))
                    new["occ"] = (
                        jnp.zeros((new_bucket, slots), jnp.int32)
                        .at[bidx, dest].set(occ, mode="drop"))
            return new

        dn = (0,) if jax.default_backend() != "cpu" else ()
        fn = jax.jit(compact, donate_argnums=dn)
        self._compiled[key] = fn
        return fn

    def _args_in_domain(self, h: History) -> bool:
        cmds = self.spec.CMDS
        return all(0 <= o.cmd < len(cmds)
                   and 0 <= o.arg < cmds[o.cmd].n_args for o in h.ops)

    # -- pending-op expansion ---------------------------------------------
    def _expand(self, h: History) -> Optional[List[History]]:
        """All complete/prune completions of a history's pending ops, or
        None if the expansion would exceed ``max_expansions`` (the caller
        then defers to the oracle via BUDGET_EXCEEDED)."""
        if h.n_pending == 0:
            return [h]
        pend = [i for i, o in enumerate(h.ops) if o.is_pending]
        n = 1
        choices = []
        for i in pend:
            # None = prune; r = complete with response r
            opts = [None] + list(self.spec.resp_domain(h.ops[i].cmd))
            n *= len(opts)
            if n > self.max_expansions:
                return None
            choices.append(opts)
        pend_pos = {i: k for k, i in enumerate(pend)}
        out = []
        for combo in itertools.product(*choices):
            ops = []
            for i, o in enumerate(h.ops):
                if i in pend_pos:
                    c = combo[pend_pos[i]]
                    if c is None:
                        continue  # pruned: never took effect
                    # completed: took effect; response unobserved, so its
                    # linearisation point is unconstrained on the right —
                    # keep the pending sentinel response_time
                    ops.append(dataclasses.replace(o, resp=int(c)))
                else:
                    ops.append(o)
            out.append(History(ops, seed=h.seed, program_id=h.program_id))
        return out

    # -- main entry --------------------------------------------------------
    def check_histories(self, spec: Spec, histories: Sequence[History],
                        init_states: Optional[Sequence] = None
                        ) -> np.ndarray:
        assert spec is self.spec, \
            "JaxTPU is compiled per spec; construct one per spec"
        # fault site: every device dispatch enters here — the resilience
        # plane simulates hangs/mid-run loss at this boundary so the
        # failover paths are tier-1 testable without hardware
        # (resilience/faults.py; no-op unless QSM_TPU_FAULTS is set)
        from ..resilience.faults import inject

        inject("dispatch")
        if not histories:
            return np.empty(0, np.int8)
        # public-parameter validation: not an assert (python -O strips it)
        if init_states is not None and len(init_states) != len(histories):
            raise ValueError(
                f"init_states has {len(init_states)} entries for "
                f"{len(histories)} histories")

        # 1. host-side pending expansion
        groups: List[Tuple[int, int]] = []  # (start, count) per input
        flat: List[History] = []
        flat_inits: List = []
        for idx, h in enumerate(histories):
            if self._uses_table and not self._args_in_domain(h):
                self.deferred_out_of_domain += 1
                groups.append((len(flat), 0))
                continue
            if (self._shadow is not None and init_states is not None
                    and not self._shadow.in_bounds(init_states[idx])):
                # a start state outside the declared element bounds would
                # pack onto a DIFFERENT valid state (wrong verdict, not a
                # crash) — defer it to the oracle instead
                self.deferred_out_of_domain += 1
                groups.append((len(flat), 0))
                continue
            exp = self._expand(h)
            if exp is None:
                groups.append((len(flat), 0))
            else:
                groups.append((len(flat), len(exp)))
                flat.extend(exp)
                if init_states is not None:
                    flat_inits.extend([init_states[idx]] * len(exp))

        out = np.full(len(histories), int(Verdict.BUDGET_EXCEEDED), np.int8)
        if flat:
            statuses = self._run_device(
                flat, flat_inits if init_states is not None else None)
            for idx, (start, count) in enumerate(groups):
                if count == 0:
                    continue
                sub = statuses[start:start + count]
                if (sub == SUCCESS).any():
                    out[idx] = int(Verdict.LINEARIZABLE)
                elif (sub == BUDGET).any():
                    out[idx] = int(Verdict.BUDGET_EXCEEDED)
                else:
                    out[idx] = int(Verdict.VIOLATION)
        return out

    def check_from(self, spec: Spec, history: History, init_state) -> Verdict:
        """Single-history :meth:`check_histories` from an explicit model
        state — the device counterpart of ``WingGongCPU.check_from`` (used
        by the segmentation combinator, ops/segdc.py)."""
        v = self.check_histories(spec, [history], init_states=[init_state])
        return Verdict(int(v[0]))

    def check_witness(self, spec: Spec, history: History):
        """(verdict, witness) for one history — the device counterpart of
        ``WingGongCPU.check_witness``: the kernel's ``chosen`` stack IS
        the successful linearization, read back on success.  Witnesses
        only for pending-free histories (pending completion happens in
        host-side expansion, so the in-kernel stack describes an expanded
        variant, not the input); (verdict, None) otherwise.  Like the
        oracle's, the witness replays independently via
        ``verify_witness`` — the kernel is not trusted, its proof is.
        """
        # fault site: the pending-free path below dispatches via
        # _run_device without passing through check_histories, so the
        # witness entry needs its own hook for the degradation paths to
        # be tier-1 testable (resilience/faults.py; no-op unless set)
        from ..resilience.faults import inject

        inject("dispatch")
        if history.n_pending or (
                self._uses_table and not self._args_in_domain(history)):
            # pending or out-of-domain: the witness path can't apply —
            # route through the normal (expanding/deferring) entry
            return Verdict(
                int(self.check_histories(spec, [history])[0])), None
        if not history.ops:
            return Verdict.LINEARIZABLE, []
        # ONE device search, witness read back from the same run (a
        # second search just to collect `chosen` would double the
        # dominant cost for hard histories)
        statuses, chosen = self._run_device([history], collect_chosen=True)
        v = {SUCCESS: Verdict.LINEARIZABLE, FAILURE: Verdict.VIOLATION,
             BUDGET: Verdict.BUDGET_EXCEEDED}[int(statuses[0])]
        if v != Verdict.LINEARIZABLE:
            return v, None
        order = [int(j) for j in chosen[0][:len(history.ops)]]
        return v, [(j, history.ops[j].resp) for j in order]

    # -- the chunked, lane-compacting driver -------------------------------
    def _run_device(self, flat: Sequence[History],
                    flat_inits: Optional[List] = None,
                    collect_chosen: bool = False):
        """Statuses for a flat batch; with ``collect_chosen`` also the
        final ``chosen`` stack per lane (the linearization witness for
        SUCCESS lanes — :meth:`check_witness`)."""
        top = min(self.MAX_BATCH, self.BATCH_BUCKETS[-1])
        if len(flat) > top:
            parts = [
                self._run_device(
                    flat[i:i + top],
                    flat_inits[i:i + top] if flat_inits else None,
                    collect_chosen=collect_chosen)
                for i in range(0, len(flat), top)]
            if collect_chosen:
                # chunks bucket n_ops independently; pad chosen to the
                # widest before concatenating (sentinel -1 beyond depth)
                width = max(p[1].shape[1] for p in parts)
                padded = [np.pad(p[1], ((0, 0), (0, width - p[1].shape[1])),
                                 constant_values=-1) for p in parts]
                return (np.concatenate([p[0] for p in parts]),
                        np.concatenate(padded))
            return np.concatenate(parts)

        # Postcondition-aware try order: permute each history's op array by
        # selectivity rank BEFORE encoding, so the kernel's argmax tries
        # the most constrained candidates first with zero per-iteration
        # cost.  Linearizability is permutation-invariant (the precedence
        # order rides the ops' own timestamps — search/ordering.py), so
        # only iteration counts change; witness indices are mapped back
        # through the permutation below.
        perms = None
        if self._ordering_table is not None:
            from ..search.ordering import permute_history

            perms = [self._ordering_table.permutation(h) for h in flat]
            flat = [permute_history(h, p) for h, p in zip(flat, perms)]

        n_ops = bucket_for(max(len(h) for h in flat) or 1)
        enc = encode_batch(flat, self.kspec.initial_state(), max_ops=n_ops)
        b = len(flat)
        cmd = enc.ops[:, :, 1].astype(np.int32)
        arg = enc.ops[:, :, 2].astype(np.int32)
        resp = enc.ops[:, :, 3].astype(np.int32)
        valid = enc.valid.astype(bool)
        prec = enc.precedes().astype(bool)
        inits = np.tile(np.asarray(enc.init_state, np.int32), (b, 1))
        if flat_inits is not None:
            for i, s in enumerate(flat_inits):
                # caller states are in the SPEC's representation; the
                # kernel runs the shadow's (validated in check_histories)
                inits[i] = (np.asarray([self._shadow.pack(s)], np.int32)
                            if self._shadow is not None
                            else np.asarray(s, np.int32))

        out_status = np.full(b, BUDGET, np.int32)
        out_chosen = (np.full((b, n_ops + 1), -1, np.int32)
                      if collect_chosen else None)
        active = np.arange(b)          # indices into the flat batch
        carry = None                   # device carry for current bucket
        args = None
        lanes = np.empty(0, np.intp)   # carry row of each active element
        cur_bucket = cur_slots = None
        prev_iters = np.zeros(b, np.int64)
        round_i = 0

        speculate = self._double_buffer_on()
        last_sched = len(self.CHUNK_SCHEDULE) - 1
        pending = None  # speculatively-dispatched NEXT chunk's carry

        while active.size:
            bucket = _batch_bucket(active.size, self.BATCH_BUCKETS)
            slots = self._slots_for(bucket)
            sched_i = min(round_i, last_sched)
            chunk = self.CHUNK_SCHEDULE[sched_i]

            if carry is None:
                carry = self._fresh_carry(active, bucket, slots, n_ops,
                                          valid, inits)
                args = self._pad_args(active, bucket,
                                      cmd, arg, resp, valid, prec)
                lanes = np.arange(active.size)
                cur_bucket, cur_slots = bucket, slots
            elif bucket != cur_bucket or slots != cur_slots:
                if pending is not None:
                    pending = None  # compaction invalidates the gamble
                    self.wasted_chunks += 1
                carry = self._compact_carry(carry, lanes, bucket,
                                            slots, cur_slots)
                args = self._pad_args(active, bucket,
                                      cmd, arg, resp, valid, prec)
                lanes = np.arange(active.size)
                cur_bucket, cur_slots = bucket, slots
                self.compactions += 1

            # Double-buffered tail (VERDICT.md round 3, "Next round" #2):
            # once the chunk schedule settles, dispatch chunk k+1 BEFORE
            # fetching chunk k's status, so the host sync overlaps device
            # compute instead of idling the device between rounds.
            # Finished lanes are frozen in-kernel (their while-cond is
            # false), so re-running them is a no-op; the gamble only loses
            # when the next round would have compacted or the batch
            # finishes.  The tail fn must not donate its input (the
            # not-yet-fetched carry stays alive) — a deliberate memory/
            # latency trade confined to the settled tail.
            tail = speculate and sched_i == last_sched
            fn = self._chunk_fn(n_ops, bucket, slots, chunk,
                                donate=not tail)
            if pending is not None:
                carry = pending
                pending = None
            else:
                carry = fn(carry, *args)
            if tail:
                pending = fn(carry, *args)
                self.speculated_chunks += 1
            t_sync = time.perf_counter()
            status = np.asarray(carry["status"])
            iters = np.asarray(carry["iters"]).astype(np.int64)
            self.host_sync_s += time.perf_counter() - t_sync
            self.batches_run += 1
            self.rounds_run += 1
            # lockstep cost: trips this chunk × padded width (what every
            # lane PAYS under lockstep, not what it needed)
            delta = iters[lanes] - prev_iters[active]
            self.lockstep_cost += int(delta.max(initial=0)) * bucket
            prev_iters[active] = iters[lanes]

            lane_status = status[lanes]
            done = lane_status != RUNNING
            if done.any():
                out_status[active[done]] = lane_status[done]
                if collect_chosen:
                    out_chosen[active[done]] = np.asarray(
                        carry["chosen"])[lanes[done]]
                decided = lane_status[done] != BUDGET
                self.rescued += int(np.sum(
                    decided & (iters[lanes][done] > self.budget)))
                # per-lane counters are cumulative in the carry; harvest a
                # lane's totals exactly once, the round it decides
                self.memo_prunes += int(
                    np.asarray(carry["prunes"])[lanes[done]].sum())
                self.memo_inserts += int(
                    np.asarray(carry["inserts"])[lanes[done]].sum())
            still = ~done
            active = active[still]
            lanes = lanes[still]
            round_i += 1

        if pending is not None:
            self.wasted_chunks += 1  # batch finished under the gamble
        self.device_histories += b
        if collect_chosen:
            if perms is not None:
                # chosen indexes the PERMUTED op array; callers (witness
                # read-back) speak original indices: permuted[k] =
                # ops[perm[k]], so chosen value v maps to perm[v]
                for i, p in enumerate(perms):
                    row = out_chosen[i]
                    m = row >= 0
                    row[m] = p[row[m]]
            return out_status, out_chosen
        return out_status

    def _fresh_carry(self, active, bucket, slots, n_ops, valid, inits):
        import jax.numpy as jnp

        pv = np.zeros((bucket, valid.shape[1]), bool)
        pi = np.zeros((bucket, inits.shape[1]), np.int32)
        pv[:active.size] = valid[active]
        pi[:active.size] = inits[active]
        # padding rows have no valid ops -> n_req == 0 -> status SUCCESS at
        # init, so their while-loop cond is immediately false (frozen)
        carry = self._init_fn(n_ops, bucket, slots)(
            jnp.asarray(pv), jnp.asarray(pi))
        return self._shard_carry(carry)

    def _compact_carry_host(self, carry, lanes, bucket, slots, old_slots):
        """Host-side reference compaction (the round-3 implementation):
        gather surviving lanes' DFS state into a smaller padded batch,
        growing the memo cache by re-hashing occupied entries into the
        larger table.  Kept as the behavioral reference for
        :meth:`_compact_carry` (tests/test_kernel_driver.py compares resumed
        searches across both paths); the driver uses the device path.
        The carry is exact: resuming it continues the identical search;
        dropped-on-collision cache entries only lose pruning
        opportunities, never soundness."""
        import jax.numpy as jnp

        host = {k: np.asarray(v) for k, v in carry.items()}
        m = lanes.size
        new = {}
        for k, v in host.items():
            if k in ("keys", "occ"):
                continue
            buf = np.zeros((bucket,) + v.shape[1:], v.dtype)
            buf[:m] = v[lanes]
            if k == "status":
                buf[m:] = SUCCESS  # freeze padding lanes
            new[k] = buf

        if slots > 0:
            key_words = host["keys"].shape[2] if "keys" in host else (
                self._stepper_key_words(host["taken"].shape[1]))
            keys = np.zeros((bucket, slots, key_words), np.uint32)
            occ = np.zeros((bucket, slots), np.int32)
            if "keys" in host and old_slots:
                if old_slots == slots:
                    keys[:m] = host["keys"][lanes]
                    occ[:m] = host["occ"][lanes]
                else:
                    for row, lane in enumerate(lanes):
                        filled = host["occ"][lane] == 1
                        if not filled.any():
                            continue
                        kk = host["keys"][lane][filled]
                        dest = hash_slots_np(kk, slots)
                        keys[row, dest] = kk
                        occ[row, dest] = 1
            new["keys"] = keys
            new["occ"] = occ
        return self._shard_carry({k: jnp.asarray(v)
                                  for k, v in new.items()})

    def _compact_carry(self, carry, lanes, bucket, slots, old_slots):
        """Lane compaction, on device: one jitted gather, no host
        materialization of the carry (see :meth:`_compact_fn`);
        :meth:`_compact_carry_host` is the behavioral reference."""
        import jax.numpy as jnp

        idx = np.zeros(bucket, np.int32)
        idx[:lanes.size] = lanes
        live = np.zeros(bucket, bool)
        live[:lanes.size] = True
        if slots > 0 and "keys" not in carry:
            # compacting OUT of a cache-off bucket (the widest buckets run
            # slots=0 — MAX_SLOTS_FOR_BATCH) into a cached one: there is
            # nothing to re-hash, survivors just get a fresh empty table
            # (key width = packed taken words + state words, the
            # build_stepper layout)
            new = self._compact_fn(bucket, 0, 0)(
                carry, jnp.asarray(idx), jnp.asarray(live))
            kw = self._stepper_key_words(carry["taken"].shape[1])
            new["keys"] = jnp.zeros((bucket, slots, kw), jnp.uint32)
            new["occ"] = jnp.zeros((bucket, slots), jnp.int32)
            return self._shard_carry(new)
        new = self._compact_fn(bucket, slots, old_slots or 0)(
            carry, jnp.asarray(idx), jnp.asarray(live))
        return self._shard_carry(new)

    def _shard_carry(self, carry):
        """Every carry leaf is batch-leading; on a mesh, place it with the
        same batch-axis sharding as the kernel args (otherwise each chunk
        call implicitly reshards the dominant state — the carry, cache
        included, is far larger than the inputs).  The placement itself
        is ``mesh.lane_sharding_of(self.sharding)``, resolved once in
        ``__init__`` — the one lane-axis derivation shared with
        :meth:`_arg_shardings`."""
        if self._lane_sharding is None:
            return carry
        import jax

        batched = self._lane_sharding
        return {k: jax.device_put(v, batched) for k, v in carry.items()}

    def _stepper_key_words(self, n_ops: int) -> int:
        """Key width of the in-kernel memo cache: packed taken-bitmask
        words + state words — MUST mirror build_stepper's layout (the one
        other definition).  Needed when survivors compact OUT of a
        cache-off bucket (the widest buckets run slots=0) into a cached
        one: there is no existing table to read the width from."""
        return (n_ops + 31) // 32 + self.kspec.STATE_DIM

    def _pad_args(self, active, bucket, cmd, arg, resp, valid, prec):
        import jax.numpy as jnp

        m = active.size
        n = cmd.shape[1]
        pc = np.zeros((bucket, n), np.int32)
        pa = np.zeros((bucket, n), np.int32)
        pr = np.zeros((bucket, n), np.int32)
        pv = np.zeros((bucket, n), bool)
        pp = np.zeros((bucket, n, n), bool)
        pc[:m] = cmd[active]
        pa[:m] = arg[active]
        pr[:m] = resp[active]
        pv[:m] = valid[active]
        pp[:m] = prec[active]
        args = (jnp.asarray(pc), jnp.asarray(pa), jnp.asarray(pr),
                jnp.asarray(pv), jnp.asarray(pp))
        if self.sharding is not None:
            import jax

            sh = self._arg_shardings()
            args = tuple(jax.device_put(a, s) for a, s in zip(args, sh))
        return args

    def _arg_shardings(self):
        """Batch-axis sharding for each kernel argument — the same
        ``lane_sharding_of`` derivation the carry uses (one definition,
        qsm_tpu/mesh/topology.py)."""
        batched = self._lane_sharding
        return (batched, batched, batched, batched, batched)
