"""``JaxTPU`` — the batched Wing–Gong branch-and-bound kernel.

This is the TPU replacement for the reference's pure, single-threaded
``Test.StateMachine.Linearise`` DFS (SURVEY.md §3.2; the north-star of
BASELINE.json:5): thousands of candidate histories are decided in ONE
``vmap``'d device call.

Mapping dynamic search onto static XLA shapes (SURVEY.md §7 hard-parts #1)
---------------------------------------------------------------------------
Wing–Gong is a backtracking DFS with data-dependent branching.  Here it runs
as a ``lax.while_loop`` over an explicit fixed-size stack:

* ``taken``   bool[N]        — ops already linearised on the current path
* ``chosen``  int32[N+1]     — op index picked at each depth; doubles as the
  sibling cursor (at depth ``d`` only indices ``> chosen[d]`` are tried, so
  backtracking resumes exactly where the recursion would)
* ``states``  int32[N+1, S]  — model-state stack (packed int vectors, so
  queue/KV specs avoid exponential step tables — hard-parts #2)

Each iteration is one DFS transition {descend | advance-sibling | backtrack},
chosen branchlessly:

* candidate mask = untaken ∧ precedence-minimal ∧ postcondition-ok ∧ beyond
  cursor; precedence-minimality is a masked any() over the precomputed strict
  precedes matrix (``resp_i < inv_j``), and postconditions for ALL ops are
  evaluated vectorised from the current state (one ``vmap`` of
  ``spec.step_jax`` — most branches die here, which is what keeps typical
  search trees tiny despite the O(n!) worst case)
* first candidate via ``argmax`` of the bool mask (same canonical op order as
  the CPU oracle, so explored trees — and therefore verdicts — agree)

Worst-case blowups are cut by an iteration budget: the kernel reports
BUDGET_EXCEEDED honestly and the property layer resolves those via the CPU
oracle, keeping CPU/TPU verdicts bit-identical (hard-parts #5).

Pending (crash/fault) ops are expanded host-side into complete histories —
every prune/complete×response combination (SURVEY.md §3.2 complete/prune) —
so the kernel itself only ever sees complete histories with static shapes.

Batching: ``vmap`` over histories (≥1024 per call — BASELINE.json:9); batch
sizes and op counts are bucketed to bound recompilation.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import History, bucket_for, encode_batch
from ..core.spec import Spec
from .backend import Verdict

RUNNING = 0
SUCCESS = 1  # == Verdict.LINEARIZABLE
FAILURE = 2
BUDGET = 3

_BATCH_BUCKETS = (8, 64, 256, 1024, 4096)


def _batch_bucket(b: int) -> int:
    """Smallest bucket holding ``b`` rows; callers split batches larger than
    the top bucket into top-bucket chunks so the compile cache stays bounded
    at len(_BATCH_BUCKETS) executables per op bucket."""
    for s in _BATCH_BUCKETS:
        if b <= s:
            return s
    return _BATCH_BUCKETS[-1]


def build_kernel(spec: Spec, n_ops: int, budget: int):
    """Build the single-history while-loop checker for one (spec, N) shape.

    Returned function signature (all jnp arrays):
        (cmd[N], arg[N], resp[N], valid[N], precedes[N,N], init_state[S])
        -> (status: int32, iters: int32)
    """
    import jax
    import jax.numpy as jnp

    iota = jnp.arange(n_ops, dtype=jnp.int32)
    iota1 = jnp.arange(n_ops + 1, dtype=jnp.int32)

    # NOTE: all stack updates below are branchless one-hot mask arithmetic,
    # deliberately avoiding jnp .at[].set scatters.  Besides being the
    # TPU-idiomatic form (masked selects fuse; scatters don't), this works
    # around an upstream JAX 0.9.0 bug where a *vmapped boolean* scatter
    # (bool_arr.at[j].set(True)) silently drops updates when the batch
    # dimension is >= 1024 — on both CPU and TPU backends.  Regression
    # coverage: tests/test_parity.py::test_large_batch_parity.

    def check_one(cmd, arg, resp, valid, precedes, init_state):
        n_req = jnp.sum(valid.astype(jnp.int32))

        def cond(c):
            return c["status"] == RUNNING

        def body(c):
            d, taken = c["d"], c["taken"]
            chosen, states = c["chosen"], c["states"]
            state = states[d]
            untaken = valid & ~taken
            # minimality: op j is blocked if some untaken op precedes it
            blocked = jnp.any(untaken[:, None] & precedes, axis=0)
            # vectorised transition+postcondition from the current state
            nxt, ok = jax.vmap(
                lambda cc, aa, rr: spec.step_jax(state, cc, aa, rr),
                out_axes=(0, 0))(cmd, arg, resp)
            ok, nxt = ok.reshape(n_ops), nxt.reshape(n_ops, -1)
            cand = untaken & ~blocked & ok & (iota > chosen[d])
            has = jnp.any(cand)
            j = jnp.argmax(cand).astype(jnp.int32)

            # -- descend: take op j, push state, open cursor at d+1 ------
            # -- backtrack: untake op below, keep its cursor -------------
            d_back = jnp.maximum(d - 1, 0)
            prev = jnp.maximum(chosen[d_back], 0)
            taken_new = jnp.where(
                has, taken | (iota == j),
                taken & ~((iota == prev) & (d > 0)))
            chosen_desc = jnp.where(iota1 == d, j,
                                    jnp.where(iota1 == d + 1, -1, chosen))
            states_desc = jnp.where((iota1 == d + 1)[:, None],
                                    nxt[j][None, :].astype(jnp.int32),
                                    states)

            d_new = jnp.where(has, d + 1, d_back)
            status = jnp.where(
                has & (d + 1 == n_req), SUCCESS,
                jnp.where((~has) & (d == 0), FAILURE, RUNNING))
            iters = c["iters"] + 1
            status = jnp.where((status == RUNNING) & (iters >= budget),
                               BUDGET, status)
            return {
                "d": d_new,
                "taken": taken_new,
                "chosen": jnp.where(has, chosen_desc, chosen),
                "states": jnp.where(has, states_desc, states),
                "status": status.astype(jnp.int32),
                "iters": iters,
            }

        init = {
            "d": jnp.int32(0),
            "taken": jnp.zeros(n_ops, bool),
            "chosen": jnp.full(n_ops + 1, -1, jnp.int32),
            "states": jnp.zeros((n_ops + 1, spec.STATE_DIM),
                                jnp.int32).at[0].set(init_state),
            "status": jnp.where(n_req == 0, SUCCESS,
                                RUNNING).astype(jnp.int32),
            "iters": jnp.int32(0),
        }
        out = jax.lax.while_loop(cond, body, init)
        return out["status"], out["iters"]

    return check_one


class JaxTPU:
    """Batched device backend implementing :class:`LineariseBackend`.

    One compiled executable per (max_ops bucket, batch bucket); host code
    pads batches into those shapes.  ``check_histories`` returns verdicts
    bit-compatible with ``WingGongCPU`` (BUDGET_EXCEEDED when the iteration
    budget ran out — never a guess).
    """

    name = "jax_tpu"

    def __init__(self, spec: Spec, budget: int = 200_000,
                 max_expansions: int = 128,
                 sharding=None):
        self.spec = spec
        self.budget = budget
        self.max_expansions = max_expansions
        self.sharding = sharding  # optional NamedSharding for the batch axis
        self._compiled: Dict[Tuple[int, int], object] = {}
        self.batches_run = 0
        self.device_histories = 0

    # -- compilation cache -------------------------------------------------
    def _kernel(self, n_ops: int, batch: int):
        import jax

        key = (n_ops, batch)
        fn = self._compiled.get(key)
        if fn is None:
            single = build_kernel(self.spec, n_ops, self.budget)
            batched = jax.vmap(single, in_axes=(0, 0, 0, 0, 0, None))
            fn = jax.jit(batched)
            self._compiled[key] = fn
        return fn

    # -- pending-op expansion ---------------------------------------------
    def _expand(self, h: History) -> Optional[List[History]]:
        """All complete/prune completions of a history's pending ops, or
        None if the expansion would exceed ``max_expansions`` (the caller
        then defers to the oracle via BUDGET_EXCEEDED)."""
        if h.n_pending == 0:
            return [h]
        pend = [i for i, o in enumerate(h.ops) if o.is_pending]
        n = 1
        choices = []
        for i in pend:
            # None = prune; r = complete with response r
            opts = [None] + list(self.spec.resp_domain(h.ops[i].cmd))
            n *= len(opts)
            if n > self.max_expansions:
                return None
            choices.append(opts)
        pend_pos = {i: k for k, i in enumerate(pend)}
        out = []
        for combo in itertools.product(*choices):
            ops = []
            for i, o in enumerate(h.ops):
                if i in pend_pos:
                    c = combo[pend_pos[i]]
                    if c is None:
                        continue  # pruned: never took effect
                    # completed: took effect; response unobserved, so its
                    # linearisation point is unconstrained on the right —
                    # keep the pending sentinel response_time
                    ops.append(dataclasses.replace(o, resp=int(c)))
                else:
                    ops.append(o)
            out.append(History(ops, seed=h.seed, program_id=h.program_id))
        return out

    # -- main entry --------------------------------------------------------
    def check_histories(self, spec: Spec, histories: Sequence[History]
                        ) -> np.ndarray:
        assert spec is self.spec, \
            "JaxTPU is compiled per spec; construct one per spec"
        if not histories:
            return np.empty(0, np.int8)

        # 1. host-side pending expansion
        groups: List[Tuple[int, int]] = []  # (start, count) per input
        flat: List[History] = []
        overflow: List[int] = []
        for idx, h in enumerate(histories):
            exp = self._expand(h)
            if exp is None:
                overflow.append(idx)
                groups.append((len(flat), 0))
            else:
                groups.append((len(flat), len(exp)))
                flat.extend(exp)

        out = np.full(len(histories), int(Verdict.BUDGET_EXCEEDED), np.int8)
        if flat:
            statuses = self._run_device(flat)
            for idx, (start, count) in enumerate(groups):
                if count == 0:
                    continue
                sub = statuses[start:start + count]
                if (sub == SUCCESS).any():
                    out[idx] = int(Verdict.LINEARIZABLE)
                elif (sub == BUDGET).any():
                    out[idx] = int(Verdict.BUDGET_EXCEEDED)
                else:
                    out[idx] = int(Verdict.VIOLATION)
        return out

    def _run_device(self, flat: Sequence[History]) -> np.ndarray:
        top = _BATCH_BUCKETS[-1]
        if len(flat) > top:
            return np.concatenate([
                self._run_device(flat[i:i + top])
                for i in range(0, len(flat), top)])
        n_ops = bucket_for(max(len(h) for h in flat) or 1)
        batch = _batch_bucket(len(flat))
        enc = encode_batch(flat, self.spec.initial_state(), max_ops=n_ops)
        b = len(flat)
        cmd = np.zeros((batch, n_ops), np.int32)
        arg = np.zeros((batch, n_ops), np.int32)
        resp = np.zeros((batch, n_ops), np.int32)
        valid = np.zeros((batch, n_ops), bool)
        prec = np.zeros((batch, n_ops, n_ops), bool)
        cmd[:b] = enc.ops[:, :, 1]
        arg[:b] = enc.ops[:, :, 2]
        resp[:b] = enc.ops[:, :, 3]
        valid[:b] = enc.valid
        prec[:b] = enc.precedes()
        args = (cmd, arg, resp, valid, prec,
                enc.init_state)
        if self.sharding is not None:
            import jax
            args = tuple(
                jax.device_put(a, s) for a, s in
                zip(args, self._arg_shardings()))
        status, _iters = self._kernel(n_ops, batch)(*args)
        self.batches_run += 1
        self.device_histories += b
        return np.asarray(status)[:b]

    def _arg_shardings(self):
        """Batch-axis sharding for each kernel argument (replicated init)."""
        import jax
        from jax.sharding import PartitionSpec as P

        mesh = self.sharding.mesh
        axis = self.sharding.spec[0] if self.sharding.spec else None
        batched = jax.NamedSharding(mesh, P(axis))
        replicated = jax.NamedSharding(mesh, P())
        return (batched, batched, batched, batched, batched, replicated)
