"""P-compositionality — per-key decomposition of linearizability checking.

After Horn & Kroening (PAPERS.md:5): for specs that are products of
independent per-key objects, a history is linearizable **iff** each per-key
sub-history is linearizable against the per-key object.  Search cost is
exponential in history length, so the split turns one 256-op history over a
composed object into many short sub-problems — exactly the shape the batched
checkers want: more, smaller, independent histories per call, landing in
SMALLER compile buckets (docs/PCOMP.md).  Long-history corpora (256-1024
ops) that fit no op bucket and blow past the native checker's 64-bit taken
mask become checkable at all only through this split.

Soundness rests on the spec's own declaration, validated ONCE at compile
time (``core.spec.projection_report``): ``partition_key`` must be total (no
cross-key ops), the projected spec must faithfully model a single key, and
keys must be independent.  An invalid projection refuses to decompose
(``NotDecomposableError``) rather than silently giving unsound verdicts;
the planner's refusal path stamps the same report into its ``why``.

Certificates: a LINEARIZABLE verdict from the decomposed path carries a
STITCHED whole-history witness — the per-key witnesses merged into one
linearization order that respects whole-history real-time precedence —
which ``verify_witness`` (ops/backend.py) replays search-free.  The merge
always exists: any cycle among per-key witness edges and cross-key
real-time edges would collapse (by timestamp transitivity) into a
real-time edge WITHIN one key, which that key's witness already respects.
"""

from __future__ import annotations

import dataclasses
import heapq
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import NO_RESP, OP_BUCKETS, History, Op
from ..core.spec import Spec, projection_report
from .backend import LineariseBackend, Verdict


def split_history(spec: Spec, history: History) -> Dict[int, History]:
    """Project a history into per-key sub-histories of the projected spec.

    Timestamps are preserved, so real-time precedence *within* each key is
    exactly the induced sub-order; cross-key precedence is discarded, which
    is precisely what P-compositionality licenses."""
    return {k: h for k, (h, _) in split_history_indexed(spec, history).items()}


def split_history_indexed(
    spec: Spec, history: History
) -> Dict[int, Tuple[History, List[int]]]:
    """Like :func:`split_history`, also returning each sub-history's map
    from sub-op position to ORIGINAL op index — what witness stitching
    needs to lift per-key linearizations back onto the whole history."""
    per_key: Dict[int, Tuple[List[Op], List[int]]] = {}
    for j, op in enumerate(history.ops):
        key = spec.partition_key(op.cmd, op.arg)
        if key is None:
            raise ValueError(
                f"{spec.name}: partition_key is not total "
                f"(cmd={op.cmd}, arg={op.arg}); cannot decompose")
        if op.is_pending:
            cmd, arg, _ = spec.project_op(op.cmd, op.arg, 0)
            resp = NO_RESP
        else:
            cmd, arg, resp = spec.project_op(op.cmd, op.arg, op.resp)
        ops, idx = per_key.setdefault(key, ([], []))
        ops.append(dataclasses.replace(op, cmd=cmd, arg=arg, resp=resp))
        idx.append(j)
    return {k: (History(ops, seed=history.seed,
                        program_id=history.program_id), idx)
            for k, (ops, idx) in per_key.items()}


class NotDecomposableError(ValueError):
    """The spec declares no per-key projection, or declares one the
    compile-time validator rejects; P-compositionality cannot apply.  A
    distinct type so callers (the CLI, the planner's refusal path) can
    convert exactly this misconfiguration to a clean refusal without
    masking unrelated ValueErrors from inner-backend construction."""


# ---------------------------------------------------------------------------
# decomposition-gain gate (shared by the planner and the serve plane)
# ---------------------------------------------------------------------------

def bucket_or_none(n_ops: int) -> Optional[int]:
    """The op bucket ``n_ops`` lands in, or None past the largest — the
    form the gain gate wants (an unencodable history is "infinite")."""
    n = max(int(n_ops), 1)
    for b in OP_BUCKETS:
        if n <= b:
            return b
    return None


def longest_sub(spec: Spec, history: History) -> int:
    """Length of the longest per-key sub-history — computed by counting,
    no History objects built (the gate runs on every serve request)."""
    counts: Dict[int, int] = {}
    for op in history.ops:
        key = spec.partition_key(op.cmd, op.arg)
        if key is None:
            raise ValueError(
                f"{spec.name}: partition_key is not total "
                f"(cmd={op.cmd}, arg={op.arg}); cannot decompose")
        counts[key] = counts.get(key, 0) + 1
    return max(counts.values(), default=0)


def history_keys(spec: Spec, history: History) -> List[int]:
    """Sorted distinct partition keys a history's ops touch — the shrink
    plane's drop-key candidate axis (qsm_tpu/shrink/frontier.py): with a
    VALIDATED projection, dropping every op of one key is the coarsest
    sound op-subset shrink.  Raises on a non-total partition_key, like
    :func:`longest_sub`."""
    keys = set()
    for op in history.ops:
        key = spec.partition_key(op.cmd, op.arg)
        if key is None:
            raise ValueError(
                f"{spec.name}: partition_key is not total "
                f"(cmd={op.cmd}, arg={op.arg}); cannot decompose")
        keys.add(key)
    return sorted(keys)


def split_gain(spec: Spec, history: History) -> bool:
    """True when decomposing ``history`` buys a strictly smaller compile
    bucket (or makes an unencodable/over-mask history checkable at all).
    Equal buckets mean the split only adds lanes — not worth it."""
    sub = bucket_or_none(longest_sub(spec, history))
    if sub is None:
        return False  # even the sub-histories fit no bucket: no gain
    whole = bucket_or_none(len(history))
    return whole is None or sub < whole


class PComp:
    """Backend combinator: decompose per key, decide ALL sub-histories of
    the whole input batch in one inner-backend call, aggregate per input.

    Aggregation: VIOLATION if any key violates; else BUDGET_EXCEEDED if any
    key was undecided; else LINEARIZABLE.  ``check_witness`` additionally
    stitches the per-key witnesses into a whole-history certificate
    (module docstring).  Construction VALIDATES the spec's projection
    (``core.spec.projection_report``) and refuses unsound declarations.
    """

    def __init__(self, spec: Spec, make_inner=None):
        """``make_inner(projected_spec) -> LineariseBackend``; defaults to
        the memoised CPU oracle — the framework-wide default resolution
        oracle (one construction site; the memo-less oracle exists only for
        parity tests and the bench denominator).  A factory (not an
        instance) because device backends bind to one spec at construction
        (compile cache per spec)."""
        from .wing_gong_cpu import WingGongCPU

        self.spec = spec
        problems = projection_report(spec)
        if problems:
            raise NotDecomposableError(
                f"spec {spec.name!r} is not per-key decomposable: "
                + "; ".join(problems)
                + " (P-compositionality, PAPERS.md:5; declare CmdSig.proj "
                  "+ projected_spec(), or use a whole-history backend)")
        self.projected = spec.projected_spec()
        self.inner: LineariseBackend = (
            make_inner(self.projected) if make_inner is not None
            else WingGongCPU(memo=True))
        self.name = f"pcomp({self.inner.name})"
        # the per-key witness searcher (and BUDGET_EXCEEDED resolver) —
        # the property layer's own resolution oracle, bound to the
        # projected spec; built lazily (check_histories never needs it)
        self._witness_oracle = None
        # pcomp_* accounting (search/stats.py)
        self.histories_seen = 0
        self.split_histories = 0   # inputs that split into >1 key
        self.subs_produced = 0     # per-key sub-histories dispatched
        self.max_sub_len = 0       # longest sub-history seen (ops)
        self.recombine_s = 0.0     # split + aggregate + stitch time

    # ------------------------------------------------------------------
    def check_histories(self, spec: Spec, histories: Sequence[History]
                        ) -> np.ndarray:
        assert spec is self.spec, "PComp is bound to one spec"
        t0 = time.perf_counter()
        self.histories_seen += len(histories)
        flat: List[History] = []
        groups: List[slice] = []
        for h in histories:
            start = len(flat)
            subs = split_history(spec, h)
            flat.extend(subs.values())
            groups.append(slice(start, len(flat)))
            self.subs_produced += len(subs)
            if len(subs) > 1:
                self.split_histories += 1
            self.max_sub_len = max(
                self.max_sub_len, max((len(s) for s in subs.values()),
                                      default=0))
        out = np.full(len(histories), int(Verdict.LINEARIZABLE), np.int8)
        if not flat:
            self.recombine_s += time.perf_counter() - t0
            return out
        t1 = time.perf_counter()
        self.recombine_s += t1 - t0
        sub = self.inner.check_histories(self.projected, flat)
        t2 = time.perf_counter()
        for i, g in enumerate(groups):
            v = sub[g]
            if (v == Verdict.VIOLATION).any():
                out[i] = int(Verdict.VIOLATION)
            elif (v == Verdict.BUDGET_EXCEEDED).any():
                out[i] = int(Verdict.BUDGET_EXCEEDED)
        self.recombine_s += time.perf_counter() - t2
        return out

    # ------------------------------------------------------------------
    def check_witness(self, spec: Spec, history: History):
        """(verdict, witness): per-key witnesses stitched into ONE
        whole-history linearization (module docstring), or None when the
        verdict is not LINEARIZABLE.  The stitched witness replays
        search-free through ``verify_witness`` — the decomposed path's
        LINEARIZABLE verdicts stay exactly as auditable as the direct
        oracle's."""
        assert spec is self.spec, "PComp is bound to one spec"
        subs = split_history_indexed(spec, history)
        self.histories_seen += 1
        self.subs_produced += len(subs)
        if len(subs) > 1:
            self.split_histories += 1
        self.max_sub_len = max(
            self.max_sub_len,
            max((len(h) for h, _ in subs.values()), default=0))
        chains: List[List[Tuple[int, int]]] = []
        for key in sorted(subs):
            sub_h, idx = subs[key]
            v, w = self._sub_witness(sub_h)
            if v != Verdict.LINEARIZABLE:
                return v, None
            chains.append([(idx[j], resp) for j, resp in w])
        t0 = time.perf_counter()
        witness = stitch_witness(history, chains)
        self.recombine_s += time.perf_counter() - t0
        return Verdict.LINEARIZABLE, witness

    def _sub_witness(self, sub_h: History):
        """One per-key (verdict, witness) — from the inner backend when
        it can produce witnesses, with BUDGET_EXCEEDED resolved on the
        memoised oracle (the property layer's resolution rule)."""
        from .wing_gong_cpu import WingGongCPU

        inner_fn = getattr(self.inner, "check_witness", None)
        if inner_fn is not None:
            v, w = inner_fn(self.projected, sub_h)
            if v != Verdict.BUDGET_EXCEEDED:
                return Verdict(int(v)), w
        if self._witness_oracle is None:
            self._witness_oracle = WingGongCPU(memo=True)
        v, w = self._witness_oracle.check_witness(self.projected, sub_h)
        return Verdict(int(v)), w

    # ------------------------------------------------------------------
    def search_stats(self):
        """The decomposition's own shape/cost record with the inner
        engine's counters absorbed — a decomposed rate must say it
        decomposed, and into what (search/stats.py)."""
        from ..search.stats import SearchStats, collect_search_stats

        st = SearchStats(
            engine=self.name,
            histories=self.histories_seen,
            pcomp_split=self.split_histories,
            pcomp_subs=self.subs_produced,
            pcomp_max_sub=self.max_sub_len,
            pcomp_recombine_ms=int(self.recombine_s * 1000),
        )
        st.absorb(collect_search_stats(self.inner))
        if self._witness_oracle is not None:
            # per-key witness searches are host nodes this combinator
            # spent; hiding them would overstate the decomposed rate
            st.absorb(collect_search_stats(self._witness_oracle))
        return st


# ---------------------------------------------------------------------------
# witness stitching
# ---------------------------------------------------------------------------

def stitch_witness(history: History,
                   chains: Sequence[Sequence[Tuple[int, int]]]
                   ) -> List[Tuple[int, int]]:
    """Merge per-key linearizations into one whole-history witness.

    ``chains``: per key, ``(original_op_index, resp)`` pairs in that
    key's linearization order.  The merge respects (a) every chain's own
    order and (b) whole-history real-time precedence between LISTED ops
    (pruned pending ops appear in no chain and are simply omitted — they
    precede nothing, so dropping them constrains nothing).  Kahn's
    algorithm with a min-heap on original index makes the result
    deterministic.  Acyclicity is a theorem, not a hope (module
    docstring); a cycle therefore raises — it means the split itself was
    unsound, which must never be papered over with a bad certificate."""
    resp_of: Dict[int, int] = {}
    order: Dict[int, List[int]] = {}   # adjacency (original indices)
    indeg: Dict[int, int] = {}
    for chain in chains:
        for j, resp in chain:
            resp_of[j] = resp
            order.setdefault(j, [])
            indeg.setdefault(j, 0)
        for (a, _), (b, _) in zip(chain, chain[1:]):
            order[a].append(b)
            indeg[b] += 1
    listed = sorted(resp_of)
    prec = history.precedes_matrix()
    for a in listed:
        for b in listed:
            if prec[a, b]:
                order[a].append(b)
                indeg[b] += 1
    heap = [j for j in listed if indeg[j] == 0]
    heapq.heapify(heap)
    out: List[Tuple[int, int]] = []
    while heap:
        j = heapq.heappop(heap)
        out.append((j, resp_of[j]))
        for b in order[j]:
            indeg[b] -= 1
            if indeg[b] == 0:
                heapq.heappush(heap, b)
    if len(out) != len(listed):
        raise RuntimeError(
            "witness stitch found a precedence cycle — the per-key "
            "split was unsound for this history; refusing to emit a "
            "false certificate")
    return out
