"""P-compositionality — per-key decomposition of linearizability checking.

After Horn & Kroening (PAPERS.md:5): for specs that are products of
independent per-key objects, a history is linearizable **iff** each per-key
sub-history is linearizable against the per-key object.  The split turns one
history of ≤64 ops over 16 pids (config #5, BASELINE.json:11) into K small
sub-problems — exactly the shape the batched device kernel wants: more,
smaller, independent histories per ``vmap`` batch (SURVEY.md §2b).

Soundness rests on the spec's own declaration (SURVEY.md §7 hard-parts #3):
``partition_key`` must be total (no cross-key ops) and the projected spec
must faithfully model a single key.  ``PComp`` validates totality at runtime
and refuses to decompose otherwise, rather than silently giving unsound
verdicts.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import numpy as np

from ..core.history import NO_RESP, History, Op
from ..core.spec import Spec
from .backend import LineariseBackend, Verdict


def split_history(spec: Spec, history: History) -> Dict[int, History]:
    """Project a history into per-key sub-histories of the projected spec.

    Timestamps are preserved, so real-time precedence *within* each key is
    exactly the induced sub-order; cross-key precedence is discarded, which
    is precisely what P-compositionality licenses."""
    per_key: Dict[int, List[Op]] = {}
    for op in history.ops:
        key = spec.partition_key(op.cmd, op.arg)
        if key is None:
            raise ValueError(
                f"{spec.name}: partition_key is not total "
                f"(cmd={op.cmd}, arg={op.arg}); cannot decompose")
        if op.is_pending:
            cmd, arg, _ = spec.project_op(op.cmd, op.arg, 0)
            resp = NO_RESP
        else:
            cmd, arg, resp = spec.project_op(op.cmd, op.arg, op.resp)
        per_key.setdefault(key, []).append(
            dataclasses.replace(op, cmd=cmd, arg=arg, resp=resp))
    return {k: History(ops, seed=history.seed,
                       program_id=history.program_id)
            for k, ops in per_key.items()}


class NotDecomposableError(ValueError):
    """The spec declares no per-key projection; P-compositionality cannot
    apply.  A distinct type so callers (the CLI) can convert exactly this
    misconfiguration to a clean exit without masking unrelated
    ValueErrors from inner-backend construction."""


class PComp:
    """Backend combinator: decompose per key, decide ALL sub-histories of
    the whole input batch in one inner-backend call, aggregate per input.

    Aggregation: VIOLATION if any key violates; else BUDGET_EXCEEDED if any
    key was undecided; else LINEARIZABLE.
    """

    def __init__(self, spec: Spec, make_inner=None):
        """``make_inner(projected_spec) -> LineariseBackend``; defaults to
        the memoised CPU oracle — the framework-wide default resolution
        oracle (one construction site; the memo-less oracle exists only for
        parity tests and the bench denominator).  A factory (not an
        instance) because device backends bind to one spec at construction
        (compile cache per spec)."""
        from .wing_gong_cpu import WingGongCPU

        self.spec = spec
        if not hasattr(spec, "projected_spec"):
            raise NotDecomposableError(
                f"spec {spec.name!r} is not per-key decomposable: "
                "P-compositionality needs projected_spec()/project_op() "
                "and a partition_key (PAPERS.md:5); use a whole-history "
                "backend for this spec")
        self.projected = spec.projected_spec()
        self.inner: LineariseBackend = (
            make_inner(self.projected) if make_inner is not None
            else WingGongCPU(memo=True))
        self.name = f"pcomp({self.inner.name})"

    def check_histories(self, spec: Spec, histories: Sequence[History]
                        ) -> np.ndarray:
        assert spec is self.spec, "PComp is bound to one spec"
        flat: List[History] = []
        groups: List[slice] = []
        for h in histories:
            start = len(flat)
            flat.extend(split_history(spec, h).values())
            groups.append(slice(start, len(flat)))
        out = np.full(len(histories), int(Verdict.LINEARIZABLE), np.int8)
        if not flat:
            return out
        sub = self.inner.check_histories(self.projected, flat)
        for i, g in enumerate(groups):
            v = sub[g]
            if (v == Verdict.VIOLATION).any():
                out[i] = int(Verdict.VIOLATION)
            elif (v == Verdict.BUDGET_EXCEEDED).any():
                out[i] = int(Verdict.BUDGET_EXCEEDED)
        return out
