"""Scalarization — pack small vector states into one scalar.

The device kernel has two step regimes (ops/jax_kernel.py): scalar-state
specs with a declared bound get a per-history ``[S, n_ops]`` step TABLE
built once per chunk call, and the while-loop body does a single dynamic
row gather per iteration; vector-state specs re-evaluate a vmapped
``step_jax`` over all ops EVERY iteration — the dominant per-iteration
cost, and the reason the round-2 verdict called vector specs the device's
worst case.

When a vector spec declares per-element domain bounds
(``Spec.state_elem_bounds``), its reachable states embed injectively into
``[0, prod(bounds))`` by mixed-radix packing.  :class:`Scalarized` is the
resulting scalar spec: ``step`` = unpack → inner step → pack.  The
packing is a bijection between reachable vector states and their images,
so the search tree, the candidate order, and the verdict are identical
to running the inner spec directly; iteration counts agree up to memo
hash-collision luck (the cache keys change width, so single-slot
collisions land on different entries).  What changes is the
per-iteration cost: a table row gather instead of a vmapped step sweep,
one-word memo keys instead of STATE_DIM words — measured 1.85× on the
queue-48 device corpus (docs/EXPERIMENTS.md).

``JaxTPU`` applies this transparently when the packed domain is small
(see ``scalar_shadow``); the queue/stack/KV parity suites pin the
equivalence.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.spec import Spec

# Packed domains beyond this get no table: rows × n_ops × 4 bytes must
# stay small next to the kernel's own carry (65536 × 64 ops ≈ 16 MB of
# table per chunk call is the ceiling we accept before the sweep regime
# is the better trade).
MAX_PACKED_STATES = 65_536


class Scalarized(Spec):
    """Scalar shadow of a vector spec with declared element bounds."""

    STATE_DIM = 1

    def __init__(self, inner: Spec):
        bounds = inner.state_elem_bounds()
        if bounds is None or inner.STATE_DIM != len(bounds):
            raise ValueError(
                f"{inner.name}: state_elem_bounds must give one exclusive "
                f"bound per state element to scalarize")
        self.inner = inner
        self.bounds = [int(b) for b in bounds]
        self.CMDS = inner.CMDS
        self.name = f"scalarized({inner.name})"
        # mixed-radix place values: element i contributes state[i]*radix[i]
        self.radix = [1] * len(self.bounds)
        for i in range(1, len(self.bounds)):
            self.radix[i] = self.radix[i - 1] * self.bounds[i - 1]
        self.n_packed = self.radix[-1] * self.bounds[-1]

    # -- packing ----------------------------------------------------------
    def pack(self, state: Sequence[int]) -> int:
        if len(state) != len(self.bounds):
            raise ValueError(
                f"state has {len(state)} elements, spec declares "
                f"{len(self.bounds)}")
        total = 0
        for v, r, b in zip(state, self.radix, self.bounds):
            v = int(v)
            if not 0 <= v < b:
                raise ValueError(
                    f"state element {v} outside declared bound {b}")
            total += v * r
        return total

    def unpack(self, packed: int) -> list:
        out = []
        for b in self.bounds:
            out.append(packed % b)
            packed //= b
        return out

    def in_bounds(self, state: Sequence[int]) -> bool:
        return (len(state) == len(self.bounds)
                and all(0 <= int(v) < b
                        for v, b in zip(state, self.bounds)))

    # -- Spec protocol ----------------------------------------------------
    def initial_state(self) -> np.ndarray:
        return np.asarray([self.pack(self.inner.initial_state())], np.int32)

    def scalar_state_bound(self, n_ops):
        return self.n_packed

    def spec_kwargs(self):
        return self.inner.spec_kwargs()

    def step_py(self, state, cmd, arg, resp):
        vec, ok = self.inner.step_py(self.unpack(int(state[0])), cmd, arg,
                                     resp)
        return [self.pack(vec)], ok

    def step_jax(self, state, cmd, arg, resp):
        import jax.numpy as jnp

        packed = state[0]
        vec = []
        for b in self.bounds:
            vec.append(packed % b)
            packed = packed // b
        nxt, ok = self.inner.step_jax(
            jnp.stack(vec).astype(jnp.int32), cmd, arg, resp)
        total = jnp.int32(0)
        for i, r in enumerate(self.radix):
            total = total + nxt[i].astype(jnp.int32) * jnp.int32(r)
        return jnp.stack([total]), ok


def scalar_shadow(spec: Spec,
                  max_states: int = MAX_PACKED_STATES
                  ) -> Optional[Scalarized]:
    """A :class:`Scalarized` shadow of ``spec`` if it declares element
    bounds and the packed domain is small enough to tabulate, else None
    (scalar specs need no shadow; they already ride the table path)."""
    if spec.STATE_DIM == 1:
        return None
    bounds = spec.state_elem_bounds()
    if bounds is None:
        return None
    n = 1
    for b in bounds:
        n *= int(b)
        if n > max_states:
            return None
    return Scalarized(spec)
