"""Per-history strategy routing — the ``auto-tpu`` backend.

Round 3 measured that no fixed device strategy is right for every history
(VERDICT.md round 3, "What's weak" #3): quiescent-cut segmentation
(``SegDC``) is the best path for histories that shatter into many small
segments (the device then works in a small op bucket and the host
enumeration per middle segment is trivial), but it is up to 14× WORSE than
the plain kernel on concurrency-dense histories whose largest segment is
nearly the whole history — the host middle-segment enumeration explodes
while the plain kernel would have decided the history in one batched
dispatch.

The router reads each history's cheap structural features from
``split_at_quiescent_cuts`` (O(n log n), the same split SegDC itself
performs) and partitions the batch.  The cost driver for SegDC's host
middle-segment enumeration is segment **width** (maximum number of
mutually-overlapping ops — the branching factor of the end-state walk),
not segment length: round-4 measurement showed 2-pid corpora with 80-op
middle segments decide 2-4.6× FASTER via segdc (narrow segments walk in
near-linear time and the device does almost nothing), while 8-pid
corpora with equally long but WIDE middles decide up to 14× slower
(round-3 sweep).  So:

* **plain** (``JaxTPU``): histories with no cuts, or any middle segment
  wider than ``WIDTH_CAP`` concurrent ops (host enumeration risk).
  Scalarization remains the kernel's own auto decision
  (ops/scalarize.py).
* **segdc** (``SegDC`` over the SAME inner kernel instance — one compile
  cache): cut histories whose middle segments are all narrow; the host
  walks them near-linearly and the device decides only the (short)
  final segments from the threaded frontier.

Specs that declare a per-key projection (``projected_spec`` +
``partition_key``) are decomposed FIRST via ``PComp``, with a nested
router on the projected spec — per-key sub-histories are sparser, so they
cut more often and the segdc path gets more use exactly where it helps.

Verdict parity: both strategies are exact (BUDGET_EXCEEDED, never a
guess), so routing changes cost only — pinned by tests/test_router.py
against the oracle on mixed corpora.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec
from .backend import LineariseBackend
from .segdc import SegDC, split_at_quiescent_cuts


def _width(ops) -> int:
    """Maximum number of mutually-overlapping ops in a segment (sweep
    line over invoke/response endpoints)."""
    events = []
    for o in ops:
        events.append((o.invoke_time, 1))
        events.append((o.response_time, -1))
    # responses sort before same-time invokes: a response at t does not
    # overlap an invoke at t (matches precedes_matrix's strict <)
    events.sort(key=lambda e: (e[0], e[1]))
    width = peak = 0
    for _, d in events:
        width += d
        peak = max(peak, width)
    return peak


class AutoDevice:
    """Backend combinator: route each history to the cheapest device
    strategy by segment structure (module docstring has the rule)."""

    # Widest middle segment (max mutually-overlapping ops) the host is
    # willing to enumerate: the end-state walk's branching is exponential
    # in width, near-linear in length (module docstring has the round-3/4
    # measurements behind the caps; re-tune on-chip when a window opens).
    # With the NATIVE enumerator (segdc.default_middle_oracle found the
    # toolchain) the cap is far higher: width-8 middles that cost the
    # Python walk 3.6× plain measured 40× FASTER than plain natively
    # (cas 8-pid 128-op sweep corpus: segdc 0.15 s vs plain 6.4 s).
    # Width 12 still bounds the 2^width state×mask blowup the native
    # memo can hit on untested 16-pid-wide segments.
    WIDTH_CAP = 4
    NATIVE_WIDTH_CAP = 12

    def __init__(self, spec: Spec,
                 make_inner: Optional[Callable] = None,
                 width_cap: Optional[int] = None,
                 **inner_kw):
        from .jax_kernel import JaxTPU

        self.spec = spec
        # explicit cap overrides BOTH class defaults — the on-chip
        # retune knob (CPU-fallback and real-TPU economics differ: a
        # fast chip moves the plain/segdc crossover toward plain)
        self._width_cap_override = width_cap
        make = make_inner or (lambda s: JaxTPU(s, **inner_kw))
        self.pcomp = None
        if hasattr(spec, "projected_spec"):
            # per-key decomposition first; each projected sub-history is
            # routed by a nested AutoDevice bound to the projected spec.
            # An UNSOUND declaration refuses (core.spec.projection_report
            # via PComp) and the router falls back to whole-history
            # routing — the refusal path, never a silent unsound split
            from .pcomp import NotDecomposableError, PComp

            try:
                self.pcomp = PComp(
                    spec, make_inner=lambda s: AutoDevice(
                        s, make_inner=make, width_cap=width_cap))
            except NotDecomposableError:
                self.pcomp = None
            else:
                self.name = f"auto({self.pcomp.name})"
                return
        self.plain: LineariseBackend = make(spec)
        # the SAME kernel instance serves as SegDC's inner backend (one
        # compile/bucket cache across both routes); SegDC's default
        # middle-segment enumerator already prefers the native checker
        # (segdc.default_middle_oracle)
        self.segdc = SegDC(spec, make_inner=lambda s: self.plain)
        # native middle enumerator present? (drives the width cap below)
        self._native_mid = hasattr(self.segdc.oracle, "end_states")
        self.name = f"auto({self.plain.name})"
        self.routed_plain = 0
        self.routed_segdc = 0

    def _route_segdc(self, h: History) -> bool:
        segs = split_at_quiescent_cuts(h)
        if len(segs) < 2:
            return False
        # host middle-segment enumeration risk is exponential in WIDTH;
        # the native enumerator pushes the affordable width well past
        # the Python walk's
        cap = self._width_cap_override
        if cap is None:
            cap = (self.NATIVE_WIDTH_CAP if self._native_mid
                   else self.WIDTH_CAP)
        return all(_width(seg) <= cap for seg in segs[:-1])

    def search_stats(self):
        """Both routes' cost record under one engine name.  The segdc
        combinator shares THE SAME kernel instance as the plain route
        (one compile cache), so its record — which absorbs the inner
        kernel — already covers both routes' device work; ``histories``
        is overridden to the router's own routed total (the kernel's
        lane count double-books pending expansion, and segdc's seen
        count covers only its route)."""
        from ..search.stats import SearchStats, collect_search_stats

        if self.pcomp is not None:
            st = collect_search_stats(self.pcomp) or SearchStats()
            st.engine = self.name
            return st
        st = self.segdc.search_stats()
        st.histories = self.routed_plain + self.routed_segdc
        st.engine = self.name
        # a failover-wrapped inner kernel surfaces its degradation
        # counters through the router too (resilience plane)
        from ..resilience.failover import collect_resilience

        r = collect_resilience(self.plain)
        st.degradations += r.get("degradations", 0)
        st.retries += r.get("retries", 0)
        if not st.fallback_engine and r.get("fallback_engine"):
            st.fallback_engine = r["fallback_engine"]
        return st

    def resilience(self) -> dict:
        """Counters from whichever engine actually dispatches (the
        shared inner kernel, possibly failover-wrapped)."""
        from ..resilience.failover import collect_resilience

        inner = self.pcomp if self.pcomp is not None else self.plain
        return collect_resilience(inner)

    def check_histories(self, spec: Spec, histories: Sequence[History]
                        ) -> np.ndarray:
        assert spec is self.spec, "AutoDevice is bound to one spec"
        if self.pcomp is not None:
            return self.pcomp.check_histories(spec, histories)
        out = np.empty(len(histories), np.int8)
        seg_idx: List[int] = []
        plain_idx: List[int] = []
        for i, h in enumerate(histories):
            (seg_idx if self._route_segdc(h) else plain_idx).append(i)
        self.routed_plain += len(plain_idx)
        self.routed_segdc += len(seg_idx)
        if plain_idx:
            sub = self.plain.check_histories(
                spec, [histories[i] for i in plain_idx])
            for i, v in zip(plain_idx, sub):
                out[i] = v
        if seg_idx:
            sub = self.segdc.check_histories(
                spec, [histories[i] for i in seg_idx])
            for i, v in zip(seg_idx, sub):
                out[i] = v
        return out
