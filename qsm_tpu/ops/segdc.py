"""Decrease-and-conquer segmentation — long histories split at quiescent cuts.

After the decrease-and-conquer linearizability-monitoring idea (PAPERS.md:9;
SURVEY.md §5 long-context row, third mechanism): the exponential cost of the
interleaving search is in the HISTORY LENGTH, so decompose the history into
independent, shorter problems wherever the real-time order allows it.

A **quiescent cut** is a position where every earlier operation's response
precedes every later operation's invocation.  Any linearization must order
the two sides as blocks (every cross-cut pair is precedence-ordered), so:

    H = S1 · S2 · … · Sk   (cut at quiescent points)
    H linearizable from s0
        ⟺  ∃ s1 ∈ endstates(S1, s0): ∃ s2 ∈ endstates(S2, s1): … Sk sat.

Unlike P-compositionality (per-key independence, a SPEC property), cuts are
a property of each individual HISTORY — concurrency-dense histories may
have none, in which case the inner backend decides them whole.  The two
combinators compose: ``PComp`` splits per key, per-key sub-histories are
sparser, so they cut more often.

Segment checking threads a FRONTIER of model states:

* middle segments (never contain pending ops — a pending op's missing
  response forbids any later cut) are exhaustively searched per frontier
  state, memoised on (taken-set, state), collecting the set of reachable
  end states;
* the final segment (pending ops allowed) only needs satisfiability, which
  is a search started from a frontier state — ``WingGongCPU.check_from`` on
  the host, or, when the inner backend supports per-lane initial states
  (``JaxTPU.check_histories(..., init_states=…)``), ONE batched device call
  deciding every (final segment × frontier state) pair across the whole
  input batch at once (VERDICT.md round 2, "Next round" #6: segments, not
  just uncut wholes, decided on the device).

Exactness: verdicts equal the plain oracle's on every history (the block
decomposition above is an iff), with BUDGET_EXCEEDED when the node budget
runs out — never a guess.
"""

from __future__ import annotations

import inspect
from typing import Callable, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.history import History, Op
from ..core.spec import Spec
from .backend import LineariseBackend, Verdict
from .wing_gong_cpu import WingGongCPU


def split_at_quiescent_cuts(history: History) -> List[List[Op]]:
    """Invoke-ordered segments; a cut sits before op i iff every earlier
    op's response_time < op i's invoke_time.  Pending ops (sentinel
    response_time) forbid all later cuts, so they always land in the final
    segment."""
    ops = sorted(history.ops, key=lambda o: o.invoke_time)
    segments: List[List[Op]] = []
    current: List[Op] = []
    max_resp = -1
    for op in ops:
        if current and max_resp < op.invoke_time:
            segments.append(current)
            current = []
        current.append(op)
        max_resp = max(max_resp, op.response_time)
    if current:
        segments.append(current)
    return segments


class _Budget:
    __slots__ = ("left",)

    def __init__(self, n: int):
        self.left = n


def _end_states(spec: Spec, ops: List[Op], starts: Set[Tuple[int, ...]],
                budget: _Budget) -> Optional[Set[Tuple[int, ...]]]:
    """All model states reachable by SOME complete valid linearization of
    ``ops`` (no pending ops) from any state in ``starts``; None on budget
    exhaustion.  Memoised on (taken-mask, state) per start so shared
    subtrees are walked once."""
    n = len(ops)
    prec_pairs: List[List[int]] = [
        [i for i in range(n) if ops[i].response_time < ops[j].invoke_time]
        for j in range(n)
    ]
    full = (1 << n) - 1
    out: Set[Tuple[int, ...]] = set()
    for start in starts:
        seen: Set[Tuple[int, Tuple[int, ...]]] = set()

        def dfs(taken: int, state: Tuple[int, ...]) -> bool:
            """Explore; returns False on budget exhaustion."""
            if taken == full:
                out.add(state)
                return True
            if (taken, state) in seen:
                return True
            seen.add((taken, state))
            for j in range(n):
                if taken & (1 << j):
                    continue
                if any(not taken & (1 << i) for i in prec_pairs[j]):
                    continue
                budget.left -= 1
                if budget.left <= 0:
                    return False
                op = ops[j]
                new_state, ok = spec.step_py(list(state), op.cmd, op.arg,
                                             op.resp)
                if not ok:
                    continue
                if not dfs(taken | (1 << j),
                           tuple(int(v) for v in new_state)):
                    return False
            return True

        if not dfs(0, start):
            return None
    return out


def default_middle_oracle(spec: Spec):
    """SegDC's default middle-segment enumerator: the native checker when
    the toolchain is present (its ``end_states`` walks middles 3-10×
    faster than the Python DFS — docs/EXPERIMENTS.md round 4), else the
    memoised Python oracle.  Callers that specifically want the pure-
    Python reference pass ``oracle=WingGongCPU(memo=True)`` explicitly."""
    try:
        from ..native import CppOracle, native_available

        if native_available():
            cpp = CppOracle(spec)
            # toolchain present is not enough: a spec with no native
            # route (no scalar table, no vector kernel, or past the C++
            # state cap) would make end_states always answer None and
            # every middle segment fall through to the Python walk —
            # while callers (ops/router.py) tune for native costs
            if cpp.can_enumerate():
                return cpp
    except Exception:  # noqa: BLE001 — optional fast path only
        pass
    return WingGongCPU(memo=True)


class SegDC:
    """Backend combinator: split each history at quiescent cuts, thread the
    frontier of reachable model states through the segments; histories with
    no cuts go to the inner backend whole (the combinator never makes a
    history harder)."""

    def __init__(self, spec: Spec,
                 make_inner: Optional[Callable] = None,
                 node_budget: int = 10_000_000,
                 oracle: Optional[WingGongCPU] = None,
                 device_final: Optional[bool] = None):
        self.spec = spec
        self.inner: LineariseBackend = (
            make_inner(spec) if make_inner is not None
            else WingGongCPU(memo=True))
        # final-segment satisfiability needs a start-state-parameterised
        # search: the host oracle's ``check_from``, or — when the inner
        # backend's ``check_histories`` takes ``init_states`` (JaxTPU) —
        # one batched device call across all (segment × frontier state)
        # pairs.  Auto-detected from the signature; override explicitly
        # with ``device_final``.
        self.oracle = oracle or default_middle_oracle(spec)
        if device_final is None:
            try:
                device_final = "init_states" in inspect.signature(
                    self.inner.check_histories).parameters
            except (TypeError, ValueError):
                device_final = False
        self.device_final = bool(device_final)
        self.node_budget = node_budget
        self.name = f"segdc({self.inner.name})"
        self.segments_split = 0    # histories that actually cut
        self.segments_total = 0    # segments across them
        self.final_states_device = 0  # (segment × state) lanes sent to device
        self.segments_native = 0   # middle segments enumerated natively
        self.histories_seen = 0    # inputs (whole + split)
        self.middle_nodes = 0      # host nodes spent enumerating middles

    def check_histories(self, spec: Spec, histories: Sequence[History]
                        ) -> np.ndarray:
        assert spec is self.spec, "SegDC is bound to one spec"
        self.histories_seen += len(histories)
        out = np.empty(len(histories), np.int8)
        whole: List[int] = []   # indices delegated to the inner backend
        # (index, final-segment history, sorted frontier states) triples of
        # histories whose middle segments resolved — their final-segment
        # satisfiability checks are batched below
        finals: List[Tuple[int, History, List[Tuple[int, ...]]]] = []
        for i, h in enumerate(histories):
            segs = split_at_quiescent_cuts(h)
            if len(segs) <= 1:
                whole.append(i)
                continue
            self.segments_split += 1
            self.segments_total += len(segs)
            budget = _Budget(self.node_budget)
            frontier: Set[Tuple[int, ...]] = {
                tuple(int(v) for v in spec.initial_state())}
            verdict: Optional[Verdict] = None
            native_ends = getattr(self.oracle, "end_states", None)
            for seg in segs[:-1]:
                nxt = None
                if native_ends is not None:
                    # native middle-segment enumeration (CppOracle); a
                    # None answer (unsupported spec/segment, budget or
                    # output cap) falls through to the Python walk, which
                    # resumes with the charged-down shared budget
                    nxt = native_ends(spec, seg, frontier, budget=budget)
                    if nxt is not None:
                        self.segments_native += 1
                if nxt is None:
                    nxt = _end_states(spec, seg, frontier, budget)
                if nxt is None:
                    verdict = Verdict.BUDGET_EXCEEDED
                    break
                if not nxt:
                    verdict = Verdict.VIOLATION
                    break
                frontier = nxt
            self.middle_nodes += self.node_budget - budget.left
            if verdict is not None:
                out[i] = int(verdict)
                continue
            last = History(segs[-1], seed=h.seed, program_id=h.program_id)
            # sorted: set order is run-dependent; the device batch layout
            # (and so any budget-tie behavior) must be deterministic
            finals.append((i, last, sorted(frontier)))
        if finals:
            if self.device_final:
                self._resolve_finals_device(spec, finals, out)
            else:
                for i, last, states in finals:
                    out[i] = int(self._final_on_oracle(spec, last, states))
        if whole:
            sub = self.inner.check_histories(
                spec, [histories[i] for i in whole])
            for i, v in zip(whole, sub):
                out[i] = v
        return out

    def search_stats(self):
        """Segment accounting plus the inner engine's own counters — a
        decomposition's cost is the middles' host nodes AND whatever the
        inner backend paid on finals/uncut wholes (search/stats.py)."""
        from ..search.stats import SearchStats, collect_search_stats

        st = SearchStats(
            engine=self.name,
            histories=self.histories_seen,
            nodes_explored=self.middle_nodes,
            segments_split=self.segments_split,
            segments_total=self.segments_total,
        )
        st.absorb(collect_search_stats(self.inner))
        return st

    def _resolve_finals_device(self, spec: Spec, finals, out) -> None:
        """ONE batched inner-backend call deciding every (final segment ×
        frontier state) pair; linearizable-from-ANY-state wins, else any
        budget blowup defers honestly to the oracle-resolving caller."""
        flat_h: List[History] = []
        flat_s: List[np.ndarray] = []
        spans: List[Tuple[int, int]] = []
        for _, last, states in finals:
            spans.append((len(flat_h), len(states)))
            flat_h.extend([last] * len(states))
            flat_s.extend(np.asarray(s, np.int32) for s in states)
        verdicts = self.inner.check_histories(spec, flat_h,
                                              init_states=flat_s)
        self.final_states_device += len(flat_h)
        for (i, _, _), (start, count) in zip(finals, spans):
            sub = np.asarray(verdicts[start:start + count])
            if (sub == int(Verdict.LINEARIZABLE)).any():
                out[i] = int(Verdict.LINEARIZABLE)
            elif (sub == int(Verdict.BUDGET_EXCEEDED)).any():
                out[i] = int(Verdict.BUDGET_EXCEEDED)
            else:
                out[i] = int(Verdict.VIOLATION)

    def _final_on_oracle(self, spec: Spec, last: History,
                         states: List[Tuple[int, ...]]) -> Verdict:
        saw_budget = False
        for state in states:
            v = self.oracle.check_from(spec, last, np.asarray(state))
            if v == Verdict.LINEARIZABLE:
                return v
            if v == Verdict.BUDGET_EXCEEDED:
                saw_budget = True
        return (Verdict.BUDGET_EXCEEDED if saw_budget
                else Verdict.VIOLATION)
