"""Hybrid device+host backend — the priced version of the property
layer's oracle-resolution contract.

The first real-TPU capture (BENCH_TPU_r04.json) and its scale-scan
diagnostics showed the chunked device driver's cost concentrating in the
straggler tail: with the full rescue ladder the CAS bench corpus runs at
a fraction of the rate the same batch reaches when stragglers are allowed
to report BUDGET_EXCEEDED after the base 2k budget (CPU-fallback
measurement: 228 h/s full-rescue vs 1318 h/s decided-rate with 5.5%
undecided — tools/bench_scale.py ``budget2k`` variant).  The fastest
EXACT plan is therefore: device decides the easy majority under a tight
budget, the tail goes to the best host checker (native C++ oracle when
the toolchain is present, the memoised Wing–Gong oracle otherwise).

That is exactly what the property layer already does between ``backend``
and ``oracle`` (core/property.py oracle resolution; SURVEY.md §7
hard-parts #5) — this module packages it as a plain
:class:`~qsm_tpu.ops.backend.LineariseBackend` so the CLI, fuzzer, and
bench tools can run the plan as ONE backend with honest counters.

Verdict contract: bit-identical to running the tail checker alone
(the device's decided verdicts are parity-pinned against the oracle by
the kernel test suite; the tail only ever sees lanes the device did not
decide).  BUDGET_EXCEEDED survives only if the tail itself gives up
(node-budget cap), which the property layer resolves as before.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec
from .backend import Verdict, device_error_types


class HybridDevice:
    """Device majority under a tight budget; host tail for the stragglers.

    ``budget``: per-lane device iteration cap before a lane defers to the
    tail (the round-4 capture's knee sits near the default 2k).
    ``tail``: any LineariseBackend; default = native C++ oracle when
    available, else the memoised Wing–Gong oracle.

    Resilience: the tail is ALREADY a full exact checker, so mid-run
    device loss (dispatch raising the XLA runtime error, an injected
    fault, a seized chip) degrades this backend in place — the whole
    batch goes to the tail, later batches skip the dead device, and the
    ``degradations``/``fallback_engine`` counters record it
    (resilience/failover.py defines the shared taxonomy).
    """

    name = "hybrid_device"

    def __init__(self, spec: Spec, budget: int = 2_000,
                 tail=None, **device_kw):
        from .jax_kernel import JaxTPU

        self.spec = spec
        # no mid/rescue ladder: stragglers are the tail's job
        self.device = JaxTPU(spec, budget=budget,
                             mid_budget=0, rescue_budget=0, **device_kw)
        if tail is None:
            tail = _default_tail(spec)
        self.tail = tail
        self.tail_histories = 0   # lanes the host tail decided for us
        self.device_decided = 0
        self.degraded = False     # device lost mid-run: tail-only now
        self.degradations = 0
        self.fallback_engine = ""
        self.last_error = ""

    def _degrade(self, err: BaseException) -> None:
        self.degraded = True
        self.degradations += 1
        self.fallback_engine = getattr(self.tail, "name",
                                       type(self.tail).__name__)
        self.last_error = f"{type(err).__name__}: {err}"[:200]
        # same global-sink report as FailoverBackend._degrade: the
        # flight ring must show a mid-run device loss even when nobody
        # plumbed an obs handle down to the engine layer (qsm_tpu/obs)
        from ..obs import emit_global

        emit_global("failover.degrade", engine=self.name,
                    fallback=self.fallback_engine,
                    error=self.last_error)

    def check_histories(self, spec: Spec,
                        histories: Sequence[History]) -> np.ndarray:
        out = np.full(len(histories), int(Verdict.BUDGET_EXCEEDED),
                      np.int8)
        if not self.degraded:
            try:
                out = np.asarray(
                    self.device.check_histories(spec, histories),
                    dtype=np.int8)
            except device_error_types() as e:
                # device lost mid-run: every lane becomes a "straggler"
                # and the exact tail decides it — verdicts unchanged,
                # only the engine that computed them
                self._degrade(e)
        und = np.nonzero(out == int(Verdict.BUDGET_EXCEEDED))[0]
        self.device_decided += len(histories) - und.size
        if und.size:
            tail_v = np.asarray(self.tail.check_histories(
                spec, [histories[i] for i in und]), dtype=np.int8)
            out[und] = tail_v
            self.tail_histories += int(und.size)
        return out

    def check_witness(self, spec: Spec, history: History):
        """Witness from whichever side decided the history (device
        witnesses verify search-free; host oracles produce their own)."""
        if not self.degraded:
            try:
                v = Verdict(int(
                    self.device.check_histories(spec, [history])[0]))
                if v != Verdict.BUDGET_EXCEEDED:
                    return self.device.check_witness(spec, history)
            except device_error_types() as e:
                self._degrade(e)
        return self.tail.check_witness(spec, history)

    def resilience(self) -> dict:
        """Self-describing fault-handling block for bench rows / CLI
        stats (resilience/failover.py collect_resilience contract)."""
        return {
            "degradations": self.degradations,
            "retries": 0,
            "fallback_engine": self.fallback_engine or None,
            "device_histories": self.device_decided,
            "fallback_histories": self.tail_histories,
            **({"last_error": self.last_error} if self.last_error else {}),
        }

    def search_stats(self):
        """Device lockstep cost AND host tail nodes, side by side — the
        honest composed form (search/stats.py): device iterations saved by
        deferring stragglers are only savings when the tail's node count
        is shown next to them."""
        from ..search.stats import collect_search_stats

        st = self.device.search_stats()
        st.engine = self.name
        st.tail_histories = self.tail_histories
        st.degradations += self.degradations
        if self.fallback_engine:
            st.fallback_engine = self.fallback_engine
        st.absorb(collect_search_stats(self.tail))
        return st


def _default_tail(spec: Spec):
    # one ladder definition for the whole stack: the hybrid tail and the
    # failover plane's degradation target are the SAME host checker
    from ..resilience.failover import host_fallback

    return host_fallback(spec)
