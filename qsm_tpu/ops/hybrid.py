"""Hybrid device+host backend — the priced version of the property
layer's oracle-resolution contract.

The first real-TPU capture (BENCH_TPU_r04.json) and its scale-scan
diagnostics showed the chunked device driver's cost concentrating in the
straggler tail: with the full rescue ladder the CAS bench corpus runs at
a fraction of the rate the same batch reaches when stragglers are allowed
to report BUDGET_EXCEEDED after the base 2k budget (CPU-fallback
measurement: 228 h/s full-rescue vs 1318 h/s decided-rate with 5.5%
undecided — tools/bench_scale.py ``budget2k`` variant).  The fastest
EXACT plan is therefore: device decides the easy majority under a tight
budget, the tail goes to the best host checker (native C++ oracle when
the toolchain is present, the memoised Wing–Gong oracle otherwise).

That is exactly what the property layer already does between ``backend``
and ``oracle`` (core/property.py oracle resolution; SURVEY.md §7
hard-parts #5) — this module packages it as a plain
:class:`~qsm_tpu.ops.backend.LineariseBackend` so the CLI, fuzzer, and
bench tools can run the plan as ONE backend with honest counters.

Verdict contract: bit-identical to running the tail checker alone
(the device's decided verdicts are parity-pinned against the oracle by
the kernel test suite; the tail only ever sees lanes the device did not
decide).  BUDGET_EXCEEDED survives only if the tail itself gives up
(node-budget cap), which the property layer resolves as before.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec
from .backend import Verdict


class HybridDevice:
    """Device majority under a tight budget; host tail for the stragglers.

    ``budget``: per-lane device iteration cap before a lane defers to the
    tail (the round-4 capture's knee sits near the default 2k).
    ``tail``: any LineariseBackend; default = native C++ oracle when
    available, else the memoised Wing–Gong oracle.
    """

    name = "hybrid_device"

    def __init__(self, spec: Spec, budget: int = 2_000,
                 tail=None, **device_kw):
        from .jax_kernel import JaxTPU

        self.spec = spec
        # no mid/rescue ladder: stragglers are the tail's job
        self.device = JaxTPU(spec, budget=budget,
                             mid_budget=0, rescue_budget=0, **device_kw)
        if tail is None:
            tail = _default_tail(spec)
        self.tail = tail
        self.tail_histories = 0   # lanes the host tail decided for us
        self.device_decided = 0

    def check_histories(self, spec: Spec,
                        histories: Sequence[History]) -> np.ndarray:
        out = np.asarray(self.device.check_histories(spec, histories),
                         dtype=np.int8)
        und = np.nonzero(out == int(Verdict.BUDGET_EXCEEDED))[0]
        self.device_decided += len(histories) - und.size
        if und.size:
            tail_v = np.asarray(self.tail.check_histories(
                spec, [histories[i] for i in und]), dtype=np.int8)
            out[und] = tail_v
            self.tail_histories += int(und.size)
        return out

    def check_witness(self, spec: Spec, history: History):
        """Witness from whichever side decided the history (device
        witnesses verify search-free; host oracles produce their own)."""
        v = Verdict(int(self.device.check_histories(spec, [history])[0]))
        if v != Verdict.BUDGET_EXCEEDED:
            return self.device.check_witness(spec, history)
        return self.tail.check_witness(spec, history)

    def search_stats(self):
        """Device lockstep cost AND host tail nodes, side by side — the
        honest composed form (search/stats.py): device iterations saved by
        deferring stragglers are only savings when the tail's node count
        is shown next to them."""
        from ..search.stats import collect_search_stats

        st = self.device.search_stats()
        st.engine = self.name
        st.tail_histories = self.tail_histories
        st.absorb(collect_search_stats(self.tail))
        return st


def _default_tail(spec: Spec):
    from ..native import CppOracle, native_available
    from .wing_gong_cpu import WingGongCPU

    if native_available():
        return CppOracle(spec)
    return WingGongCPU(memo=True)
