"""Linearisability-checker backend protocol.

The north-star threads a ``LineariseBackend`` (default ``WingGongCPU``, new
``JaxTPU``) through the runner and property layer (BASELINE.json:5).  Backends
decide *batches* of histories because the shrink loop produces thousands of
candidates at once (SURVEY.md §3.5).

Verdicts are a tri-state: the device kernel runs under a bounded iteration
budget and reports BUDGET_EXCEEDED instead of guessing; the property layer
resolves those via the CPU oracle so CPU/TPU verdicts stay bit-identical
(SURVEY.md §7 hard-parts #5).
"""

from __future__ import annotations

import enum
from typing import Protocol, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec


class Verdict(enum.IntEnum):
    VIOLATION = 0
    LINEARIZABLE = 1
    BUDGET_EXCEEDED = 2


class LineariseBackend(Protocol):
    name: str

    def check_histories(
        self, spec: Spec, histories: Sequence[History]
    ) -> np.ndarray:
        """Return int8[len(histories)] of :class:`Verdict` values."""
        ...


def check_one(backend: LineariseBackend, spec: Spec, history: History) -> Verdict:
    return Verdict(int(backend.check_histories(spec, [history])[0]))
