"""Linearisability-checker backend protocol.

The north-star threads a ``LineariseBackend`` (default ``WingGongCPU``, new
``JaxTPU``) through the runner and property layer (BASELINE.json:5).  Backends
decide *batches* of histories because the shrink loop produces thousands of
candidates at once (SURVEY.md §3.5).

Verdicts are a tri-state: the device kernel runs under a bounded iteration
budget and reports BUDGET_EXCEEDED instead of guessing; the property layer
resolves those via the CPU oracle so CPU/TPU verdicts stay bit-identical
(SURVEY.md §7 hard-parts #5).
"""

from __future__ import annotations

import enum
from typing import Protocol, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec


class Verdict(enum.IntEnum):
    VIOLATION = 0
    LINEARIZABLE = 1
    BUDGET_EXCEEDED = 2


class BackendUnavailable(RuntimeError):
    """A backend lost its substrate mid-run (device seized by another
    process, tunnel wedged, runtime torn down).  The typed signal the
    resilience plane reacts to: callers degrade to a host fallback
    instead of crashing (resilience/failover.py, core/property.py)."""


def device_error_types() -> tuple:
    """THE definition of "device loss" — every error class that means
    the dispatch substrate failed (as opposed to a bug in the caller's
    arguments, which must keep crashing loudly).  One site, imported by
    the failover combinator, the hybrid backend, and the property layer,
    so what degrades and what crashes can never drift apart.
    """
    from ..resilience.faults import InjectedFault
    from ..resilience.policy import WatchdogTimeout

    # deliberately NOT OSError: a FileNotFoundError from memo
    # persistence (or any caller bug) is not device loss, and silently
    # degrading on it would hide the bug behind a correct-looking
    # host-fallback run — only typed substrate failures degrade
    errs = [BackendUnavailable, WatchdogTimeout, InjectedFault]
    try:  # the XLA runtime's own failure type (absent on stripped jaxlibs)
        import jax

        errs.append(jax.errors.JaxRuntimeError)
    except (ImportError, AttributeError):
        pass
    return tuple(errs)


class LineariseBackend(Protocol):
    name: str

    def check_histories(
        self, spec: Spec, histories: Sequence[History]
    ) -> np.ndarray:
        """Return int8[len(histories)] of :class:`Verdict` values."""
        ...


def check_one(backend: LineariseBackend, spec: Spec, history: History) -> Verdict:
    return Verdict(int(backend.check_histories(spec, [history])[0]))


def verify_witness(spec: Spec, history: History, witness) -> bool:
    """Independently replay a claimed linearization — NO search involved.

    ``witness`` is a list of ``(op_index, resp)`` pairs in linearization
    order (the shape ``check_witness`` returns).  Valid iff: every
    non-pending op appears exactly once (pending ops may appear at most
    once — unlisted means pruned), real-time precedence is respected
    (an op linearizes only after everything that strictly precedes it),
    listed resps match each non-pending op's own response, and every
    step's postcondition holds from the initial state.  This is what
    makes a LINEARIZABLE verdict auditable: the checker's exponential
    search is not trusted, only this linear replay.
    """
    ops = history.ops
    n = len(ops)
    prec = history.precedes_matrix()
    listed = [j for j, _ in witness]
    if len(set(listed)) != len(listed):
        return False  # an op linearized twice
    if not all(0 <= j < n for j in listed):
        return False
    required = {j for j in range(n) if not ops[j].is_pending}
    if required - set(listed):
        return False  # a completed op never linearized
    taken = [False] * n
    state = list(int(v) for v in spec.initial_state())
    for j, resp in witness:
        if ops[j].is_pending:
            if not 0 <= resp < spec.CMDS[ops[j].cmd].n_resps:
                return False  # completion outside the response domain
        elif resp != ops[j].resp:
            return False
        for i in range(n):
            if prec[i, j] and not taken[i]:
                return False  # linearized before a real-time predecessor
        state, ok = spec.step_py(state, ops[j].cmd, ops[j].arg, resp)
        state = list(state)
        if not ok:
            return False
        taken[j] = True
    # unlisted PENDING ops count as pruned — but a pruned op must not
    # strictly precede any listed op (it never took effect, which is
    # only consistent if nothing was required to wait for it; pending
    # ops never precede anything, so this holds by construction)
    return True
