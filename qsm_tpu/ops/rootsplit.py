"""Root splitting — intra-history search parallelism.

The batched device kernel is data-parallel over *histories* (one DFS per
lane); a single pathological history therefore occupies one lane while the
rest of the batch idles — the lockstep tail the chunked driver compacts
around.  Root splitting attacks the tail itself: it decomposes ONE search
into many independent sub-searches and spreads them across lanes, the
search-space analog of tensor parallelism (SURVEY.md §2b names in-kernel
frontier parallelism as exactly this analog).

The decomposition is the first Wing–Gong choice point made explicit: the
set of linearizations of a complete history partitions by which
precedence-minimal operation linearizes FIRST.  For each minimal op ``j``
whose postcondition holds from the current state, the child problem is the
same history minus ``j``, checked from ``step(state, j)`` — precisely the
per-lane ``init_states`` route the kernel already exposes for the
segmentation combinator (ops/jax_kernel.py ``check_histories``).  So:

    linearizable(h, s)  ⇔  ∃ j minimal, ok(s, j):
                               linearizable(h − j, step(s, j))

Splitting to ``depth`` d yields up to ``pids^d`` children (only minimal
ops branch, and only ok steps survive); children arising from different
orders of the same op set are deduplicated by their (remaining-ops,
state) configuration — the root-level analog of the Lowe memo cache.

Aggregation per input history: any child LINEARIZABLE → LINEARIZABLE;
else any child BUDGET_EXCEEDED → BUDGET_EXCEEDED (the undecided child
could have been the succeeding branch); else VIOLATION.  Histories with
pending ops are routed to the inner backend whole (their completion
expansion already multiplies lanes).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import History
from ..core.spec import Spec
from .backend import LineariseBackend, Verdict


def _split_once(spec: Spec, h: History, state: Tuple[int, ...]
                ) -> List[Tuple[History, Tuple[int, ...]]]:
    """All ok (child-history, child-state) pairs one root step down."""
    prec = h.precedes_matrix()
    out = []
    for j in range(len(h.ops)):
        if prec[:, j].any():
            continue  # some op precedes j: j cannot linearize first
        o = h.ops[j]
        nxt, ok = spec.step_py(list(state), o.cmd, o.arg, o.resp)
        if not ok:
            continue  # this first choice dies immediately
        rest = History([p for i, p in enumerate(h.ops) if i != j],
                       seed=h.seed, program_id=h.program_id)
        out.append((rest, tuple(int(v) for v in nxt)))
    return out


def split_history(spec: Spec, h: History, depth: int = 1,
                  init_state=None, max_children: int = 256
                  ) -> Optional[List[Tuple[History, Tuple[int, ...]]]]:
    """Decompose ``h`` into root-split children at the given depth, or
    None when splitting does not apply (pending ops, empty, or the
    frontier would exceed ``max_children``).

    Children are deduplicated by (remaining-op identity set, state):
    depth ≥ 2 reaches the same configuration along every permutation of
    the removed ops, and deciding it once is enough (any-path semantics).
    An EMPTY returned list is meaningful: every first choice failed its
    postcondition, i.e. the history is a proven VIOLATION.
    """
    if len(h.ops) == 0 or h.n_pending or depth < 1:
        return None
    state = tuple(int(v) for v in (spec.initial_state()
                                   if init_state is None else init_state))
    frontier = [(h, state)]
    for _ in range(depth):
        nxt: List[Tuple[History, Tuple[int, ...]]] = []
        seen = set()
        for hist, st in frontier:
            if len(hist.ops) == 0:
                # already fully linearized along this branch: keep as a
                # trivially-LINEARIZABLE child rather than re-splitting
                key = ((), st)
                if key not in seen:
                    seen.add(key)
                    nxt.append((hist, st))
                continue
            for child, cst in _split_once(spec, hist, st):
                key = (tuple((o.pid, o.invoke_time) for o in child.ops),
                       cst)
                if key in seen:
                    continue
                seen.add(key)
                nxt.append((child, cst))
        frontier = nxt
        if len(frontier) > max_children:
            return None  # splitting would flood the batch; caller decides
        if not frontier:
            return []  # every branch died: proven VIOLATION
    return frontier


def _check_with_inits(inner: LineariseBackend, spec: Spec,
                      hists: Sequence[History],
                      inits: Sequence[Tuple[int, ...]]) -> np.ndarray:
    """Batched init-state check on backends that support it (JaxTPU,
    CppOracle); per-history ``check_from`` loop otherwise (oracle).
    Capability by signature inspection (same detection as SegDC) — an
    ``except TypeError`` around the call would swallow genuine TypeErrors
    raised inside a capable inner."""
    import inspect

    sig = inspect.signature(inner.check_histories)
    if "init_states" in sig.parameters:
        return inner.check_histories(spec, hists, init_states=list(inits))
    return np.asarray(
        [int(inner.check_from(spec, h, np.asarray(s, np.int32)))
         for h, s in zip(hists, inits)], np.int8)


class RootSplit:
    """Backend combinator: parallelize the HARD TAIL by root splitting.

    Two modes, chosen by measurement (docs/EXPERIMENTS.md):

    * ``eager=False`` (default, ESCALATION): run the inner backend on the
      whole histories first; only those it returns BUDGET_EXCEEDED for are
      split and re-decided as children.  A parent search had to explore
      all root subtrees *sequentially* within one lane's budget; its
      children each get a full budget for ONE subtree — splitting
      multiplies the effective iteration budget by the fanout exactly
      where the search is pathological, and costs nothing anywhere else.
    * ``eager=True``: split every history of ≥ ``min_ops`` ops up front.
      Measured 31× MORE total lockstep work on the CAS bench corpus
      (children forfeit the shared in-kernel memo cache and multiply the
      padded batch) — kept for experiments, not the default.

    ``depth`` is the number of root levels to expand (fanout ≈ number of
    concurrent pids per level).
    """

    def __init__(self, spec: Spec, inner: LineariseBackend = None,
                 depth: int = 1, min_ops: int = 8,
                 max_children: int = 256, eager: bool = False):
        from .wing_gong_cpu import WingGongCPU

        self.spec = spec
        self.inner = inner if inner is not None else WingGongCPU(memo=True)
        self.depth = depth
        self.min_ops = min_ops
        self.max_children = max_children
        self.eager = eager
        self.name = f"rootsplit({self.inner.name})"
        self.split_histories = 0   # inputs that were actually decomposed
        self.children_checked = 0

    # -- shared: split a set of histories, decide children, aggregate ----
    def _decide_split(self, spec: Spec, idx: List[int],
                      histories: Sequence[History],
                      verdicts: np.ndarray) -> List[int]:
        """Split ``histories[i]`` for i in idx; write aggregated verdicts;
        return the indices that could NOT be split (caller routes them)."""
        unsplit: List[int] = []
        flat: List[History] = []
        flat_inits: List[Tuple[int, ...]] = []
        groups: List[Tuple[int, slice]] = []
        for i in idx:
            h = histories[i]
            kids = (split_history(spec, h, depth=self.depth,
                                  max_children=self.max_children)
                    if len(h.ops) >= self.min_ops else None)
            if kids is None:
                unsplit.append(i)
            elif not kids:
                verdicts[i] = int(Verdict.VIOLATION)  # all roots died
                self.split_histories += 1
            else:
                groups.append(
                    (i, slice(len(flat), len(flat) + len(kids))))
                flat.extend(k for k, _ in kids)
                flat_inits.extend(s for _, s in kids)
                self.split_histories += 1
        if flat:
            sub = _check_with_inits(self.inner, spec, flat, flat_inits)
            self.children_checked += len(flat)
            for i, g in groups:
                v = sub[g]
                if (v == int(Verdict.LINEARIZABLE)).any():
                    verdicts[i] = int(Verdict.LINEARIZABLE)
                elif (v == int(Verdict.BUDGET_EXCEEDED)).any():
                    verdicts[i] = int(Verdict.BUDGET_EXCEEDED)
                else:
                    verdicts[i] = int(Verdict.VIOLATION)
        return unsplit

    def check_histories(self, spec: Spec, histories: Sequence[History]
                        ) -> np.ndarray:
        assert spec is self.spec, "RootSplit is bound to one spec"
        verdicts = np.full(len(histories), int(Verdict.BUDGET_EXCEEDED),
                           np.int8)
        if self.eager:
            unsplit = self._decide_split(
                spec, list(range(len(histories))), histories, verdicts)
            if unsplit:
                sub = self.inner.check_histories(
                    spec, [histories[i] for i in unsplit])
                for k, i in enumerate(unsplit):
                    verdicts[i] = sub[k]
            return verdicts
        # escalation (default): whole pass first, split only the hard tail
        verdicts[:] = self.inner.check_histories(spec, histories)
        hard = [i for i, v in enumerate(verdicts)
                if v == int(Verdict.BUDGET_EXCEEDED)]
        if hard:
            # unsplittable hard histories keep their BUDGET_EXCEEDED —
            # the property layer resolves those via the oracle as usual
            self._decide_split(spec, hard, histories, verdicts)
        return verdicts
