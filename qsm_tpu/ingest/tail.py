"""Log tailing — a growing foreign event log as a live session stream.

``qsm-tpu monitor`` rides this: each appended line of a
jepsen/porcupine-style log becomes one monitor event
(``{"type": "invoke"|"respond", ...}`` — serve/protocol.py session
ops) the moment it lands, so an unmodified system that only writes a
log file is monitored live, flips included.  Only COMPLETE lines are
consumed (a partially-written tail line stays in the buffer until its
newline arrives — the CellJournal torn-tail discipline, applied
forward), and the tailer is bounded: ``follow=False`` drains what is
there and stops, ``follow=True`` polls until ``stop()`` or
``max_idle_s`` of silence.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional

from .adapters import decode_event
from .edn import parse_map_line
from .specmap import IngestError, spec_map_for


class EventTailer:
    """Incremental line→event converter for one (format, model) pair.

    Rides THE shared per-line decode (``adapters.decode_event``) —
    the live-monitor path and the batch ingest path can never
    disagree on the same log — and keeps the per-pid outstanding-op
    table the response mapping needs (a ``:ok`` line names no
    cmd/arg — its invocation does)."""

    def __init__(self, fmt: str, model: str, spec):
        if fmt not in ("jepsen", "porcupine"):
            raise IngestError(f"unknown ingest format {fmt!r}; one of "
                              "['jepsen', 'porcupine']")
        self.keyed_field = "key" if fmt == "porcupine" else None
        self.smap = spec_map_for(model, spec)
        self._open: dict = {}
        self.lines = 0

    def events_for_line(self, line: str) -> list:
        """Monitor events for one log line ([] for blanks/comments/
        nemesis lines/``:info`` — an unknown outcome leaves the op
        pending, which is exactly what NOT sending its response
        does)."""
        line = line.strip()
        if not line or line.startswith(";"):
            return []
        self.lines += 1
        ev = decode_event(parse_map_line(line), self.smap,
                          self.keyed_field, self._open)
        if ev is None or ev[0] == "info":
            return []
        kind, pid, payload = ev
        if kind == "invoke":
            return [{"type": "invoke", "pid": pid, "cmd": payload[0],
                     "arg": payload[1]}]
        return [{"type": "respond", "pid": pid, "resp": payload}]


def tail_file(path: str, *, follow: bool = False, poll_s: float = 0.2,
              max_idle_s: float = 30.0,
              stop: Optional[Callable[[], bool]] = None
              ) -> Iterator[str]:
    """Yield complete lines of a (possibly growing) file.  Bounded by
    contract: non-follow drains once; follow stops on ``stop()`` or
    after ``max_idle_s`` without growth (a dead producer must not hold
    the monitor open forever)."""
    buf = ""
    idle_since = time.monotonic()
    with open(path, "r") as fh:
        while True:
            chunk = fh.read(65536)
            if chunk:
                idle_since = time.monotonic()
                buf += chunk
                while "\n" in buf:
                    line, _, buf = buf.partition("\n")
                    yield line
                continue
            if not follow:
                return
            if stop is not None and stop():
                return
            if time.monotonic() - idle_since >= max_idle_s:
                return
            time.sleep(poll_s)
