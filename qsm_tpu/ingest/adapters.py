"""Format adapters — foreign event logs as first-class History corpora.

Two externally-common layouts (the OmniLink premise, PAPERS.md: traces
of UNMODIFIED systems become checkable without touching the system):

* **jepsen** — Jepsen/Knossos-style EDN maps, one event per line::

      {:process 0, :type :invoke, :f :write, :value 1}
      {:process 1, :type :invoke, :f :read, :value nil}
      {:process 0, :type :ok, :f :write, :value 1}
      {:process 1, :type :ok, :f :read, :value 1}

  Keyed specs (kv) pack ``:value [key payload]``.  ``:fail`` completes
  an op with its failure response (cas), ``:info`` leaves it pending
  forever (unknown outcome — exactly the checker's pending semantics).

* **porcupine** — the same event grammar with an explicit ``:key``
  field (porcupine's kv test-data shape)::

      {:process 0, :type :invoke, :f :get, :key 2, :value nil}
      {:process 0, :type :ok, :f :get, :key 2, :value 1}

Timestamps are LINE ORDER (invoke at its line index, response at its)
— the real-time precedence a line-ordered log actually attests.  The
decoded rows ride ``utils/report.py history_from_rows`` (the ONE
decoder: canonical op order, loud refusal of mis-paired events), so an
ingested trace is indistinguishable from a native corpus to ``check``,
``submit``, ``shrink``, bench and the monitor plane.

``emit_*`` regenerate the canonical text: ``emit(parse(text)) == text``
for canonical files (the golden round-trip pin, tests/test_ingest.py).
Pending ops re-emit their invoke only (an ``:info`` line's position is
not part of the history's identity).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core.history import History
from ..sched.runner import PENDING_T
from .edn import EdnError, parse_lines, render_map_line
from .specmap import IngestError, spec_map_for

_INVOKE_TYPES = (":invoke", ":call")
_OK_TYPES = (":ok", ":return")
_FAIL = ":fail"
_INFO = ":info"


def decode_event(doc: dict, smap, keyed_field: Optional[str],
                 open_ops: Dict[int, Tuple[int, int]]):
    """THE per-line event decode — the batch adapters and the live
    tailer (ingest/tail.py) both ride exactly this, so the two paths
    can never disagree on the same log.  One parsed EDN map →

    * ``("invoke", pid, (cmd, arg))`` — ``open_ops`` gains the pid;
    * ``("ok", pid, resp)`` — ``:ok``/``:return``/``:fail`` complete
      the pid's outstanding op (popped from ``open_ops``);
    * ``("info", pid, None)`` — unknown outcome, op stays pending;
    * ``None`` — a non-op line to skip: an ``:info`` whose ``:process``
      is not an integer (real Jepsen logs carry ``:process :nemesis``
      lifecycle lines; they are not history operations).

    Anything else — non-integer process on a real op, unknown type,
    mis-paired completion, out-of-domain value — raises
    :class:`IngestError`."""
    typ = doc.get("type")
    if isinstance(typ, str) and not typ.startswith(":"):
        typ = ":" + typ
    pid = doc.get("process")
    if not isinstance(pid, int):
        if typ == _INFO:
            return None  # nemesis/system lifecycle line: not an op
        raise IngestError(f":process must be an integer, got {pid!r}")
    f = doc.get("f")
    f = f[1:] if isinstance(f, str) and f.startswith(":") else f
    value = doc.get("value")
    if keyed_field is not None:
        key = doc.get(keyed_field)
    elif smap.keyed:
        # jepsen keyed layout: :value [key payload]
        if not isinstance(value, (list, tuple)) or len(value) != 2:
            raise IngestError(f"keyed spec needs :value "
                              f"[key payload], got {value!r}")
        key, value = value[0], value[1]
    else:
        key = None
    if typ in _INVOKE_TYPES:
        if pid in open_ops:
            raise IngestError(f"process {pid} invokes with an "
                              "outstanding op")
        cmd, arg = smap.invoke_op(f, key, value)
        open_ops[pid] = (cmd, arg)
        return ("invoke", pid, (cmd, arg))
    if typ in _OK_TYPES or typ == _FAIL:
        op = open_ops.pop(pid, None)
        if op is None:
            raise IngestError(f"process {pid} completes with no "
                              "outstanding invocation")
        return ("ok", pid, smap.resp_of(op[0], op[1], value,
                                        typ == _FAIL))
    if typ == _INFO:
        if open_ops.pop(pid, None) is None:
            raise IngestError(f":info for process {pid} with no "
                              "outstanding invocation")
        return ("info", pid, None)
    raise IngestError(f"unknown :type {typ!r}")


def _parse(text: str, smap, keyed_field: Optional[str]) -> List[list]:
    rows: List[list] = []
    open_ops: Dict[int, Tuple[int, int]] = {}   # decode pairing state
    row_of: Dict[int, int] = {}                 # pid -> open row index
    for line_no, doc in parse_lines(text):
        try:
            ev = decode_event(doc, smap, keyed_field, open_ops)
        except IngestError as e:
            raise IngestError(f"line {line_no}: {e}") from None
        if ev is None:
            continue
        kind, pid, payload = ev
        if kind == "invoke":
            row_of[pid] = len(rows)
            rows.append([pid, payload[0], payload[1], -1, line_no,
                         PENDING_T])
        elif kind == "ok":
            i = row_of.pop(pid)
            rows[i][3] = payload
            rows[i][5] = line_no
        else:  # info: unknown outcome — the op stays pending
            row_of.pop(pid, None)
    return rows


def _emit(history: History, smap, keyed_field: Optional[str]) -> str:
    stream = []  # (t, order, pairs)
    for op in history.ops:
        f, key, value = smap.render_invoke(op.cmd, op.arg)
        stream.append((op.invoke_time, 0,
                       _pairs(op.pid, ":invoke", f, key, value,
                              keyed_field, smap)))
        if op.is_pending:
            continue
        f, key, value, failed = smap.render_resp(op.cmd, op.arg, op.resp)
        typ = _FAIL if failed else ":ok"
        stream.append((op.response_time, 1,
                       _pairs(op.pid, typ, f, key, value, keyed_field,
                              smap)))
    stream.sort(key=lambda e: (e[0], e[1]))
    return "".join(render_map_line(p) + "\n" for _, _, p in stream)


def _pairs(pid: int, typ: str, f: str, key, value,
           keyed_field: Optional[str], smap) -> List[tuple]:
    pairs = [("process", pid), ("type", typ), ("f", ":" + f)]
    if keyed_field is not None:
        pairs.append((keyed_field, 0 if key is None else key))
        pairs.append(("value", value))
    elif smap.keyed:
        pairs.append(("value", [key, value]))
    else:
        pairs.append(("value", value))
    return pairs


# ---------------------------------------------------------------------------
# the two public formats
# ---------------------------------------------------------------------------

def parse_jepsen(text: str, model: str, spec) -> List[list]:
    """Jepsen/Knossos EDN lines → canonical history rows."""
    return _parse(text, spec_map_for(model, spec), keyed_field=None)


def emit_jepsen(history: History, model: str, spec) -> str:
    return _emit(history, spec_map_for(model, spec), keyed_field=None)


def parse_porcupine(text: str, model: str, spec) -> List[list]:
    """porcupine-style (explicit ``:key``) EDN lines → history rows."""
    return _parse(text, spec_map_for(model, spec), keyed_field="key")


def emit_porcupine(history: History, model: str, spec) -> str:
    return _emit(history, spec_map_for(model, spec), keyed_field="key")


FORMATS = {
    "jepsen": (parse_jepsen, emit_jepsen),
    "porcupine": (parse_porcupine, emit_porcupine),
}


def parse_trace(fmt: str, text: str, model: str, spec) -> List[list]:
    if fmt not in FORMATS:
        raise IngestError(f"unknown ingest format {fmt!r}; one of "
                          f"{sorted(FORMATS)}")
    return FORMATS[fmt][0](text, model, spec)


def emit_trace(fmt: str, history: History, model: str, spec) -> str:
    if fmt not in FORMATS:
        raise IngestError(f"unknown ingest format {fmt!r}; one of "
                          f"{sorted(FORMATS)}")
    return FORMATS[fmt][1](history, model, spec)
