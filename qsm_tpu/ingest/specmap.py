"""Spec maps — external operation vocabularies ↔ spec (cmd, arg, resp).

A format adapter (ingest/adapters.py) understands a FILE layout
(jepsen's ``:f``/``:value`` maps, porcupine's explicit ``:key`` field);
a spec map understands one MODEL's integer packing (core/spec.py
``CmdSig`` domains).  The split keeps both sides honest: adapters never
guess at arg packing, maps never guess at file syntax.

Each map speaks four verbs over ``(f, key, value)`` triples — ``key``
is the per-key component (None for unkeyed specs) and ``value`` the
payload:

* ``invoke_op(f, key, value) -> (cmd, arg)``
* ``resp_of(cmd, arg, value, failed) -> resp``
* ``render_invoke(cmd, arg) -> (f, key, value)``
* ``render_resp(cmd, arg, resp) -> (f, key, value, failed)``

Out-of-domain values are refused loudly (:class:`IngestError`): a trace
that does not fit the spec's declared domains is a spec-selection
mistake, not something to clamp quietly.
"""

from __future__ import annotations

from typing import Optional, Tuple


class IngestError(ValueError):
    """A trace event the selected spec cannot represent."""


def _int_in(v, bound: int, what: str) -> int:
    if not isinstance(v, int):
        raise IngestError(f"{what} must be an integer, got {v!r}")
    if not 0 <= v < bound:
        raise IngestError(f"{what} {v} outside spec domain [0, {bound})")
    return v


class RegisterMap:
    """``read``/``write`` over one register (models/register.py; the
    cas map extends it with ``cas [old new]``)."""

    READ, WRITE = 0, 1
    keyed = False

    def __init__(self, spec):
        self.spec = spec
        self.n_values = spec.CMDS[self.READ].n_resps

    def invoke_op(self, f: str, key, value) -> Tuple[int, int]:
        if f == "read":
            return self.READ, 0
        if f == "write":
            return self.WRITE, _int_in(value, self.n_values, "write value")
        raise IngestError(f"{self.spec.name}: unknown op :f :{f} "
                          "(read/write)")

    def resp_of(self, cmd: int, arg: int, value, failed: bool) -> int:
        if cmd == self.READ:
            if failed:
                raise IngestError("a read cannot :fail (it has no "
                                  "precondition); use :info for unknown")
            return _int_in(value, self.n_values, "read result")
        return 0

    def render_invoke(self, cmd: int, arg: int):
        if cmd == self.READ:
            return "read", None, None
        return "write", None, arg

    def render_resp(self, cmd: int, arg: int, resp: int):
        if cmd == self.READ:
            return "read", None, resp, False
        return "write", None, arg, False


class CasMap(RegisterMap):
    """register ops plus ``cas [old new]`` (models/cas.py: arg packs
    ``old * n_values + new``; resp 1 = swapped, 0 = precondition
    failed — jepsen's ``:fail`` line)."""

    CAS = 2

    def invoke_op(self, f: str, key, value) -> Tuple[int, int]:
        if f == "cas":
            if (not isinstance(value, (list, tuple)) or len(value) != 2):
                raise IngestError(f"cas value must be [old new], "
                                  f"got {value!r}")
            old = _int_in(value[0], self.n_values, "cas old")
            new = _int_in(value[1], self.n_values, "cas new")
            return self.CAS, old * self.n_values + new
        try:
            return super().invoke_op(f, key, value)
        except IngestError:
            raise IngestError(f"{self.spec.name}: unknown op :f :{f} "
                              "(read/write/cas)") from None

    def resp_of(self, cmd: int, arg: int, value, failed: bool) -> int:
        if cmd == self.CAS:
            return 0 if failed else 1
        return super().resp_of(cmd, arg, value, failed)

    def render_invoke(self, cmd: int, arg: int):
        if cmd == self.CAS:
            return "cas", None, [arg // self.n_values,
                                 arg % self.n_values]
        return super().render_invoke(cmd, arg)

    def render_resp(self, cmd: int, arg: int, resp: int):
        if cmd == self.CAS:
            return ("cas", None,
                    [arg // self.n_values, arg % self.n_values],
                    resp == 0)
        return super().render_resp(cmd, arg, resp)


class KvMap:
    """``get``/``put`` (aliases ``read``/``write``) over a keyed map
    (models/kv.py: put packs ``key * n_values + value``)."""

    GET, PUT = 0, 1
    keyed = True

    def __init__(self, spec):
        self.spec = spec
        self.n_keys = spec.CMDS[self.GET].n_args
        self.n_values = spec.CMDS[self.GET].n_resps

    def invoke_op(self, f: str, key, value) -> Tuple[int, int]:
        k = _int_in(key, self.n_keys, "key")
        if f in ("get", "read"):
            return self.GET, k
        if f in ("put", "write"):
            v = _int_in(value, self.n_values, "put value")
            return self.PUT, k * self.n_values + v
        raise IngestError(f"{self.spec.name}: unknown op :f :{f} "
                          "(get/put)")

    def resp_of(self, cmd: int, arg: int, value, failed: bool) -> int:
        if cmd == self.GET:
            if failed:
                raise IngestError("a get cannot :fail; use :info")
            return _int_in(value, self.n_values, "get result")
        return 0

    def render_invoke(self, cmd: int, arg: int):
        if cmd == self.GET:
            return "get", arg, None
        return "put", arg // self.n_values, arg % self.n_values

    def render_resp(self, cmd: int, arg: int, resp: int):
        if cmd == self.GET:
            return "get", arg, resp, False
        return "put", arg // self.n_values, arg % self.n_values, False


class RangeSetMap:
    """``add``/``remove``/``contains``/``count-below`` over a keyed set
    (models/rangeset.py).  The key is the set element; ``count-below``'s
    key may equal ``n_keys`` (count the whole set).  ``add``/``remove``
    ride jepsen's ``:fail`` convention (resp 0 = no-op), queries carry
    their result in ``:value``."""

    ADD, REMOVE, CONTAINS, COUNT_BELOW = 0, 1, 2, 3
    keyed = True

    def __init__(self, spec):
        self.spec = spec
        self.n_keys = spec.CMDS[self.ADD].n_args

    def invoke_op(self, f: str, key, value) -> Tuple[int, int]:
        if f in ("count-below", "count_below"):
            return self.COUNT_BELOW, _int_in(key, self.n_keys + 1,
                                             "count-below bound")
        k = _int_in(key, self.n_keys, "key")
        if f == "add":
            return self.ADD, k
        if f == "remove":
            return self.REMOVE, k
        if f == "contains":
            return self.CONTAINS, k
        raise IngestError(f"{self.spec.name}: unknown op :f :{f} "
                          "(add/remove/contains/count-below)")

    def resp_of(self, cmd: int, arg: int, value, failed: bool) -> int:
        if cmd in (self.ADD, self.REMOVE):
            return 0 if failed else 1
        if failed:
            raise IngestError("a query cannot :fail (no precondition); "
                              "use :info for unknown")
        if cmd == self.CONTAINS:
            return _int_in(value, 2, "contains result")
        return _int_in(value, self.n_keys + 1, "count-below result")

    def render_invoke(self, cmd: int, arg: int):
        f = ("add", "remove", "contains", "count-below")[cmd]
        return f, arg, None

    def render_resp(self, cmd: int, arg: int, resp: int):
        if cmd in (self.ADD, self.REMOVE):
            return (("add", "remove")[cmd], arg, None, resp == 0)
        f = "contains" if cmd == self.CONTAINS else "count-below"
        return f, arg, resp, False


class SemaphoreMap:
    """``acquire``/``release``/``available`` (models/lock.py).  Unkeyed
    and argless; acquire/release ride ``:fail`` for the refused case
    (resp 0), ``available`` carries its count in ``:value``."""

    ACQUIRE, RELEASE, AVAILABLE = 0, 1, 2
    keyed = False

    def __init__(self, spec):
        self.spec = spec
        self.permits = spec.CMDS[self.AVAILABLE].n_resps - 1

    def invoke_op(self, f: str, key, value) -> Tuple[int, int]:
        if f == "acquire":
            return self.ACQUIRE, 0
        if f == "release":
            return self.RELEASE, 0
        if f == "available":
            return self.AVAILABLE, 0
        raise IngestError(f"{self.spec.name}: unknown op :f :{f} "
                          "(acquire/release/available)")

    def resp_of(self, cmd: int, arg: int, value, failed: bool) -> int:
        if cmd == self.AVAILABLE:
            if failed:
                raise IngestError("available cannot :fail; use :info")
            return _int_in(value, self.permits + 1, "available count")
        return 0 if failed else 1

    def render_invoke(self, cmd: int, arg: int):
        return ("acquire", "release", "available")[cmd], None, None

    def render_resp(self, cmd: int, arg: int, resp: int):
        if cmd == self.AVAILABLE:
            return "available", None, resp, False
        return (("acquire", "release")[cmd], None, None, resp == 0)


class TxnMap:
    """``read``/``write``/``copy`` over keyed cells (models/txn.py).
    ``read cell``/``write cell v`` are the multi-register shape; ``copy``
    keys by its SOURCE cell and carries the destination in ``:value`` —
    mirroring the spec's (deliberately unsound) src-keyed projection, so
    an ingested trace round-trips through the same packing the refusal
    pins exercise."""

    READ, WRITE, COPY = 0, 1, 2
    keyed = True

    def __init__(self, spec):
        self.spec = spec
        self.n_cells = spec.n_cells
        self.n_values = spec.n_values

    def invoke_op(self, f: str, key, value) -> Tuple[int, int]:
        cell = _int_in(key, self.n_cells, "cell")
        if f == "read":
            return self.READ, cell
        if f == "write":
            v = _int_in(value, self.n_values, "write value")
            return self.WRITE, self.spec.write_arg(cell, v)
        if f == "copy":
            dst = _int_in(value, self.n_cells, "copy dst")
            if dst == cell:
                raise IngestError(f"copy src and dst must differ, "
                                  f"both {cell}")
            return self.COPY, self.spec.copy_arg(cell, dst)
        raise IngestError(f"{self.spec.name}: unknown op :f :{f} "
                          "(read/write/copy)")

    def resp_of(self, cmd: int, arg: int, value, failed: bool) -> int:
        if cmd == self.READ:
            if failed:
                raise IngestError("a read cannot :fail; use :info")
            return _int_in(value, self.n_values, "read result")
        return 0

    def render_invoke(self, cmd: int, arg: int):
        if cmd == self.READ:
            return "read", arg, None
        if cmd == self.WRITE:
            return "write", arg // self.n_values, arg % self.n_values
        src, dst = self.spec.copy_pair(arg)
        return "copy", src, dst

    def render_resp(self, cmd: int, arg: int, resp: int):
        if cmd == self.READ:
            return "read", arg, resp, False
        if cmd == self.WRITE:
            return ("write", arg // self.n_values,
                    arg % self.n_values, False)
        src, dst = self.spec.copy_pair(arg)
        return "copy", src, dst, False


# model name -> map factory; multireg/multicas reuse the kv shape?  No:
# their alphabets differ — only the externally-common vocabularies are
# mapped.  Unmapped models are refused with this table in the error.
SPEC_MAPS = {
    "register": RegisterMap,
    "cas": CasMap,
    "kv": KvMap,
    "rangeset": RangeSetMap,
    "semaphore": SemaphoreMap,
    "txn": TxnMap,
}


def spec_map_for(model: str, spec):
    factory = SPEC_MAPS.get(model)
    if factory is None:
        raise IngestError(
            f"no ingest spec map for model {model!r}; one of "
            f"{sorted(SPEC_MAPS)}")
    return factory(spec)
