"""Minimal EDN-map-line tokenizer/renderer for the ingest adapters.

Jepsen/Knossos histories and porcupine's test corpora are streams of
one-flat-EDN-map-per-line events (``{:process 0, :type :invoke,
:f :write, :value 1}``).  This module parses exactly that subset —
keywords, integers, nil, strings, and flat vectors of those — and
renders it back CANONICALLY (one space after commas, no trailing
separators, keys in the order the adapter specifies), so
``emit(parse(text)) == text`` for canonical files: the byte-stable
round-trip the golden-log tests pin.  It is deliberately NOT a general
EDN reader; anything outside the subset is refused loudly with the
line number (an ingest adapter must never guess at a trace).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

Value = Union[int, str, None, List["Value"]]


class EdnError(ValueError):
    """Unparsable event line — refused with position context."""


class _Cursor:
    __slots__ = ("s", "i")

    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def skip_ws(self) -> None:
        while self.i < len(self.s) and self.s[self.i] in " \t,":
            self.i += 1

    def peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def take(self) -> str:
        c = self.peek()
        self.i += 1
        return c


def _parse_value(c: _Cursor) -> Value:
    c.skip_ws()
    ch = c.peek()
    if ch == ":":
        c.take()
        start = c.i
        while c.peek() and c.peek() not in " \t,{}[]":
            c.take()
        return ":" + c.s[start:c.i]
    if ch == "[":
        c.take()
        out: List[Value] = []
        while True:
            c.skip_ws()
            if c.peek() == "]":
                c.take()
                return out
            if not c.peek():
                raise EdnError("unterminated vector")
            out.append(_parse_value(c))
    if ch == '"':
        c.take()
        start = c.i
        while c.peek() and c.peek() != '"':
            c.take()
        if c.peek() != '"':
            raise EdnError("unterminated string")
        s = c.s[start:c.i]
        c.take()
        return s
    start = c.i
    while c.peek() and c.peek() not in " \t,{}[]":
        c.take()
    tok = c.s[start:c.i]
    if not tok:
        raise EdnError(f"empty token at column {c.i}")
    if tok == "nil":
        return None
    try:
        return int(tok)
    except ValueError:
        raise EdnError(f"unsupported token {tok!r} (int/nil/:kw/"
                       "[...]/\"str\" only)") from None


def parse_map_line(line: str) -> dict:
    """One flat EDN map line → ``{keyword-without-colon: value}``."""
    c = _Cursor(line.strip())
    if c.take() != "{":
        raise EdnError("event line must be one EDN map ({...})")
    out: dict = {}
    while True:
        c.skip_ws()
        if c.peek() == "}":
            c.take()
            c.skip_ws()
            if c.peek():
                raise EdnError(f"trailing content {c.s[c.i:]!r}")
            return out
        if not c.peek():
            raise EdnError("unterminated map")
        k = _parse_value(c)
        if not isinstance(k, str) or not k.startswith(":"):
            raise EdnError(f"map key must be a keyword, got {k!r}")
        out[k[1:]] = _parse_value(c)


def render_value(v: Value) -> str:
    if v is None:
        return "nil"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, str):
        return v if v.startswith(":") else f'"{v}"'
    return "[" + " ".join(render_value(x) for x in v) + "]"


def render_map_line(pairs: List[Tuple[str, Value]]) -> str:
    """``[(key, value), ...]`` → the canonical one-line map (key order
    preserved — the adapter owns it, so emits are deterministic)."""
    inner = ", ".join(f":{k} {render_value(v)}" for k, v in pairs)
    return "{" + inner + "}"


def parse_lines(text: str):
    """Yield ``(line_no, doc)`` for each nonempty line; EdnError gains
    the line number."""
    for i, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith(";"):
            continue
        try:
            yield i, parse_map_line(line)
        except EdnError as e:
            raise EdnError(f"line {i}: {e}") from None
