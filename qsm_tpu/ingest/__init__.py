"""qsm_tpu.ingest — foreign trace formats as first-class corpora.

Jepsen/Knossos- and porcupine-style event logs decode into the repo's
ONE history encoding (utils/report.py rows) and flow into ``check``,
``submit``, ``shrink``, bench and the monitor plane unchanged — the
OmniLink premise (PAPERS.md): validating UNMODIFIED systems' traces is
what makes a checker a production tool.  ``adapters.py`` owns the file
layouts (byte-stable round trips), ``specmap.py`` the per-model integer
packing, ``tail.py`` the live log→session stream (``qsm-tpu monitor``).
"""

from .adapters import (FORMATS, emit_trace, parse_trace)
from .edn import EdnError
from .specmap import SPEC_MAPS, IngestError, spec_map_for
from .tail import EventTailer, tail_file

__all__ = [
    "FORMATS", "SPEC_MAPS", "parse_trace", "emit_trace",
    "spec_map_for", "IngestError", "EdnError", "EventTailer",
    "tail_file",
]
