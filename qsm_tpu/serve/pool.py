"""Supervised worker pool — checking outscales one core before one machine.

BENCH_SERVE_r07 is the wall this module removes: one process checked
every micro-batch, so served throughput *degraded* from 121.9 to
79.1 h/s going 4 → 8 clients, and a single wedged engine wedged the
whole service.  The pool keeps the server's single admission → batcher
→ cache plane and fans dispatches out to N ``serve/worker.py``
processes, treating a worker exactly the way ``resilience/failover.py``
treats a chip:

* **Shed, don't wait.**  Every dispatch is bounded by the
  ``worker-dispatch`` :data:`~qsm_tpu.resilience.policy.PRESETS` entry;
  a worker that misses the bound is presumed wedged, SIGKILLed, and
  its batch — undecided lanes only, nothing was banked — re-dispatches
  to a healthy worker, or (last resort) the caller's own in-process
  host cpp→memo ladder.  A crashed worker (pipe EOF) sheds the same
  way, just faster.
* **Respawn with bounded backoff.**  The supervisor thread respawns
  dead slots on an exponential backoff schedule with a lifetime
  attempt bound per slot — a dying worker costs a bounded number of
  spawns, never a crash loop (the QSM-POOL-RESPAWN lint pass gates the
  code-level twin of this rule).
* **Quarantine a killer spec.**  A spec whose dispatches have now
  crashed ``quarantine_after`` workers is poison, not unlucky: it is
  quarantined to the in-process ladder (``is_quarantined`` — the
  server stops routing it here) so one adversarial input class cannot
  grind the pool through its respawn budget.
* **Soft per-spec affinity.**  A spec prefers the worker at
  ``hash(spec_key) % n`` so its compile caches and memo tables stay
  warm in one process — but an idle worker always beats a busy
  preferred one, so a single hot spec still spreads across the pool
  (the bench's whole scaling story).
* **Workers stay bank-free.**  Verdicts return to the caller, which
  banks them through the cache's one ``put_many`` path; nothing a
  SIGKILL interrupts can tear the bank.

Every counter a capacity decision needs (per-worker dispatches,
faults, respawns, quarantines, per-batch ``worker_faults``) rides
:meth:`WorkerPool.snapshot` into ``stats()`` and the bench rows.
"""

from __future__ import annotations

import itertools
import json
import os
import select
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Set

from ..resilience.policy import RetryPolicy, preset
from .frames import HDR, MAX_FRAME_BYTES, encode_frame


class WorkerFault(RuntimeError):
    """A dispatch lost to a worker (crash, wedge, or protocol skew);
    the lanes are undecided and the caller re-dispatches them."""


class WorkerDead(WorkerFault):
    """Pipe EOF / broken pipe / exited process: the worker crashed."""


class WorkerTimeout(WorkerFault):
    """The worker missed its dispatch/heartbeat bound: presumed wedged
    (SIGKILLed by the shed path — abandonment is not enough, a wedged
    process still holds memory and a core)."""


class WorkerBusy(RuntimeError):
    """The per-worker serialization lock could not be acquired inside
    the bound: the worker is WORKING (on someone else's batch), not
    wedged — callers try another worker and must NOT shed this one (a
    shed here would cascade: killing a busy worker also kills the
    healthy dispatch it was serving)."""


# ---------------------------------------------------------------------------
# bounded pipe I/O (supervisor side) — every read and write carries a
# deadline, the LineChannel discipline applied to worker pipes
# ---------------------------------------------------------------------------

_POLL_S = 0.25


def _read_exact_bounded(fd: int, n: int, deadline: float,
                        label: str) -> bytes:
    buf = b""
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise WorkerTimeout(f"{label}: read deadline exceeded")
        r, _, _ = select.select([fd], [], [], min(_POLL_S, remaining))
        if not r:
            continue
        try:
            chunk = os.read(fd, n - len(buf))
        except (BlockingIOError, InterruptedError):
            continue
        except OSError as e:
            raise WorkerDead(f"{label}: {type(e).__name__}: {e}") from None
        if not chunk:
            raise WorkerDead(f"{label}: pipe EOF (worker exited)")
        buf += chunk
    return buf


def _write_bounded(fd: int, data: bytes, deadline: float,
                   label: str) -> None:
    view = memoryview(data)
    while view:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise WorkerTimeout(f"{label}: write deadline exceeded")
        _, w, _ = select.select([], [fd], [], min(_POLL_S, remaining))
        if not w:
            continue
        try:
            n = os.write(fd, view[:65536])
        except (BlockingIOError, InterruptedError):
            continue
        except (BrokenPipeError, OSError) as e:
            raise WorkerDead(f"{label}: {type(e).__name__}: {e}") from None
        view = view[n:]


class WorkerHandle:
    """One live worker process: pipes, serialization lock, counters.
    ``request`` is the only I/O entry — bounded both ways, serialized
    per worker (a worker is single-threaded by design)."""

    def __init__(self, wid: int, proc: subprocess.Popen):
        self.wid = wid
        self.proc = proc
        self._stdin_fd = proc.stdin.fileno()
        self._stdout_fd = proc.stdout.fileno()
        # non-blocking + select: a wedged worker that stopped draining
        # its pipe must never block the supervisor past the deadline
        os.set_blocking(self._stdin_fd, False)
        os.set_blocking(self._stdout_fd, False)
        self.lock = threading.Lock()
        self.busy = False          # a dispatch holds the lock right now
        self.dead = False          # shed: never dispatched again
        self._seq = itertools.count(1)
        self.started = time.monotonic()
        self.last_ok = self.started
        self.dispatches = 0
        self.faults = 0
        self.specs: Set[str] = set()

    def request(self, doc: dict, timeout_s: float) -> dict:
        """One bounded round-trip.  Raises :class:`WorkerFault`; the
        caller sheds this worker on any raise."""
        frame = {**doc, "seq": next(self._seq)}
        payload = encode_frame(frame)
        deadline = time.monotonic() + max(0.1, float(timeout_s))
        label = f"worker{self.wid}.{doc.get('op', '?')}"
        # the lock wait is bounded SEPARATELY from the I/O deadline:
        # waiting behind another batch means the worker is busy, not
        # wedged — timing out here must raise Busy (try elsewhere),
        # never a shed-worthy fault
        if not self.lock.acquire(
                timeout=max(0.05, deadline - time.monotonic())):
            raise WorkerBusy(f"{label}: worker mid-dispatch")
        try:
            if self.dead:
                raise WorkerDead(f"{label}: worker already shed")
            self.busy = True
            # the I/O clock starts NOW: time spent queueing behind
            # another batch was the lock's budget, not this round-trip's
            deadline = time.monotonic() + max(0.1, float(timeout_s))
            try:
                _write_bounded(self._stdin_fd, payload, deadline, label)
                while True:
                    hdr = _read_exact_bounded(self._stdout_fd, HDR.size,
                                              deadline, label)
                    (n,) = HDR.unpack(hdr)
                    if n > MAX_FRAME_BYTES:
                        raise WorkerDead(
                            f"{label}: insane frame length {n} "
                            "(protocol skew)")
                    body = _read_exact_bounded(self._stdout_fd, n,
                                               deadline, label)
                    try:
                        resp = json.loads(body)
                    except ValueError:
                        raise WorkerDead(
                            f"{label}: undecodable frame") from None
                    if resp.get("seq") == frame["seq"]:
                        self.last_ok = time.monotonic()
                        return resp
                    # a stale frame from an earlier abandoned request
                    # (should be impossible — timeouts shed the worker —
                    # but dropping it beats desyncing the stream)
            finally:
                self.busy = False
        finally:
            self.lock.release()


class _Slot:
    """One pool position: the live handle (or None while dead) plus the
    respawn backoff state that makes restarts bounded."""

    def __init__(self, index: int, backoff_s: float):
        self.index = index
        self.handle: Optional[WorkerHandle] = None
        self.base_backoff_s = backoff_s
        self.backoff_s = backoff_s
        self.next_respawn_at = 0.0
        self.respawns = 0          # lifetime spawn count beyond the first
        self.deaths = 0


class WorkerPool:
    """See module docstring.  Thread-safe: the batcher's dispatcher
    threads call :meth:`dispatch` concurrently; one supervisor thread
    owns heartbeats and respawns."""

    # a worker that survives this long has its slot backoff forgiven —
    # deaths separated by healthy service are unlucky, not a loop
    HEALTHY_RESET_S = 30.0

    def __init__(self, n_workers: int, *,
                 policy: Optional[RetryPolicy] = None,
                 quarantine_after: int = 2,
                 heartbeat_s: float = 5.0,
                 heartbeat_timeout_s: float = 5.0,
                 spawn_timeout_s: float = 60.0,
                 max_respawns: int = 8,
                 respawn_backoff_s: float = 0.5,
                 max_backoff_s: float = 30.0,
                 obs=None):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.n_workers = n_workers
        self.policy = policy or preset("worker-dispatch")
        self.quarantine_after = max(1, quarantine_after)
        self.heartbeat_s = heartbeat_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.spawn_timeout_s = spawn_timeout_s
        self.max_respawns = max_respawns
        self.max_backoff_s = max_backoff_s
        self._slots = [_Slot(i, respawn_backoff_s)
                       for i in range(n_workers)]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self.spec_crashes: Dict[str, int] = {}
        self.quarantined: Set[str] = set()
        self.dispatches = 0
        self.worker_faults = 0     # sheds + error answers, dispatch level
        self.respawns = 0
        self.quarantines = 0
        # trace plane (qsm_tpu/obs): span events for dispatch/shed/
        # respawn/quarantine feed the flight-recorder ring (a SIGKILLed
        # worker's dump shows the doomed dispatch's trace ids), and the
        # per-worker round-trip latency histogram feeds live metrics.
        # All emission guarded by obs.on — the no-obs path pays one
        # attribute read per dispatch.
        self._obs = obs
        self._m_dispatch = (obs.metrics.histogram(
            "qsm_pool_dispatch_seconds",
            "per-worker micro-batch dispatch round-trip seconds")
            if obs is not None else None)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "WorkerPool":
        for slot in self._slots:
            self._spawn(slot)
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True, name="qsm-pool-supervise")
        self._supervisor.start()
        return self

    def stop(self) -> None:
        """Deterministic teardown: polite exit frame → terminate →
        bounded wait → kill escalation → bounded reap.  Tier-1 tests
        must never leak a worker process."""
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(2.0)
        for slot in self._slots:
            with self._lock:
                handle, slot.handle = slot.handle, None
            if handle is None:
                continue
            # polite exit FIRST (request() refuses dead handles, and
            # _stop already gates new dispatches), THEN mark dead and
            # escalate — the exit frame lets the worker flush and leave
            # on its own before SIGTERM/SIGKILL ever fire
            if not handle.busy:
                try:
                    handle.request({"op": "exit"}, timeout_s=0.5)
                except (WorkerBusy, WorkerFault):
                    pass
            with self._lock:  # dead is checked/set under the pool lock
                handle.dead = True
            proc = handle.proc
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=1.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
            self._close_pipes(proc)

    @staticmethod
    def _close_pipes(proc: subprocess.Popen) -> None:
        for stream in (proc.stdin, proc.stdout):
            try:
                if stream is not None:
                    stream.close()
            except OSError:
                pass

    # -- spawn / shed / supervise --------------------------------------
    def _spawn(self, slot: _Slot) -> bool:
        import qsm_tpu

        root = os.path.dirname(os.path.dirname(
            os.path.abspath(qsm_tpu.__file__)))
        env = dict(os.environ)
        # workers run the host ladder only; never let one initialize a
        # device backend (the supervisor owns any device plane) — an
        # unconditional pin, so an inherited JAX_PLATFORMS=tpu can
        # never leak N workers onto the supervisor's chip
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "qsm_tpu.serve.worker",
                 "--wid", str(slot.index)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        except OSError:
            # slot backoff state is shared with the dispatch threads'
            # _shed path — same guard discipline (QSM-RACE-UNGUARDED)
            with self._lock:
                slot.deaths += 1
                slot.next_respawn_at = time.monotonic() + slot.backoff_s
                slot.backoff_s = min(slot.backoff_s * 2,
                                     self.max_backoff_s)
            return False
        with self._lock:
            slot.handle = WorkerHandle(slot.index, proc)
        return True

    def _emit(self, name: str, trace: str = "", **attrs) -> None:
        """One obs event (no-op without an obs handle or with tracing
        off — a single attribute read either way)."""
        if self._obs is None or not self._obs.on:
            return
        self._obs.event(name, trace=trace, **attrs)

    def _shed(self, handle: WorkerHandle, spec_key: Optional[str],
              err: BaseException,
              traces: Optional[List[str]] = None) -> None:
        """A worker is lost (crash or wedge): kill it like a wedged
        chip, count it, schedule the bounded respawn, and quarantine
        the spec when it has now killed ``quarantine_after`` workers."""
        slot = self._slots[handle.wid]
        with self._lock:
            if handle.dead:
                return  # a concurrent path shed it first
            handle.dead = True
            handle.faults += 1
            self.worker_faults += 1
            slot.deaths += 1
            slot.handle = None
            now = time.monotonic()
            slot.next_respawn_at = now + slot.backoff_s
            slot.backoff_s = min(slot.backoff_s * 2, self.max_backoff_s)
            quarantined_now = False
            n_crash = 0
            if spec_key is not None:
                n_crash = self.spec_crashes.get(spec_key, 0) + 1
                self.spec_crashes[spec_key] = n_crash
                if (n_crash >= self.quarantine_after
                        and spec_key not in self.quarantined):
                    self.quarantined.add(spec_key)
                    self.quarantines += 1
                    quarantined_now = True
        # worker.shed / pool.quarantine are flight-recorder DUMP
        # triggers (qsm_tpu/obs): the dump's last events include the
        # doomed dispatch's trace ids (worker.dispatch rode the ring)
        self._emit("worker.shed", wid=handle.wid, spec=spec_key,
                   error=f"{type(err).__name__}: {err}"[:200],
                   traces=traces or [])
        if quarantined_now:
            self._emit("pool.quarantine", spec=spec_key, crashes=n_crash)
        proc = handle.proc
        try:
            # SIGKILL, not terminate: a wedged dispatch does not honor
            # signals it can catch, and a crashed one no longer cares
            proc.kill()
            proc.wait(timeout=5.0)
        except (OSError, subprocess.TimeoutExpired):
            pass
        self._close_pipes(proc)

    def _supervise(self) -> None:
        """Heartbeat + respawn loop (NOT a while-True spawn loop: every
        respawn waits out its slot's backoff and the per-slot lifetime
        bound — the discipline QSM-POOL-RESPAWN gates)."""
        while not self._stop.wait(0.25):
            now = time.monotonic()
            for slot in self._slots:
                with self._lock:
                    handle = slot.handle
                if handle is None:
                    if (slot.respawns < self.max_respawns
                            and now >= slot.next_respawn_at
                            and slot.next_respawn_at > 0.0):
                        with self._lock:
                            slot.respawns += 1
                            self.respawns += 1
                        self._emit("worker.respawn", wid=slot.index,
                                   respawns=slot.respawns)
                        self._spawn(slot)
                    continue
                if (now - handle.started >= self.HEALTHY_RESET_S
                        and slot.backoff_s != slot.base_backoff_s):
                    # backoff is also written by _shed under the lock
                    with self._lock:
                        slot.backoff_s = slot.base_backoff_s
                if handle.busy or handle.dead:
                    continue  # dispatch deadline covers busy workers
                if now - handle.last_ok < self.heartbeat_s:
                    continue
                try:
                    handle.request({"op": "ping"},
                                   timeout_s=self.heartbeat_timeout_s)
                except WorkerBusy:
                    continue  # a dispatch won the lock race: healthy
                except WorkerFault as e:
                    self._shed(handle, None, e)

    # -- dispatch ------------------------------------------------------
    def is_quarantined(self, spec_key: str) -> bool:
        return spec_key in self.quarantined

    def idle_workers(self) -> int:
        """Live, not-mid-dispatch workers (the batcher's flush-target
        signal)."""
        with self._lock:
            return sum(1 for s in self._slots
                       if s.handle is not None
                       and not s.handle.dead and not s.handle.busy)

    def live_workers(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots
                       if s.handle is not None and not s.handle.dead)

    def _pick(self, spec_key: str, tried: Set[int]
              ) -> Optional[WorkerHandle]:
        """Soft affinity: walk the ring from ``hash(spec_key) % n``,
        preferring an idle worker (warm caches win ties, a hot spec
        still spreads); any live untried worker beats none."""
        preferred = hash(spec_key) % self.n_workers
        order = [(preferred + i) % self.n_workers
                 for i in range(self.n_workers)]
        fallback = None
        with self._lock:
            for i in order:
                h = self._slots[i].handle
                if h is None or h.dead or h.wid in tried:
                    continue
                if not h.busy:
                    return h
                if fallback is None:
                    fallback = h
        return fallback

    def dispatch(self, spec_key: str, model: str, spec_kwargs: dict,
                 rows: List[list], width: int,
                 traces: Optional[List[str]] = None) -> Optional[dict]:
        """Decide one micro-batch on the pool.  Returns the worker's
        response (verdicts + per-batch search/resilience stamps, plus
        ``batch_worker_faults`` — how many workers this batch burned),
        or None when the pool cannot decide it (quarantined spec, no
        healthy worker, ladder exhausted): the caller falls back to its
        own in-process host ladder.  Lanes are all-or-nothing per
        attempt — a lost worker banked nothing, so the whole batch is
        the undecided remainder.  ``traces`` (the batch's request trace
        ids, qsm_tpu/obs) ride the worker frame's optional ``trace``
        field and every dispatch/shed event."""
        if self.is_quarantined(spec_key):
            return None
        doc = {"op": "check", "model": model, "spec_kwargs": spec_kwargs,
               "rows": rows, "width": width}
        if traces:
            doc["trace"] = traces
        deadline = (time.monotonic() + self.policy.deadline_s
                    if self.policy.deadline_s else None)
        tried: Set[int] = set()
        faults = 0
        for _attempt in range(max(1, self.policy.attempts)):
            if self._stop.is_set():
                return None
            timeout_s = self.policy.timeout_s or 30.0
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None  # ladder deadline: in-process last resort
                timeout_s = min(timeout_s, remaining)
            handle = self._pick(spec_key, tried)
            if handle is None:
                return None
            tried.add(handle.wid)
            handle.specs.add(spec_key)
            self._emit("worker.dispatch", wid=handle.wid, spec=model,
                       lanes=len(rows), traces=traces or [])
            t0 = time.monotonic()
            try:
                resp = handle.request(doc, timeout_s)
            except WorkerBusy:
                continue  # working, not wedged: never shed, try the next
            except WorkerFault as e:
                faults += 1
                self._shed(handle, spec_key, e, traces=traces)
                continue
            if resp.get("ok"):
                if self._m_dispatch is not None:
                    # bounded label values by construction: wid < n
                    self._m_dispatch.observe(time.monotonic() - t0,
                                             wid=str(handle.wid))
                with self._lock:
                    self.dispatches += 1
                handle.dispatches = int(resp.get("dispatches",
                                                 handle.dispatches + 1))
                resp["batch_worker_faults"] = faults
                return resp
            # a clean error answer: the worker is alive (it answered)
            # but this dispatch failed there (raise:worker, bad spec);
            # count the fault and try a different worker — deterministic
            # failures exhaust the ladder into the in-process path
            faults += 1
            with self._lock:
                self.worker_faults += 1
        return None

    def warm(self, model: str, spec_kwargs: Optional[dict] = None) -> int:
        """Build the spec's engine in every live worker (the server's
        ``--warm`` amortization, pool edition).  Returns workers warmed."""
        doc = {"op": "warm", "model": model,
               "spec_kwargs": spec_kwargs or {}}
        warmed = 0
        for slot in self._slots:
            with self._lock:
                handle = slot.handle
            if handle is None or handle.dead:
                continue
            try:
                # generous bound: the FIRST warm may compile the native
                # oracle (cached on disk for every later worker)
                resp = handle.request(doc, timeout_s=self.spawn_timeout_s)
                warmed += int(bool(resp.get("ok")))
            except WorkerBusy:
                continue  # it is mid-dispatch: warm enough
            except WorkerFault as e:
                self._shed(handle, None, e)
        return warmed

    # -- observability -------------------------------------------------
    def shed_state(self) -> dict:
        """The compact pool block SHED responses carry: enough for a
        client to tell 'overloaded' from 'degraded to one worker'."""
        with self._lock:
            live = sum(1 for s in self._slots
                       if s.handle is not None and not s.handle.dead)
            return {"workers": self.n_workers, "live": live,
                    "quarantined": len(self.quarantined)}

    def snapshot(self) -> dict:
        with self._lock:
            workers = []
            for slot in self._slots:
                h = slot.handle
                workers.append({
                    "wid": slot.index,
                    "alive": h is not None and not h.dead,
                    "pid": h.proc.pid if h is not None else None,
                    "dispatches": h.dispatches if h is not None else 0,
                    "faults": h.faults if h is not None else 0,
                    "deaths": slot.deaths,
                    "respawns": slot.respawns,
                    "uptime_s": round(time.monotonic() - h.started, 1)
                    if h is not None else 0.0,
                    "specs": sorted(h.specs) if h is not None else [],
                })
            return {
                "n_workers": self.n_workers,
                "live": sum(1 for w in workers if w["alive"]),
                "dispatches": self.dispatches,
                "worker_faults": self.worker_faults,
                "respawns": self.respawns,
                "quarantines": self.quarantines,
                "quarantined_specs": sorted(self.quarantined),
                "spec_crashes": dict(self.spec_crashes),
                "policy": self.policy.name,
                "workers": workers,
            }
