"""Cross-request adaptive micro-batching — N clients, one dispatch.

The whole device story of this repo is "decide many histories in ONE
backend call" (BASELINE.json:9); per-request dispatch throws that away
the moment checking becomes a service.  The batcher coalesces history
lanes arriving from concurrent connections into one padded batch per
(spec, flush window), exactly the compile-bucket discipline
``core/property.py`` uses for trial groups: the batch is padded to a
FIXED lane width with empty (instantly-SUCCESS) histories so every
dispatch hits the same compiled executable, and ops are padded to the
shared ``OP_BUCKETS`` inside the engine as always.

Flush policy (first match wins, per spec group):

* ``full``     — the group reached ``max_lanes``: dispatch now;
* ``deadline`` — the earliest request deadline in the group is within
  one flush window: dispatch early rather than shed late;
* ``interval`` — the oldest lane has waited ``flush_s``: latency floor
  for lonely clients;
* ``close``    — server shutdown drains every group.

Every batch carries a ``why`` provenance stamp (batch id, lane count,
width, occupancy, flush reason) that rides the responses of every
request in the batch and aggregates into ``qsm-tpu stats`` — the same
self-describing-artifact discipline as the planner's ``why`` and the
resilience counters.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.history import History


@dataclasses.dataclass
class Lane:
    """One history awaiting a verdict (the unit the batcher coalesces)."""

    key: str                 # verdict-cache fingerprint key
    history: History
    deadline: float          # absolute monotonic deadline of its request
    resolve: Callable        # resolve(verdict:int, batch_stamp:dict)


class _Group:
    __slots__ = ("lanes", "first_ts")

    def __init__(self):
        self.lanes: List[Lane] = []
        self.first_ts = time.monotonic()


class MicroBatcher:
    """Coalesce lanes per spec group; dispatch on a single worker thread
    (which also serializes engine access — engines are not required to
    be thread-safe)."""

    def __init__(self, dispatch: Callable[[str, List[Lane], dict], None],
                 max_lanes: int = 64, flush_s: float = 0.02,
                 queue_depth: int = 4096):
        self._dispatch = dispatch
        self.max_lanes = max_lanes
        self.flush_s = flush_s
        # bounded by contract (QSM-SERVE-UNBOUNDED): admission gates
        # in-flight lanes above this, so a full queue means misconfig,
        # and submit() fails fast instead of growing memory
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.batches = 0
        self.lanes_dispatched = 0
        self.width_dispatched = 0  # Σ padded widths (occupancy denominator)

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="qsm-serve-batcher")
        self._thread.start()

    def stop(self, drain_timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(drain_timeout_s)

    def submit(self, group_key: str, lane: Lane) -> bool:
        """Enqueue one lane; False when the (bounded) queue is full —
        the caller sheds the request."""
        try:
            self._q.put((group_key, lane), block=False)
            return True
        except queue.Full:
            return False

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        groups: Dict[str, _Group] = {}
        # drain everything before exiting: lanes admitted pre-stop must
        # resolve (their requests hold admission slots)
        while not (self._stop.is_set() and self._q.empty() and not groups):
            try:
                group_key, lane = self._q.get(timeout=self.flush_s / 2
                                              if self.flush_s > 0 else 0.01)
            except queue.Empty:
                pass
            else:
                groups.setdefault(group_key, _Group()).lanes.append(lane)
            now = time.monotonic()
            for key in list(groups):
                g = groups[key]
                reason = self._flush_reason(g, now)
                if reason is not None:
                    del groups[key]
                    self._flush(key, g.lanes, reason)
        for key, g in list(groups.items()):
            self._flush(key, g.lanes, "close")

    def _flush_reason(self, g: _Group, now: float) -> Optional[str]:
        if len(g.lanes) >= self.max_lanes:
            return "full"
        if self._stop.is_set():
            return "close"
        if g.lanes and min(l.deadline for l in g.lanes) - now <= self.flush_s:
            return "deadline"
        if now - g.first_ts >= self.flush_s:
            return "interval"
        return None

    def _flush(self, group_key: str, lanes: List[Lane], reason: str) -> None:
        # width is FIXED at max_lanes so every dispatch hits the same
        # compiled executable (core/property.py's padding lesson); a
        # group can never exceed it (lanes arrive one per loop turn),
        # but never drop a lane even if that invariant breaks
        width = max(self.max_lanes, len(lanes))
        self.batches += 1
        self.lanes_dispatched += len(lanes)
        self.width_dispatched += width
        why = {"batch": self.batches, "lanes": len(lanes), "width": width,
               "occupancy": round(len(lanes) / width, 3), "flush": reason}
        try:
            self._dispatch(group_key, lanes, why)
        except Exception as e:  # noqa: BLE001 — the loop thread must survive
            # an undispatchable batch resolves BUDGET_EXCEEDED (honest
            # "not decided", never a guess) so its requests don't hang
            # to their deadlines and the batcher keeps serving
            for lane in lanes:
                try:
                    lane.resolve(2, {**why, "error":
                                     f"{type(e).__name__}: {e}"[:200]})
                except Exception:  # noqa: BLE001 — resolver must not re-kill
                    pass

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        return {"batches": self.batches,
                "lanes": self.lanes_dispatched,
                "mean_occupancy": round(
                    self.lanes_dispatched / self.width_dispatched, 3)
                if self.width_dispatched else 0.0,
                "max_lanes": self.max_lanes,
                "flush_s": self.flush_s}
