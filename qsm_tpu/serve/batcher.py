"""Cross-request adaptive micro-batching — N clients, one dispatch.

The whole device story of this repo is "decide many histories in ONE
backend call" (BASELINE.json:9); per-request dispatch throws that away
the moment checking becomes a service.  The batcher coalesces history
lanes arriving from concurrent connections into one padded batch per
(spec, flush window), exactly the compile-bucket discipline
``core/property.py`` uses for trial groups: the batch is padded to a
FIXED lane width with empty (instantly-SUCCESS) histories so every
dispatch hits the same compiled executable, and ops are padded to the
shared ``OP_BUCKETS`` inside the engine as always.

Flush policy (first match wins, per spec group):

* ``full``     — the group reached ``max_lanes``: dispatch now;
* ``deadline`` — the earliest request deadline in the group is within
  one flush window: dispatch early rather than shed late;
* ``target``   — concurrent-dispatch mode only (``concurrency > 1``,
  the worker-pool server): the group reached the per-worker flush
  target ``lanes_target`` AND a dispatch slot is idle.  Waiting to
  fill ``max_lanes`` while workers sit idle trades the pool's whole
  point (parallel checking) for batch occupancy; under load every
  slot is busy, ``target`` stops firing, and groups grow to ``full``
  again — the batch width adapts to pool pressure by itself;
* ``interval`` — the oldest lane has waited ``flush_s``: latency floor
  for lonely clients;
* ``close``    — server shutdown drains every group.

With ``concurrency = 1`` (no pool) dispatch runs inline on the loop
thread, exactly the single-process behavior every pre-pool artifact
measured.  With a pool, flushes ride a BOUNDED hand-off queue to
``concurrency`` dispatcher threads (full queue ⇒ the group keeps
coalescing — backpressure, never a drop, never unbounded buffering).

Every batch carries a ``why`` provenance stamp (batch id, lane count,
width, occupancy, flush reason) that rides the responses of every
request in the batch and aggregates into ``qsm-tpu stats`` — the same
self-describing-artifact discipline as the planner's ``why`` and the
resilience counters.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.history import History


@dataclasses.dataclass
class Lane:
    """One history awaiting a verdict (the unit the batcher coalesces)."""

    key: str                 # verdict-cache fingerprint key
    history: History
    deadline: float          # absolute monotonic deadline of its request
    resolve: Callable        # resolve(verdict:int, batch_stamp:dict)
    # P-compositional sub-lane: this lane is one per-key sub-history of a
    # longer request history (server split it — serve/server.py).  Rides
    # the batch `why` stamp so a micro-batch says how many of its lanes
    # came from decomposition.
    pcomp: bool = False
    # trace plane (qsm_tpu/obs): the request's trace id and the span id
    # this lane's batch events parent under — how a micro-batch lands in
    # the right place of `qsm-tpu trace <id>`'s causal tree.  Empty when
    # tracing is off (the default); the batcher itself never reads them.
    trace: str = ""
    span: str = ""


class _Group:
    __slots__ = ("lanes", "first_ts")

    def __init__(self):
        self.lanes: List[Lane] = []
        self.first_ts = time.monotonic()


class MicroBatcher:
    """Coalesce lanes per spec group.  With ``concurrency = 1`` one
    loop thread also dispatches (the historical single-process shape);
    with a worker pool, ``concurrency`` dispatcher threads run flushes
    in parallel — engine access is then serialized per spec entry by
    the SERVER (``server.py _EngineEntry.dispatch_lock``; pool workers
    own their engines outright), never assumed here."""

    def __init__(self, dispatch: Callable[[str, List[Lane], dict], None],
                 max_lanes: int = 64, flush_s: float = 0.02,
                 queue_depth: int = 4096, concurrency: int = 1,
                 lanes_target: Optional[int] = None,
                 mesh_devices: int = 1):
        self._dispatch = dispatch
        self.flush_s = flush_s
        self.concurrency = max(1, int(concurrency))
        # mesh-aware flush target (qsm_tpu/mesh/): when the engine under
        # _dispatch shards its lane axis over N devices, every flushed
        # width must divide by N or the padded batch shards raggedly —
        # so max_lanes and lanes_target round UP to mesh multiples, and
        # one dispatch fills the whole mesh instead of one device
        self.mesh_devices = max(1, int(mesh_devices))
        self.max_lanes = self._mesh_ceil(max_lanes)
        # per-worker flush target: with N dispatch slots, a burst of
        # lanes splits into N parallel batches instead of one serial
        # max_lanes batch (the pool's scaling shape); 1 slot keeps the
        # historical fill-to-max_lanes behavior
        if lanes_target is not None:
            self.lanes_target = self._mesh_ceil(lanes_target)
        elif self.concurrency > 1:
            self.lanes_target = self._mesh_ceil(
                max(1, self.max_lanes // self.concurrency))
        else:
            self.lanes_target = self.max_lanes
        # bounded by contract (QSM-SERVE-UNBOUNDED): admission gates
        # in-flight lanes above this, so a full queue means misconfig,
        # and submit() fails fast instead of growing memory
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        # flush hand-off to the dispatcher threads — bounded so pool
        # pressure backs groups up into BIGGER batches, not into memory
        self._flush_q: Optional["queue.Queue"] = (
            queue.Queue(maxsize=max(2, self.concurrency * 2))
            if self.concurrency > 1 else None)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dispatchers: List[threading.Thread] = []
        self._in_flight = 0
        self._if_lock = threading.Lock()
        self.batches = 0
        self.lanes_dispatched = 0
        self.width_dispatched = 0  # Σ padded widths (occupancy denominator)

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="qsm-serve-batcher")
        self._thread.start()
        if self._flush_q is not None:
            for i in range(self.concurrency):
                t = threading.Thread(target=self._dispatch_loop,
                                     daemon=True,
                                     name=f"qsm-serve-dispatch-{i}")
                t.start()
                self._dispatchers.append(t)

    def stop(self, drain_timeout_s: float = 10.0) -> None:
        t_end = time.monotonic() + drain_timeout_s
        self._stop.set()
        if self._thread is not None:
            self._thread.join(drain_timeout_s)
        if self._flush_q is not None:
            # one sentinel per dispatcher, AFTER the loop thread drained
            # its groups into the flush queue; puts are bounded — the
            # dispatchers are consuming, so Full only means still-busy.
            # The window gets a floor: a loop-thread join that ate the
            # whole drain budget must not starve sentinel delivery
            # (dispatchers also self-terminate — _dispatch_loop — so a
            # lost sentinel degrades to a slower exit, never a leak)
            t_sent = max(t_end, time.monotonic() + 1.0)
            for _ in self._dispatchers:
                while time.monotonic() < t_sent:
                    try:
                        self._flush_q.put(None, timeout=0.25)
                        break
                    except queue.Full:
                        continue
            for t in self._dispatchers:
                t.join(max(0.5, t_end - time.monotonic()))

    def _mesh_ceil(self, n: int) -> int:
        """Smallest multiple of ``mesh_devices`` holding ``n`` lanes."""
        m = self.mesh_devices
        return max(1, int(n)) if m == 1 else -(-max(1, int(n)) // m) * m

    def submit(self, group_key: str, lane: Lane) -> bool:
        """Enqueue one lane; False when the (bounded) queue is full —
        the caller sheds the request."""
        try:
            self._q.put((group_key, lane), block=False)
            return True
        except queue.Full:
            return False

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        groups: Dict[str, _Group] = {}
        # drain everything before exiting: lanes admitted pre-stop must
        # resolve (their requests hold admission slots)
        while not (self._stop.is_set() and self._q.empty() and not groups):
            try:
                group_key, lane = self._q.get(timeout=self.flush_s / 2
                                              if self.flush_s > 0 else 0.01)
            except queue.Empty:
                pass
            else:
                groups.setdefault(group_key, _Group()).lanes.append(lane)
            now = time.monotonic()
            for key in list(groups):
                g = groups[key]
                reason = self._flush_reason(g, now)
                if reason is None:
                    continue
                if self._flush_q is None:
                    del groups[key]
                    self._flush(key, g.lanes, reason)
                elif self._try_enqueue(key, g.lanes, reason):
                    del groups[key]
                # else: hand-off queue full — the group stays and keeps
                # coalescing (backpressure into bigger batches)
        for key, g in list(groups.items()):
            if self._flush_q is None:
                self._flush(key, g.lanes, "close")
            else:
                self._enqueue_blocking(key, g.lanes, "close")

    def _flush_reason(self, g: _Group, now: float) -> Optional[str]:
        if len(g.lanes) >= self.max_lanes:
            return "full"
        if self._stop.is_set():
            return "close"
        if g.lanes and min(l.deadline for l in g.lanes) - now <= self.flush_s:
            return "deadline"
        if (self._flush_q is not None
                and len(g.lanes) >= self.lanes_target
                and self._idle_slots() > 0):
            return "target"
        if now - g.first_ts >= self.flush_s:
            return "interval"
        return None

    def _idle_slots(self) -> int:
        with self._if_lock:
            in_flight = self._in_flight
        return self.concurrency - in_flight - self._flush_q.qsize()

    def _try_enqueue(self, key: str, lanes: List[Lane],
                     reason: str) -> bool:
        try:
            self._flush_q.put_nowait((key, lanes, reason))
            return True
        except queue.Full:
            return False

    def _enqueue_blocking(self, key: str, lanes: List[Lane],
                          reason: str, timeout_s: float = 60.0) -> None:
        """Close-path hand-off: bounded blocking (the dispatchers are
        draining); past the bound the lanes resolve BUDGET_EXCEEDED
        rather than hang their requests."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            try:
                self._flush_q.put((key, lanes, reason), timeout=0.25)
                return
            except queue.Full:
                continue
        for lane in lanes:
            try:
                lane.resolve(2, {"flush": reason, "error": "drain timeout"})
            except Exception:  # noqa: BLE001 — resolver must not re-kill
                pass

    def _dispatch_loop(self) -> None:
        while True:
            try:
                item = self._flush_q.get(timeout=0.5)
            except queue.Empty:
                # self-termination: once stop() ran and the loop thread
                # (the only producer) is gone, an empty queue is final —
                # a dispatcher must not park forever waiting for a
                # sentinel that stop()'s bounded window failed to deliver
                if (self._stop.is_set() and self._thread is not None
                        and not self._thread.is_alive()):
                    return
                continue
            if item is None:
                return
            with self._if_lock:
                self._in_flight += 1
            try:
                self._flush(*item)
            finally:
                with self._if_lock:
                    self._in_flight -= 1

    def _flush(self, group_key: str, lanes: List[Lane], reason: str) -> None:
        # width is FIXED at max_lanes so every dispatch hits the same
        # compiled executable (core/property.py's padding lesson); a
        # group can never exceed it (lanes arrive one per loop turn),
        # but never drop a lane even if that invariant breaks — and the
        # overflow fallback still pads to a mesh-divisible width
        width = max(self.max_lanes, self._mesh_ceil(len(lanes)))
        with self._if_lock:  # dispatcher threads share these counters
            self.batches += 1
            batch_id = self.batches
            self.lanes_dispatched += len(lanes)
            self.width_dispatched += width
        why = {"batch": batch_id, "lanes": len(lanes), "width": width,
               "occupancy": round(len(lanes) / width, 3), "flush": reason}
        n_pcomp = sum(1 for lane in lanes if lane.pcomp)
        if n_pcomp:
            # decomposed lanes flattened into this micro-batch — the
            # stamp keeps split traffic distinguishable from whole-lane
            # traffic in every response and `qsm-tpu stats` aggregate
            why["pcomp_lanes"] = n_pcomp
        try:
            self._dispatch(group_key, lanes, why)
        except Exception as e:  # noqa: BLE001 — the loop thread must survive
            # an undispatchable batch resolves BUDGET_EXCEEDED (honest
            # "not decided", never a guess) so its requests don't hang
            # to their deadlines and the batcher keeps serving
            for lane in lanes:
                try:
                    lane.resolve(2, {**why, "error":
                                     f"{type(e).__name__}: {e}"[:200]})
                except Exception:  # noqa: BLE001 — resolver must not re-kill
                    pass

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._if_lock:
            in_flight = self._in_flight
        return {"batches": self.batches,
                "lanes": self.lanes_dispatched,
                "mean_occupancy": round(
                    self.lanes_dispatched / self.width_dispatched, 3)
                if self.width_dispatched else 0.0,
                "max_lanes": self.max_lanes,
                "flush_s": self.flush_s,
                "concurrency": self.concurrency,
                "lanes_target": self.lanes_target,
                "mesh_devices": self.mesh_devices,
                "in_flight": in_flight}
