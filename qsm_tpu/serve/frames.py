"""Length-prefixed JSON frames — the supervisor ⇄ worker pipe encoding.

4-byte big-endian length + UTF-8 JSON, shared by ``serve/worker.py``
(blocking worker-side reads) and ``serve/pool.py`` (deadline-bounded
supervisor-side reads).  Newline framing (the socket plane's choice,
serve/protocol.py) would be wrong here: a worker is trusted but
*killable*, and a length prefix makes a half-written frame from a
SIGKILLed worker detectable instead of silently mergeable with the
next one.

This module is deliberately import-light and OUTSIDE the package's
``__init__`` import graph on the worker side: ``python -m
qsm_tpu.serve.worker`` must not find its own module pre-imported by
``qsm_tpu.serve`` (runpy's double-import warning).

Frames are plain JSON dicts, so the schema is extensible by optional
keys: the trace plane (qsm_tpu/obs) adds an OPTIONAL ``trace`` field
to ``check`` frames — the trace ids of the micro-batch's request(s) —
which new workers echo in their response and old workers simply
ignore (a dict key nobody reads).  Version skew in either direction
stays harmless.
"""

from __future__ import annotations

import json
import struct
from typing import BinaryIO, Optional

HDR = struct.Struct(">I")
# sanity bound on a frame length read off the pipe: a supervisor/worker
# version skew or a torn frame must fail loudly, not allocate gigabytes
MAX_FRAME_BYTES = 256 * 1024 * 1024


def encode_frame(doc: dict) -> bytes:
    payload = json.dumps(doc).encode()
    return HDR.pack(len(payload)) + payload


def read_frame(stream: BinaryIO) -> Optional[dict]:
    """Blocking worker-side frame read; None on EOF (supervisor gone —
    the worker exits rather than linger orphaned).  The supervisor side
    never uses this: its reads are deadline-bounded (serve/pool.py)."""
    hdr = _read_exact(stream, HDR.size)
    if hdr is None:
        return None
    (n,) = HDR.unpack(hdr)
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {n} exceeds {MAX_FRAME_BYTES}")
    payload = _read_exact(stream, n)
    if payload is None:
        return None
    return json.loads(payload)


def _read_exact(stream: BinaryIO, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            return None  # EOF mid-frame: the peer is gone
        buf += chunk
    return buf
