"""``qsm_tpu.serve`` — linearizability checking as a long-lived service.

The ROADMAP north star is a system that "serves heavy traffic … via
sharding, batching, async, caching"; every prior entry point was a
one-shot process.  This package is the serving plane over the existing
ones — admission → micro-batch → dispatch → cache (docs/SERVING.md):

* ``protocol``  — JSON-lines wire format (the repo's one external
  history-row encoding) over TCP/UNIX sockets;
* ``server``    — :class:`CheckServer`: warm planner-built engines per
  spec behind ``resilience.FailoverBackend``;
* ``batcher``   — cross-request adaptive micro-batching into
  compile-bucket-padded lanes, with per-batch ``why`` provenance;
* ``cache``     — fingerprint-keyed verdict/witness LRU with an atomic
  persistent bank (kill/restart serves banked verdicts in O(1));
* ``admission`` — bounded in-flight lanes, preset-driven per-request
  deadlines, explicit ``SHED`` load shedding (with pool state);
* ``pool``      — :class:`WorkerPool`: supervised engine worker
  processes (``--workers N``) with crash/wedge shedding, undecided-lane
  re-dispatch, bounded-backoff respawn and per-spec quarantine;
* ``worker``    — the pool worker process entry point (bank-free warm
  host-ladder engines over a length-prefixed pipe protocol);
* ``client``    — :class:`CheckClient` (``qsm-tpu submit`` / bench)
  and :class:`SessionHandle` (seq-tracked streaming sessions).

Monitor sessions (qsm_tpu/monitor, docs/MONITOR.md): the protocol's
``session.open`` / ``session.append`` / ``session.close`` verbs turn
request/response checking into a LIVE service — clients stream
invocation/response events as they happen, per-session incremental
frontiers bank decided prefixes in the verdict cache under rolling
prefix fingerprints (a restarted node resumes from the bank), and a
verdict flip is answered the moment it is decidable with a
shrink-plane-minimized repro and certificate.  ``qsm-tpu monitor``
tails a foreign event log (qsm_tpu/ingest) into a session.

Observability (qsm_tpu/obs, docs/OBSERVABILITY.md): every response
carries a request-scoped trace id; ``--trace-log`` records the full
causal tree (``qsm-tpu trace <id>`` rebuilds it), ``--metrics-port``
serves live Prometheus metrics that reconcile with ``stats`` by
construction, and ``--flight-dir`` arms the crash flight recorder.

Fleet tier (qsm_tpu/fleet, docs/SERVING.md "Fleet"): N of these
servers — started with ``node_id`` / ``replog_dir`` so responses are
node-stamped and the verdict bank is a segmented REPLICATED log
serving the ``replog.*`` anti-entropy ops — sit behind a
protocol-identical ``fleet.FleetRouter``; clients need no changes.
With ``peers=``/``--peers`` the nodes also gossip replog segments
DIRECTLY (fleet/gossip.py) so replication survives every router
dying, and routers themselves run HA behind a filesystem lease
(fleet/lease.py; clients ride it with a comma ``--addr a,b`` list).

CLI: ``qsm-tpu serve`` / ``qsm-tpu submit`` / ``qsm-tpu fleet``
(utils/cli.py); bench: tools/bench_serve.py (artifact
``BENCH_SERVE_r08.json``) and tools/bench_fleet.py
(``BENCH_FLEET_r13.json``); static gates: the QSM-SERVE pass family
(analysis/serve_passes.py), the QSM-POOL family
(analysis/pool_passes.py), the QSM-OBS family
(analysis/obs_passes.py) and the QSM-FLEET family
(analysis/fleet_passes.py).
"""

from .admission import AdmissionController
from .batcher import Lane, MicroBatcher
from .cache import CacheEntry, VerdictCache, fingerprint_key
from .client import CheckClient, SessionHandle
from .pool import (WorkerDead, WorkerFault, WorkerPool, WorkerTimeout)
from .protocol import (VERDICT_NAMES, history_to_rows, parse_address,
                       rows_to_history)
from .server import CheckServer

__all__ = [
    "AdmissionController", "CacheEntry", "CheckClient", "CheckServer",
    "Lane", "MicroBatcher", "VERDICT_NAMES", "VerdictCache",
    "WorkerDead", "WorkerFault", "WorkerPool", "WorkerTimeout",
    "SessionHandle", "fingerprint_key", "history_to_rows",
    "parse_address", "rows_to_history",
]
