"""``CheckServer`` — long-lived, warm, batched linearizability checking.

Every one-shot entry point (CLI run/check, the bench tools) pays engine
construction, compile-bucket warmup and planner profiling per
invocation, and identical histories re-check from scratch.  The server
is the inference-stack shape the ROADMAP's serving north star names —
admission → micro-batch → dispatch → cache — over the existing planes:

* **Warm engine set** — one engine per spec, built once via the search
  planner (``search/planner.py plan_search`` supplies the plan and its
  ``why`` provenance) and wrapped in ``resilience.FailoverBackend``: a
  wedged device degrades the SERVER to the exact host ladder, not the
  request.  The default ``auto`` engine is the host cpp→memo ladder —
  today's honest fast path (README) — kept warm and shared.
* **Micro-batching** — ``batcher.MicroBatcher`` coalesces lanes from
  concurrent connections into one padded dispatch per spec (N clients
  share one backend call instead of N).
* **Verdict cache** — ``cache.VerdictCache`` answers duplicate
  submissions (and their witnesses) in O(1) from an atomic persistent
  bank that survives server kill/restart.
* **Admission** — ``admission.AdmissionController`` bounds in-flight
  lanes and enforces per-request deadlines from the ``serve`` policy
  preset; overload and lateness are answered ``SHED``, never wrong.
* **Worker pool** — with ``workers=N`` the batcher's dispatches fan
  out to a supervised pool of engine worker PROCESSES
  (``serve/pool.py`` / ``serve/worker.py``): checking outscales the
  one core the in-process path saturates (BENCH_SERVE_r07's wall), a
  crashed or wedged worker is shed like a wedged chip with undecided
  lanes re-dispatched, and the verdict bank stays supervisor-owned
  (workers are bank-free, so no SIGKILL can tear it).  ``workers=0``
  keeps the single-process path unchanged.
* **Fault plane** — the batch dispatch runs through the ``serve``
  fault site (``QSM_TPU_FAULTS=hang:serve`` / ``raise:serve``) under a
  watchdog, and pool workers through the ``worker`` site
  (``kill:worker`` / ``hang:worker`` / ``raise:worker``), so every
  degraded-server behavior is CPU-testable like every other
  degradation path (tests/test_serve.py, tests/test_serve_pool.py).

Wire protocol: serve/protocol.py (JSON lines over TCP or UNIX socket).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..core.history import History
from ..obs import Observability, new_span_id, new_trace_id
from ..obs import global_obs, set_global
from ..ops.backend import Verdict, device_error_types
from ..resilience.failover import (FailoverBackend, collect_resilience,
                                   host_fallback)
from ..resilience.faults import fired_snapshot, inject
from ..resilience.policy import RetryPolicy, preset, watchdog
from ..search.stats import collect_search_stats, stats_delta
from .admission import AdmissionController
from .batcher import Lane, MicroBatcher
from .cache import VerdictCache, fingerprint_key
from .protocol import (VERDICT_NAMES, LineChannel, history_to_rows,
                       rows_to_history, send_doc)


class _EngineEntry:
    """One warm spec: engine + witness oracle + planner provenance.

    ``dispatch_lock`` serializes in-process dispatches on this entry:
    engines are stateful (memo tables, search counters) and NOT
    thread-safe — with one batcher thread (workers=0) the lock is
    uncontended, and with a worker pool it guards the fallback path
    (quarantined spec / exhausted pool), where several dispatcher
    threads may otherwise hit the same engine concurrently.

    ``proj`` / ``proj_group_key`` carry the spec's VALIDATED per-key
    projection (ops/pcomp.py): the projected spec instance whose
    fingerprints key the per-sub-history cache rows, and the batcher
    group sub-lanes flatten into (the projected spec's own group — a
    kv-256 request and a plain register request share one engine and
    one micro-batch stream).  None when the spec does not decompose.
    ``pcomp`` is the lazy witness-path combinator."""

    __slots__ = ("spec", "engine", "oracle", "plan_why", "emergency",
                 "dispatch_lock", "proj", "proj_group_key", "pcomp")

    def __init__(self, spec, engine, oracle, plan_why,
                 proj=None, proj_group_key=None):
        self.spec = spec
        self.engine = engine
        self.oracle = oracle
        self.plan_why = plan_why
        self.emergency = None  # built on first serve-site fault
        self.dispatch_lock = threading.Lock()
        self.proj = proj
        self.proj_group_key = proj_group_key
        self.pcomp = None  # built on first decomposed witness request


class _SubJoin:
    """Recombine per-key sub-lane verdicts into ONE whole-history verdict
    — the PComp aggregation rule (VIOLATION beats BUDGET_EXCEEDED beats
    LINEARIZABLE) — across cache hits, batch dispatches and aborts.
    Thread-safe: feeds arrive from the connection thread (hits) and any
    dispatcher thread (batch resolutions)."""

    def __init__(self, n: int, on_complete):
        self._lock = threading.Lock()
        self._n = n
        self._fed = 0
        self._worst = int(Verdict.LINEARIZABLE)
        self._batch: Optional[dict] = None
        self._on_complete = on_complete

    def feed(self, verdict: int, batch: Optional[dict] = None) -> None:
        with self._lock:
            if batch is not None:
                self._batch = batch
            v = int(verdict)
            if v == int(Verdict.VIOLATION):
                self._worst = v
            elif (v == int(Verdict.BUDGET_EXCEEDED)
                  and self._worst == int(Verdict.LINEARIZABLE)):
                self._worst = v
            self._fed += 1
            fire = self._fed == self._n
            worst, b = self._worst, self._batch
        if fire:
            self._on_complete(worst, b)

    def resolver(self):
        def _resolve(verdict: int, batch: dict) -> None:
            self.feed(verdict, batch)

        return _resolve

    def abort(self, k: int) -> None:
        """Feed BUDGET_EXCEEDED for ``k`` sub-lanes that will never
        dispatch (mid-request shed): the join still completes once the
        in-flight remainder resolves, so the lane's admission slot
        releases and nothing leaks."""
        for _ in range(k):
            self.feed(int(Verdict.BUDGET_EXCEEDED))


class _PendingRequest:
    """Per-request lane accounting: connection thread waits, cache hits
    and batch dispatches resolve."""

    def __init__(self, n: int):
        self._lock = threading.Lock()
        self._remaining = n
        self.verdicts: List[Optional[int]] = [None] * n
        self.cached: List[bool] = [False] * n
        self.witnesses: List[Optional[list]] = [None] * n
        self.lane_submitted: List[bool] = [False] * n  # batcher owns it
        self.batches: List[dict] = []
        self.dead = False  # shed: late resolutions are cache-only
        self._done = threading.Event()
        if n == 0:
            self._done.set()

    def resolve(self, i: int, verdict: int, cached: bool = False,
                witness: Optional[list] = None,
                batch: Optional[dict] = None) -> None:
        with self._lock:
            if self.verdicts[i] is not None:
                return
            self.verdicts[i] = int(verdict)
            self.cached[i] = cached
            self.witnesses[i] = witness
            if batch is not None and batch.get("batch") not in {
                    b.get("batch") for b in self.batches}:
                self.batches.append(batch)
            self._remaining -= 1
            if self._remaining == 0:
                self._done.set()

    def wait(self, timeout_s: float) -> bool:
        return self._done.wait(max(0.0, timeout_s))


class CheckServer:
    """See module docstring.  ``start()`` binds and returns; the accept
    loop, connection readers and the batcher run on daemon threads."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 unix_path: Optional[str] = None, *,
                 engine: str = "auto",
                 max_lanes: int = 64, flush_s: float = 0.02,
                 queue_depth: int = 1024,
                 cache_path: Optional[str] = None,
                 cache_entries: int = 4096,
                 policy: Optional[RetryPolicy] = None,
                 allow_shutdown: bool = True,
                 engine_factory=None,
                 workers: int = 0,
                 worker_policy: Optional[RetryPolicy] = None,
                 quarantine_after: int = 2,
                 pcomp: bool = True,
                 trace_log: Optional[str] = None,
                 flight_dir: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 obs: Optional[Observability] = None,
                 node_id: Optional[str] = None,
                 replog_dir: Optional[str] = None,
                 replog_seal_rows: int = 256,
                 peers: Optional[list] = None,
                 gossip_s: float = 0.0,
                 gossip_fanout: int = 2,
                 max_sessions: int = 256,
                 session_events: int = 65_536,
                 session_states: int = 64,
                 session_budget: int = 2_000_000,
                 session_dir: Optional[str] = None,
                 lease_path: Optional[str] = None,
                 slo: Optional[str] = None,
                 slo_window_s: float = 60.0,
                 devq_dir: Optional[str] = None,
                 devq_cap: int = 512,
                 mesh_devices: int = 1):
        if engine not in ("auto", "planned"):
            raise ValueError(f"unknown serve engine {engine!r}; "
                             "one of ('auto', 'planned')")
        if workers and engine != "auto":
            # pool workers own the host ladder only; a device engine
            # belongs in the supervisor process where the probe gate ran
            raise ValueError("workers>0 requires engine='auto' (pool "
                             "workers run the host cpp->memo ladder)")
        if workers and mesh_devices > 1:
            # mutually exclusive fan-outs: pool workers own host-ladder
            # engines (no device, nothing to shard); the mesh fan-out
            # belongs to the planned device engine in THIS process
            raise ValueError("workers>0 and mesh_devices>1 are exclusive "
                             "fan-outs (the pool runs host engines)")
        self.host, self.port, self.unix_path = host, port, unix_path
        self.engine_kind = engine
        # lane-axis mesh span of the planned device engine (qsm_tpu/mesh/,
        # docs/MESH.md): >1 makes _build_engine plan per-mesh-shape
        # buckets + sharded dispatch, and the batcher's flush target
        # rounds to mesh multiples so one flush fills the whole mesh
        self.mesh_devices = max(1, int(mesh_devices))
        self.policy = policy or preset("serve")
        self.max_lanes = max_lanes
        self.allow_shutdown = allow_shutdown
        self._engine_factory = engine_factory
        # observability plane (qsm_tpu/obs, docs/OBSERVABILITY.md):
        # metrics are ALWAYS live; span emission + the flight ring are
        # opt-in via trace_log/flight_dir and every emit site below
        # guards on obs.on — the tracing-off serve path must stay
        # within noise of a no-obs build (BENCH_OBS_r11.json)
        self.obs = obs if obs is not None else Observability(
            trace_log=trace_log, flight_dir=flight_dir)
        self.metrics_port = metrics_port
        self._metrics_server = None
        self._m_request_s = self.obs.metrics.histogram(
            "qsm_serve_request_seconds",
            "end-to-end request latency (admission to response), "
            "labeled by verb")
        self.obs.metrics.register_collector(self._metric_samples)
        # SLO plane (obs/slo.py, docs/OBSERVABILITY.md "Fleet"):
        # declared objectives evaluated over sliding windows of the
        # SAME per-verb latency histogram and shed counters /metrics
        # exposes; the health op reads it and an ok->breach transition
        # emits the slo.breach flight-dump trigger
        self.slo = None
        if slo:
            from ..obs import SloEvaluator, parse_slo

            self.slo = SloEvaluator(
                parse_slo(slo), latency_hist=self._m_request_s,
                requests_fn=lambda: self.requests,
                sheds_fn=self._shed_total, window_s=slo_window_s,
                on_breach=self._on_slo_breach)
            self.obs.metrics.register_collector(self.slo.metric_samples)
        self.n_workers = max(0, int(workers))
        self.pool = None
        if self.n_workers:
            from .pool import WorkerPool

            self.pool = WorkerPool(self.n_workers, policy=worker_policy,
                                   quarantine_after=quarantine_after,
                                   obs=self.obs)
        # fleet tier (qsm_tpu/fleet): the node id stamps every response
        # (a router-merged answer says which node decided which lanes),
        # and replog_dir swaps the single-file bank for the segmented
        # replicated log so this node can serve the replog.* anti-
        # entropy ops (docs/SERVING.md "Fleet")
        self.node_id = node_id
        self.replog = None
        if replog_dir is not None:
            if cache_path is not None:
                # refuse, don't silently pick: --cache's single file
                # would never be written (or loaded) once the store
                # owns persistence, and prior banked verdicts in it
                # would be silently abandoned
                raise ValueError(
                    "cache_path and replog_dir are mutually exclusive "
                    "banks; the segmented replog replaces the single "
                    "file (migrate by serving once from --cache, then "
                    "re-banking under --replog-dir)")
            from ..fleet.replog import SegmentedLog

            self.replog = SegmentedLog(replog_dir,
                                       node_id=node_id or "n0",
                                       seal_rows=replog_seal_rows)
        self.cache = VerdictCache(max_entries=cache_entries,
                                  path=cache_path, store=self.replog)
        # peer-to-peer anti-entropy (fleet/gossip.py): with peers and
        # a replog, this node keeps its banked verdicts converging
        # with the fleet's NODE-TO-NODE — no router in the loop, so
        # replication survives every router being dead.  Peers come
        # from the ctor (static deploys) or the `gossip.peers` op
        # (qsm-tpu fleet wires spawned nodes whose addresses are only
        # known after their banners).
        self.gossip = None
        self._gossip_interval = float(gossip_s)
        self._gossip_fanout = int(gossip_fanout)
        if self.replog is not None and peers:
            self._make_gossip(peers)
        # lease hosting (fleet/lease.py, ISSUE 18): with a lease_path
        # this node answers the lease.* ops, so routers on OTHER hosts
        # share one record through a TcpLeaseStore — the flock and the
        # clock both live here, keeping the single-authority safety
        # argument of the filesystem lease
        self.lease_store = None
        self.lease_ops = 0
        if lease_path is not None:
            from ..fleet.lease import FileLeaseStore

            self.lease_store = FileLeaseStore(lease_path)
        # device-work queue (qsm_tpu/devq, docs/WINDOWS.md): with a
        # devq_dir this node banks device-worthy work — its own seams
        # (oversize admission, the pcomp split, shrink rounds, monitor
        # appends reach it through the process-global hook) plus the
        # devq.* wire ops, so ANY fleet node can bank work this or some
        # other node's window later drains.  The queue rides its own
        # SegmentedLog (a second replog row domain); gossip grows a
        # devq exchange leg when both are configured.
        self.devq = None
        self.devq_report: Optional[dict] = None  # last drained window
        if devq_dir is not None:
            from ..devq import DeviceWorkQueue, set_global_devq

            self.devq = DeviceWorkQueue(devq_dir,
                                        node_id=node_id or "n0",
                                        cap=devq_cap)
            set_global_devq(self.devq)
        self.admission = AdmissionController(
            queue_depth=queue_depth, policy=self.policy,
            pool_state=self.pool.shed_state if self.pool else None)
        self.batcher = MicroBatcher(self._dispatch, max_lanes=max_lanes,
                                    flush_s=flush_s,
                                    queue_depth=max(queue_depth * 2, 64),
                                    concurrency=self.n_workers or 1,
                                    mesh_devices=self.mesh_devices)
        self._engines: Dict[str, _EngineEntry] = {}
        self._engines_lock = threading.Lock()
        self._engine_builds: Dict[str, threading.Lock] = {}
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []
        self._t0 = time.monotonic()
        self.requests = 0
        self.histories = 0
        self.serve_faults = 0       # serve-site degradations (batch level)
        self.budget_resolved = 0    # engine BUDGET_EXCEEDED → oracle-exact
        # P-compositional split plane (ops/pcomp.py): long histories of
        # specs with a VALIDATED projection are split into per-key
        # sub-lanes that ride the projected spec's micro-batches, with
        # per-sub-history cache rows — a one-key change to a 512-op
        # history re-checks that key only (docs/PCOMP.md)
        self.pcomp_enabled = bool(pcomp)
        # counters below are written from concurrent connection threads
        # — guarded, because bench/tests compute per-request DELTAS from
        # stats() (one_key_change.recheck_keys) and a lost increment
        # would corrupt them (the QSM-RACE-UNGUARDED discipline)
        self._pcomp_lock = threading.Lock()
        self.pcomp_split = 0        # request histories decomposed
        self.pcomp_subs = 0         # sub-lanes produced from them
        self.pcomp_sub_hits = 0     # sub-lanes answered from the cache
        # Shrink verb (qsm_tpu/shrink, docs/SHRINK.md): a failing
        # history submitted as {"op": "shrink"} is minimized with its
        # frontier candidates riding the SAME micro-batch lanes as
        # paying check traffic (and banking in the same verdict cache),
        # and the minimized result banks under the original history's
        # fingerprint so duplicate shrink requests answer O(1)
        self._shrink_lock = threading.Lock()
        self._shrink_bank: "OrderedDict[str, dict]" = OrderedDict()
        self.shrink_bank_entries = 1024
        self.shrink_requests = 0
        self.shrink_bank_hits = 0
        self.shrink_rounds = 0      # frontier rounds across all requests
        self.shrink_lanes = 0      # candidate lanes those rounds carried
        self.shrink_memo_hits = 0  # candidates answered without checking
        # Monitor sessions (qsm_tpu/monitor, docs/MONITOR.md): clients
        # stream invocation/response events through the session.* ops;
        # per-session incremental frontiers bank decided prefixes in
        # THE verdict cache (prefix fingerprints — a node restart
        # resumes from the bank), and a verdict flip is answered the
        # moment it is decidable with a shrink-plane-minimized repro.
        from ..monitor import SessionManager

        # ``session_dir`` makes sessions DURABLE (ISSUE 18,
        # monitor/store.py): restart-or-evicted sids resume from the
        # snapshot+journal substrate in O(doc) with zero engine folds
        session_store = None
        if session_dir is not None:
            from ..monitor import SessionStore

            session_store = SessionStore(session_dir)
        self.monitor = SessionManager(
            bank=self.cache, max_sessions=max_sessions,
            max_events=session_events, node_budget=session_budget,
            max_states=session_states, store=session_store)

    def _make_gossip(self, peers) -> None:
        from ..fleet.gossip import GossipAgent

        if self.gossip is None:
            self.gossip = GossipAgent(
                self.node_id or "n0", self.replog, self.cache,
                peers=peers, interval_s=self._gossip_interval,
                fanout=self._gossip_fanout, obs=self.obs,
                devq=self.devq)
        else:
            self.gossip.set_peers(peers)

    # -- lifecycle -----------------------------------------------------
    @property
    def address(self) -> str:
        if self.unix_path:
            return self.unix_path
        return f"{self.host}:{self.port}"

    def start(self) -> "CheckServer":
        if self.unix_path:
            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(self.unix_path)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._sock.settimeout(0.2)  # accept loop stays shutdown-checkable
        if self.pool is not None:
            self.pool.start()
        self.batcher.start()
        # install as the process-global obs sink so engine layers
        # without an obs handle (failover/hybrid degradations, the
        # fault plane) report into this server's trace/flight rails
        set_global(self.obs)
        if self.metrics_port is not None:
            from ..obs import MetricsServer

            # bound to the SERVE host (loopback for unix-socket
            # servers): the printed metrics address must be the one a
            # scraper can actually reach
            self._metrics_server = MetricsServer(
                self.obs.metrics,
                host=self.host if not self.unix_path else "127.0.0.1",
                port=self.metrics_port).start()
            self.metrics_port = self._metrics_server.port
        if self.gossip is not None:
            self.gossip.start()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="qsm-serve-accept")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        # the CLI stops twice by design (shutdown handler + finally);
        # teardown below is idempotent, but the post-mortem flight dump
        # must fire exactly once or every clean exit banks duplicates
        first_stop = not self._stop.is_set()
        self._stop.set()
        if not first_stop:
            # the first stop() often runs on a daemon handler thread
            # (the shutdown op); returning before it finishes lets the
            # CLI's finally-stop exit the process and kill that thread
            # MID-flight-dump — a torn FLIGHT .tmp.  Teardown below is
            # bounded (joins/waits all carry timeouts), so this is too.
            self._stopped.wait(15.0)
            return
        # order matters: the batcher drains FIRST (in-flight batches
        # still need the pool), THEN the pool tears down its worker
        # processes deterministically (exit frame → terminate → bounded
        # wait → kill escalation → reap) so no test or caller ever
        # leaks a child process
        self.batcher.stop()
        if self.gossip is not None:
            self.gossip.stop()
        if self.pool is not None:
            self.pool.stop()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self.unix_path:
            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
        # the accept thread is retained, so its teardown is bounded:
        # the loop re-checks _stop every 0.2 s (settimeout) and the
        # closed socket breaks it immediately (QSM-THREAD-LIFECYCLE)
        for t in self._threads:
            t.join(2.0)
        self.cache.flush()
        # the post-mortem baseline dump: what was in flight at teardown
        # (forced — a stop() artifact must not be rate-limited away)
        if first_stop:
            self.obs.dump_flight("server_stop", force=True)
        # a caller-supplied Observability outlives this server: the
        # collector must go with the server or a reused registry
        # double-emits every serve series (and pins the dead server)
        self.obs.metrics.unregister_collector(self._metric_samples)
        if self.slo is not None:
            self.obs.metrics.unregister_collector(
                self.slo.metric_samples)
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if global_obs() is self.obs:
            set_global(None)
        if self.devq is not None:
            from ..devq import global_devq, set_global_devq

            if global_devq() is self.devq:
                set_global_devq(None)
        self.obs.close()
        self._stopped.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Block until the server stops (shutdown request / stop());
        True when it did."""
        return self._stop.wait(timeout_s)

    # -- engines -------------------------------------------------------
    def warm(self, model: str, spec_kwargs: Optional[dict] = None) -> None:
        """Build (and warm-dispatch) the engine for a spec up front so
        the first request pays nothing — in this process AND in every
        pool worker."""
        entry = self._engine_for(model, spec_kwargs or {})
        pad = [History([])] * self.max_lanes
        with entry.dispatch_lock:
            entry.engine.check_histories(entry.spec, pad)
        if self.pool is not None:
            self.pool.warm(model, spec_kwargs or {})

    def _spec_key(self, model: str, spec_kwargs: dict) -> str:
        return json.dumps([model, spec_kwargs or {}], sort_keys=True)

    def _engine_for(self, model: str, spec_kwargs: dict) -> _EngineEntry:
        key = self._spec_key(model, spec_kwargs)
        with self._engines_lock:
            entry = self._engines.get(key)
            if entry is not None:
                return entry
            build_lock = self._engine_builds.setdefault(
                key, threading.Lock())
        # construction happens OUTSIDE the global map lock (a planned
        # device build can take tens of seconds; warm specs' lookups and
        # the batcher's dispatch must not block behind it) but under a
        # per-key lock so each spec still gets exactly ONE engine — the
        # resilience/search counters aggregate per instance
        with build_lock:
            with self._engines_lock:
                entry = self._engines.get(key)
                if entry is not None:
                    return entry
            entry = self._build_engine(model, spec_kwargs)
            with self._engines_lock:
                self._engines[key] = entry
            return entry

    def _build_engine(self, model: str, spec_kwargs: dict) -> _EngineEntry:
        from ..models.registry import MODELS, make
        from ..ops.wing_gong_cpu import WingGongCPU
        from ..search.planner import plan_search

        spec, _ = make(model, "atomic", spec_kwargs or None)
        if self._engine_factory is not None:
            inner, plan_why = self._engine_factory(spec), ["injected"]
        elif self.engine_kind == "planned":
            # the planner-built device checker; same reachability
            # contract as --backend tpu (the CLI gates before start).
            # mesh_devices > 1 sizes the plan for the mesh and
            # build_backend derives the matching lane sharding — ONE
            # dispatch then fills every device (docs/MESH.md)
            from ..search.planner import build_backend

            plan = plan_search(spec, platform=None,
                               mesh_devices=self.mesh_devices)
            inner, plan_why = build_backend(spec, plan), list(plan.why)
        else:
            # today's fast path: the exact host ladder (native C++
            # when the toolchain builds, else the memoised oracle),
            # warm and shared.  The plan is still computed for its
            # provenance — the response's `why` says what a device
            # plan WOULD do for this spec.
            plan = plan_search(spec, platform="cpu")
            inner, plan_why = host_fallback(spec), list(plan.why)
        engine = FailoverBackend(spec, inner)
        oracle = WingGongCPU(memo=True)
        proj = proj_group = None
        if self.pcomp_enabled:
            from ..core.spec import projection_report

            problems = projection_report(spec)
            if not problems:
                p = spec.projected_spec()
                if p.name in MODELS:
                    # dispatchable: sub-lanes rebuild this spec from its
                    # registry name in the supervisor AND in every pool
                    # worker, so split traffic rides the pool unchanged
                    proj = p
                    proj_group = self._spec_key(p.name, p.spec_kwargs())
                else:
                    plan_why.append(
                        f"pcomp=off (projected spec {p.name!r} is not a "
                        "registry model; sub-lanes would be "
                        "undispatchable)")
            else:
                # the refusal path, stamped: an invalid projection must
                # never split silently — the whole-history plan serves
                plan_why.append(f"pcomp=off (refused: {problems[0]})")
        return _EngineEntry(spec, engine, oracle, plan_why,
                            proj=proj, proj_group_key=proj_group)

    # -- accept / connection plumbing ----------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # socket closed by stop()
            # daemon, never joined, and NOT retained: a long-lived
            # server accepting one connection per stats poll would
            # otherwise grow an unbounded thread list — the same
            # accumulation hazard the QSM-SERVE-UNBOUNDED lint exists
            # for, at the object level
            threading.Thread(target=self._serve_connection,
                             args=(conn,), daemon=True,
                             name="qsm-serve-conn").start()

    def _serve_connection(self, conn: socket.socket) -> None:
        chan = LineChannel(conn)
        try:
            while not self._stop.is_set():
                line = chan.read_line(stop=self._stop.is_set)
                if line is None:
                    return
                try:
                    req = json.loads(line)
                except ValueError:
                    self._send(conn, {"ok": False, "error": "bad json"})
                    continue
                self._handle(conn, req)
                if req.get("op") == "shutdown" and self.allow_shutdown:
                    return
        except OSError:
            pass  # peer went away mid-response
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _send(self, conn: socket.socket, doc: dict) -> None:
        """THE response egress: a fleet node stamps its ``node`` id on
        every response (ok/SHED/error alike) so a router-merged answer
        — and a flight dump, and a trace — can say which node decided
        which lanes (docs/SERVING.md "Fleet")."""
        if self.node_id is not None and "node" not in doc:
            doc = {**doc, "node": self.node_id}
        send_doc(conn, doc)

    def _handle(self, conn: socket.socket, req: dict) -> None:
        op = req.get("op", "check")
        if op == "stats":
            self._send(conn, {"ok": True, "stats": self.stats()})
        elif op in ("obs.spans", "obs.trace", "obs.metrics", "health"):
            try:
                self._handle_obs(conn, op, req)
            except OSError:
                raise
            except Exception as e:  # noqa: BLE001 — answer, don't die
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "error": f"{type(e).__name__}: {e}"})
        elif op in ("replog.digests", "replog.pull", "replog.push",
                    "replog.covers", "replog.subsumed"):
            self._handle_replog(conn, op, req)
        elif op in ("devq.put", "devq.digests", "devq.pull",
                    "devq.drain_report"):
            try:
                self._handle_devq(conn, op, req)
            except OSError:
                raise
            except Exception as e:  # noqa: BLE001 — answer, don't die
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "error": f"{type(e).__name__}: {e}"})
        elif op == "gossip.peers":
            self._handle_gossip_peers(conn, req)
        elif op in ("lease.acquire", "lease.renew", "lease.release",
                    "lease.read"):
            self._handle_lease(conn, op, req)
        elif op in ("session.open", "session.append", "session.close"):
            try:
                self._handle_session(conn, op, req)
            except OSError:
                raise  # peer gone: let the connection close
            except Exception as e:  # noqa: BLE001 — answer, don't die
                self._send(conn, {"id": req.get("id"), "ok": False,
                                  "session": req.get("session"),
                                  "error": f"{type(e).__name__}: {e}"})
        elif op == "shutdown":
            if self.allow_shutdown:
                self._send(conn, {"ok": True, "stopping": True})
                self.stop()
            else:
                self._send(conn, {"ok": False,
                                "error": "shutdown disabled"})
        elif op in ("check", "shrink"):
            try:
                if op == "check":
                    self._handle_check(conn, req)
                else:
                    self._handle_shrink(conn, req)
            except OSError:
                raise  # the peer went away: let the connection close
            except Exception as e:  # noqa: BLE001 — a malformed request
                # (bad rows, bad spec_kwargs, a failing engine build)
                # must answer an error, not kill the connection thread;
                # no admission slots are held here (_handle_check admits
                # only after validation and releases on its own errors;
                # _handle_shrink releases in its finally)
                self._send(conn, {"id": req.get("id"), "ok": False,
                                "error": f"{type(e).__name__}: {e}"})
        else:
            self._send(conn, {"ok": False, "error": f"unknown op {op!r}"})

    # -- the obs collection / federation / health ops ------------------
    def _handle_obs(self, conn: socket.socket, op: str,
                    req: dict) -> None:
        """The fleet-observability surface every node answers
        (docs/OBSERVABILITY.md "Fleet"):

        * ``obs.spans``   — one cursor-paged, bounded, idempotent page
          of this process's span log (obs/collect.py owns the cursor
          semantics) — what the router's collection sweep scrapes;
        * ``obs.trace``   — one trace's events (causal closure) from
          this process's own span log, for single-node debugging;
        * ``obs.metrics`` — this process's metric samples, JSON-shaped,
          so a router can federate them under a ``node`` label without
          every node needing its own scrape port;
        * ``health``      — the SLO evaluation (obs/slo.py) or plain
          liveness when no objectives are configured.
        """
        if op == "health":
            self._send(conn, {"id": req.get("id"), "ok": True,
                              **self.health_doc()})
            return
        if op == "obs.metrics":
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "samples": [list(s) for s in
                                          self.obs.metrics.collect()]})
            return
        if op == "obs.spans":
            from ..obs.collect import span_page_response

            self._send(conn, span_page_response(self.obs.tracer, req))
            return
        # obs.trace: the trace's events plus causal ancestors from the
        # local log only (a router's handler merges its collected log)
        from ..obs import load_events, trace_closure

        path = self.obs.tracer.path
        if path is None:
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "enabled": False, "events": []})
            return
        self.obs.tracer.flush()
        trace_id = str(req.get("trace") or "")
        events = trace_closure(load_events(path), trace_id)
        self._send(conn, {"id": req.get("id"), "ok": True,
                          "enabled": True, "trace": trace_id,
                          "events": events})

    def _shed_total(self) -> float:
        adm = self.admission.snapshot()
        return float(adm["shed_queue"] + adm["shed_deadline"])

    def _on_slo_breach(self, row: dict) -> None:
        # the configured-objective incident: one event per ok->breach
        # transition; `slo.breach` is a flight-dump trigger
        self.obs.event("slo.breach", objective=row["objective"],
                       burn=row["burn_rate"], value=row["value"],
                       target=row["target"])

    def health_doc(self) -> dict:
        """The ``health`` op payload: per-objective burn rates and an
        overall status (obs/slo.py), or plain liveness when no SLO is
        configured — the status maps to `qsm-tpu health`'s pinned exit
        codes either way.  A node running a device-work queue folds
        ``window_utilization`` in as one more objective (ISSUE 20): the
        last drained window's utilization against the 0.8 target, with
        no-windows-yet reported as zero samples, NOT a breach — rare
        windows are the premise, their absence is not an incident."""
        if self.slo is None:
            doc = {"status": "ok",
                   "slo": {"configured": False},
                   "uptime_s": round(time.monotonic() - self._t0, 1)}
        else:
            ev = self.slo.evaluate()
            doc = {"status": ev["status"],
                   "slo": {"configured": True,
                           "window_s": ev["window_s"],
                           "window_actual_s": ev["window_actual_s"],
                           "objectives": ev["objectives"]},
                   "uptime_s": round(time.monotonic() - self._t0, 1)}
        if self.devq is not None:
            from ..obs.slo import utilization_objective
            from ..obs.slo import worst_status as _worst

            row = utilization_objective(
                (self.devq_report or {}).get("window_utilization"))
            doc["devq"] = {"pending": len(self.devq),
                           "window_utilization": row}
            doc["status"] = _worst([doc["status"], row["status"]])
            if self.slo is not None:
                doc["slo"]["objectives"] = (
                    list(doc["slo"]["objectives"]) + [row])
        return doc

    # -- the replog anti-entropy ops (fleet/replog.py) -----------------
    def _handle_replog(self, conn: socket.socket, op: str,
                       req: dict) -> None:
        """The segment-exchange surface a fleet router reconciles
        through: ``digests`` advertises what this node holds (and has
        absorbed — a peer must not think compaction lost anything),
        ``pull`` ships whole sealed segments out, ``push`` adopts
        replicated ones — fingerprint-verified, idempotent, and folded
        into the live cache WITHOUT re-banking (each verdict lands on
        this node's disk exactly once)."""
        if self.replog is None:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": "node runs no replicated log "
                                       "(start with replog_dir)"})
            return
        if op == "replog.digests":
            # `absorbed` on the wire = everything covered (absorbed by
            # compaction OR subsumed by row coverage): a peer must not
            # re-offer either kind
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "digests": self.replog.digests(),
                              "absorbed": self.replog.covered(),
                              "active_rows":
                                  self.replog.snapshot()["active_rows"]})
            return
        if op == "replog.covers":
            # the coverage leg of row-level subsumption: the row KEYS
            # of held segments (never the rows), so a peer can decide
            # whether a ship is needed at all — one file read each
            covers = self.replog.covers(
                [str(n) for n in list(req.get("segments") or [])[:64]])
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "covers": covers})
            return
        if op == "replog.subsumed":
            # the decision leg: THIS node's live set says whether it
            # already holds every row of the offered segment — if so
            # the name is recorded as covered and the rows never ship
            name = str(req.get("name") or "")
            fp = str(req.get("fingerprint") or "")
            keys = [str(k) for k in (req.get("keys") or [])]
            held = (name in self.replog.digests()
                    or name in self.replog.covered())
            if held:
                self._send(conn, {"id": req.get("id"), "ok": True,
                                  "subsumed": True, "held": True})
                return
            subsumed = False
            if keys and self.cache.holds_all(keys):
                try:
                    subsumed = self.replog.note_subsumed(name, fp)
                except ValueError as e:
                    self._send(conn, {"id": req.get("id"), "ok": False,
                                      "error": f"{type(e).__name__}: "
                                               f"{e}"[:200]})
                    return
                if subsumed:
                    self.obs.event("replog.subsume", segment=name,
                                   rows=len(keys))
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "subsumed": subsumed})
            return
        if op == "replog.pull":
            segments = []
            for name in list(req.get("segments") or [])[:64]:
                got = self.replog.read_segment(str(name))
                if got is not None:
                    segments.append({"name": str(name),
                                     "fingerprint": got[0],
                                     "lines": got[1]})
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "segments": segments})
            return
        adopted = rows_in = 0
        errors: List[str] = []
        for seg in list(req.get("segments") or []):
            try:
                rows = self.replog.adopt(str(seg.get("name")),
                                         str(seg.get("fingerprint")),
                                         list(seg.get("lines") or []))
            except (ValueError, OSError, AttributeError) as e:
                errors.append(f"{type(e).__name__}: {e}"[:200])
                continue
            if rows:
                adopted += 1
                rows_in += self.cache.adopt_rows(rows)
        self.obs.event("replog.adopt", segments=adopted, rows=rows_in)
        doc = {"id": req.get("id"), "ok": True, "adopted": adopted,
               "rows": rows_in}
        if errors:
            doc["errors"] = errors
        self._send(conn, doc)

    # -- the device-work-queue ops (qsm_tpu/devq) ----------------------
    def _handle_devq(self, conn: socket.socket, op: str,
                     req: dict) -> None:
        """The window-arbitrage surface (docs/WINDOWS.md): ``put`` banks
        fingerprint-keyed work items (dedup by item key — a replayed put
        is a no-op), ``digests``/``pull`` are the queue's anti-entropy
        legs mirroring ``replog.*`` over the devq segment log, and
        ``drain_report`` is how a window host hands a drained window
        back — verdict rows bank into THIS node's cache under their
        originating fingerprints (set-union), drained item keys
        tombstone as done (absorbing), and the report feeds the
        ``window_utilization`` SLO the ``health`` verb reports.  Sent
        with no body, ``drain_report`` reads the last banked report."""
        if self.devq is None:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": "node runs no device-work queue "
                                       "(start with devq_dir)"})
            return
        if op == "devq.put":
            banked = 0
            errors: List[str] = []
            for doc in list(req.get("items") or [])[:64]:
                try:
                    if self.devq.put_doc(dict(doc)):
                        banked += 1
                except (KeyError, ValueError, TypeError) as e:
                    errors.append(f"{type(e).__name__}: {e}"[:200])
            self.obs.event("devq.put", banked=banked,
                           pending=len(self.devq))
            doc = {"id": req.get("id"), "ok": True, "banked": banked,
                   "pending": len(self.devq)}
            if errors:
                doc["errors"] = errors
            self._send(conn, doc)
            return
        if op == "devq.digests":
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "digests": self.devq.digests(),
                              "queue": self.devq.snapshot()})
            return
        if op == "devq.pull":
            segments = []
            for name in list(req.get("segments") or [])[:64]:
                got = self.devq.read_segment(str(name))
                if got is not None:
                    segments.append({"name": str(name),
                                     "fingerprint": got[0],
                                     "lines": got[1]})
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "segments": segments})
            return
        if op == "devq.drain_report":
            # bank + tombstone (write form) or read the last report
            # back (empty body — what `health`/tools poll)
            report = req.get("report")
            rows_in = req.get("rows") or []
            done_in = req.get("done") or []
            if report is None and not rows_in and not done_in:
                self._send(conn, {"id": req.get("id"), "ok": True,
                                  "report": self.devq_report})
                return
            if report is not None:
                self.devq_report = dict(report)
            self.cache.put_many(
                (str(r[0]), int(r[1]), r[2] if len(r) > 2 else None)
                for r in rows_in)
            done = 0
            for key in done_in:
                if self.devq.mark_done(str(key)):
                    done += 1
            self.obs.event("devq.drain_report", rows=len(rows_in),
                           done=done,
                           utilization=(report or {}).get(
                               "window_utilization"))
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "rows": len(rows_in), "done": done,
                              "pending": len(self.devq)})

    def _handle_gossip_peers(self, conn: socket.socket,
                             req: dict) -> None:
        """(Re)configure this node's gossip peer set at runtime — the
        wiring op ``qsm-tpu fleet`` uses after spawned nodes' addresses
        are known.  Idempotent; requires a replog (gossip replicates
        segments, a bankless node has none to exchange)."""
        if self.replog is None:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": "node runs no replicated log "
                                       "(start with replog_dir)"})
            return
        peers = req.get("peers") or []
        if req.get("interval_s") is not None:
            self._gossip_interval = float(req["interval_s"])
        self._make_gossip(peers)
        self.gossip.interval_s = self._gossip_interval
        if not self._stop.is_set():
            # idempotent: also wakes an agent created dormant
            # (interval 0) that this op just gave a real beat
            self.gossip.start()
        self._send(conn, {"id": req.get("id"), "ok": True,
                          "peers": self.gossip.peer_ids(),
                          "interval_s": self.gossip.interval_s})

    # -- the lease service (fleet/lease.py TcpLeaseStore) --------------
    def _handle_lease(self, conn: socket.socket, op: str,
                      req: dict) -> None:
        """The lease-hosting surface: each op runs ONE flock-excluded
        FileLeaseStore transaction on this node's ``lease_path`` —
        the term/expiry semantics routers see over TCP are byte-for-
        byte the single-host semantics, with this host's clock as the
        one authority.  A REFUSED transaction (live foreign term,
        superseded renew, lost flock beat) is an OK response with the
        flag false — only transport failure reads as a lost beat on
        the caller's side, so the two are never conflated."""
        if self.lease_store is None:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "error": "node hosts no lease record "
                                       "(start with lease_path)"})
            return
        holder = str(req.get("holder", ""))
        self.lease_ops += 1
        if op == "lease.read":
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "record": self.lease_store.read()})
            return
        if op == "lease.acquire":
            rec = self.lease_store.acquire(
                holder, ttl_s=float(req.get("ttl_s", 3.0)),
                grace_s=float(req.get("grace_s", 0.0)))
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "acquired": rec is not None,
                              "record": (rec if rec is not None
                                         else self.lease_store.read())})
            return
        if op == "lease.renew":
            rec = self.lease_store.renew(
                holder, int(req.get("term", -1)),
                ttl_s=float(req.get("ttl_s", 3.0)))
            self._send(conn, {"id": req.get("id"), "ok": True,
                              "renewed": rec is not None,
                              "record": rec})
            return
        self.lease_store.release(holder)  # lease.release
        self._send(conn, {"id": req.get("id"), "ok": True,
                          "released": True})

    # -- the check path ------------------------------------------------
    def _handle_check(self, conn: socket.socket, req: dict) -> None:
        from ..models.registry import MODELS

        t_req = time.perf_counter()
        model = req.get("model")
        if model not in MODELS:
            self._send(conn, {"id": req.get("id"), "ok": False,
                            "error": f"unknown model {model!r}; one of "
                                     f"{sorted(MODELS)}"})
            return
        rows_list = req.get("histories")
        if rows_list is None and "history" in req:
            rows_list = [req["history"]]
        if not isinstance(rows_list, list) or not rows_list:
            self._send(conn, {"id": req.get("id"), "ok": False,
                            "error": "request needs a non-empty "
                                     "'histories' (or 'history') array"})
            return
        hists = [rows_to_history(rows) for rows in rows_list]
        spec_kwargs = req.get("spec_kwargs") or {}
        want_witness = bool(req.get("witness"))
        deadline = self.admission.deadline_for(req.get("deadline_s"))
        self.requests += 1
        # the request-scoped trace id: minted HERE at admission (or
        # adopted from the client), propagated through every stage and
        # carried by every response — docs/OBSERVABILITY.md.  A router
        # sub-request also carries `parent` (its node.dispatch span),
        # so in the COLLECTED fleet log this node's whole subtree pins
        # under the router edge that caused it — causality by edge,
        # never by cross-process wall clocks.
        trace = str(req.get("trace") or "") or new_trace_id()
        root = ""
        if self.obs.on:
            root = new_span_id()
            self.obs.tracer.emit("request", trace=trace, span=root,
                                 parent=str(req.get("parent") or ""),
                                 model=model, lanes=len(hists),
                                 witness=want_witness)

        # engine construction/validation BEFORE admission: bad
        # spec_kwargs (or a failing device build) must never reserve
        # lanes it cannot use
        entry = self._engine_for(model, spec_kwargs)
        spec_key = self._spec_key(model, spec_kwargs)
        if self.devq is not None and len(hists) >= self.max_lanes:
            # the admission seam (qsm_tpu/devq): an oversize corpus is
            # exactly what a device window pays for — bank a copy for
            # the drain scheduler (side channel; THIS request still
            # serves on the host path right now, shed or not)
            from ..devq import bank_histories

            bank_histories(entry.spec, hists, plane="check",
                           queue=self.devq)
        if not self.admission.try_admit(len(hists)):
            self._respond(conn, self._shed(req, "queue full", trace,
                                           root), trace, root, t_req)
            return
        self.obs.event("admission.admit", trace=trace, parent=root,
                       lanes=len(hists),
                       deadline_s=round(deadline - time.monotonic(), 3))
        pending = _PendingRequest(len(hists))
        self.histories += len(hists)
        # exactly-once release per admitted lane, whatever path resolves
        # it (cache hit, witness search, batch dispatch, mid-request
        # shed, or an unexpected exception below) — a leaked slot would
        # permanently shrink queue_depth until the server sheds all
        # traffic
        released = [False] * len(hists)
        rel_lock = threading.Lock()

        def release_lane(i: int) -> None:
            with rel_lock:
                if released[i]:
                    return
                released[i] = True
            self.admission.release(1)

        try:
            self._check_admitted(conn, req, entry, spec_key, hists,
                                 pending, deadline, want_witness,
                                 release_lane, t_req, model, trace,
                                 root)
        except Exception as e:
            # the request dies, its slots must not: lanes the batcher
            # owns release via their resolvers; everything else here
            pending.dead = True
            for j in range(len(hists)):
                if not pending.lane_submitted[j]:
                    release_lane(j)
            self._respond(conn, {"id": req.get("id"), "ok": False,
                                 "trace": trace,
                                 "error": f"{type(e).__name__}: {e}"},
                          trace, root, t_req, status="error")

    def _check_admitted(self, conn, req, entry, spec_key, hists, pending,
                        deadline, want_witness, release_lane, t_req,
                        model, trace, root) -> None:
        for i, h in enumerate(hists):
            key = fingerprint_key(entry.spec, h)
            lane_span = self.obs.event("lane", trace=trace, parent=root,
                                       index=i, ops=len(h))
            e = self.cache.get(key)
            if e is not None and not (want_witness and e.witness is None
                                      and e.verdict
                                      == int(Verdict.LINEARIZABLE)):
                # O(1) banked verdict (and witness when asked for one —
                # a hit missing a needed witness falls through to the
                # one-search witness path below)
                self.obs.event("cache.hit", trace=trace,
                               parent=lane_span,
                               verdict=VERDICT_NAMES[e.verdict])
                pending.resolve(i, e.verdict, cached=True,
                                witness=e.witness)
                release_lane(i)
            elif want_witness:
                # ONE host-oracle search serves verdict AND witness
                # (the replay/check CLI rule); bounded by the request
                # deadline between items.  A history whose split pays
                # (smaller buckets) takes the DECOMPOSED witness path:
                # per-key searches + a stitched whole-history witness
                # that verify_witness replays identically (ops/pcomp.py)
                if time.monotonic() >= deadline:
                    pending.dead = True
                    self.admission.shed_late()
                    self._release_unsubmitted(pending, release_lane)
                    self._respond(conn, self._shed(req, "deadline",
                                                   trace, root),
                                  trace, root, t_req)
                    return
                with self.obs.span("witness", trace=trace,
                                   parent=lane_span) as wsp:
                    if self._split_pays(entry, h):
                        with self._pcomp_lock:
                            if entry.pcomp is None:
                                from ..ops.pcomp import PComp

                                entry.pcomp = PComp(entry.spec)
                        before = entry.pcomp.subs_produced
                        v, w = entry.pcomp.check_witness(entry.spec, h)
                        with self._pcomp_lock:
                            self.pcomp_split += 1
                            # witness traffic's sub-histories count
                            # too, or stats() would claim histories
                            # split into zero sub-lanes
                            subs = entry.pcomp.subs_produced - before
                            self.pcomp_subs += subs
                        wsp.add(pcomp_subs=subs)
                    else:
                        v, w = entry.oracle.check_witness(entry.spec, h)
                    wsp.add(verdict=VERDICT_NAMES[int(v)])
                self.cache.put(key, int(v), w)
                self.obs.event("cache.put", trace=trace,
                               parent=lane_span,
                               verdict=VERDICT_NAMES[int(v)])
                pending.resolve(i, int(v), witness=w)
                release_lane(i)
            elif self._split_pays(entry, h):
                if not self._submit_split(entry, h, key, pending, i,
                                          deadline, release_lane,
                                          trace=trace,
                                          parent=lane_span):
                    pending.dead = True
                    self._release_unsubmitted(pending, release_lane)
                    self._respond(conn, self._shed(req, "batcher full",
                                                   trace, root),
                                  trace, root, t_req)
                    return
            else:
                lane = Lane(key=key, history=h, deadline=deadline,
                            resolve=self._lane_resolver(pending, i,
                                                        release_lane),
                            trace=trace, span=lane_span)
                pending.lane_submitted[i] = True
                if not self.batcher.submit(spec_key, lane):
                    pending.lane_submitted[i] = False
                    pending.dead = True
                    self._release_unsubmitted(pending, release_lane)
                    self._respond(conn, self._shed(req, "batcher full",
                                                   trace, root),
                                  trace, root, t_req)
                    return
        if not pending.wait(deadline - time.monotonic()):
            # the deadline fired with lanes still in flight: SHED —
            # never a partial or late answer.  In-flight lanes complete
            # into the cache (their admission slots release there).
            pending.dead = True
            self.admission.shed_late()
            self._respond(conn, self._shed(req, "deadline", trace,
                                           root), trace, root, t_req)
            return
        verdicts = [int(v) for v in pending.verdicts]
        doc = {
            "id": req.get("id"), "ok": True,
            "model": model, "trace": trace,
            "verdicts": [VERDICT_NAMES[v] for v in verdicts],
            "cached": list(pending.cached),
            "violations": sum(v == int(Verdict.VIOLATION)
                              for v in verdicts),
            "undecided": sum(v == int(Verdict.BUDGET_EXCEEDED)
                             for v in verdicts),
            "batches": list(pending.batches),
            "plan_why": entry.plan_why,
            "resilience": collect_resilience(entry.engine),
            "seconds": round(time.perf_counter() - t_req, 4),
        }
        if want_witness:
            doc["witnesses"] = [
                [list(p) for p in w] if w is not None else None
                for w in pending.witnesses]
        self._respond(conn, doc, trace, root, t_req)

    def _respond(self, conn, doc: dict, trace: str, root: str,
                 t_req: float, status: str = "ok",
                 verb: str = "check") -> None:
        """The check path's ONE terminal: closes the request's causal
        tree with a ``response`` event and feeds the request-latency
        histogram (labeled by verb — the SLO plane's sliding windows
        read the same series), then sends."""
        dt = time.perf_counter() - t_req
        if self.obs.on:
            self.obs.tracer.emit(
                "response", trace=trace, parent=root,
                ms=round(dt * 1000.0, 3), status=status,
                shed=bool(doc.get("shed")),
                violations=doc.get("violations"),
                cached=sum(bool(c) for c in doc.get("cached", ())))
        self._m_request_s.observe(dt, verb=verb)
        self._send(conn, doc)

    # -- P-compositional split lanes (ops/pcomp.py) --------------------
    def _split_pays(self, entry: _EngineEntry, h: History) -> bool:
        """Decompose iff the spec's projection validated at engine build
        AND this history's per-key sub-histories land in a strictly
        smaller compile bucket (the planner's gate, per history)."""
        if entry.proj is None:
            return False
        from ..ops.pcomp import split_gain

        try:
            return split_gain(entry.spec, h)
        except ValueError:
            return False  # runtime non-totality: refuse, never split

    def _submit_split(self, entry: _EngineEntry, h: History,
                      whole_key: str, pending: _PendingRequest, i: int,
                      deadline: float, release_lane,
                      trace: str = "", parent: str = "") -> bool:
        """Fan one request history out as per-key sub-lanes riding the
        PROJECTED spec's micro-batch group; verdicts recombine through a
        :class:`_SubJoin` whose completion banks the whole-history key
        and resolves lane ``i``.  Each sub-history has its own cache row
        (fingerprint under the projected spec), so a later history that
        changes one key re-checks that key only.  False = batcher full
        (the caller sheds; in-flight sub-lanes drain into the join,
        which still completes and releases the admission slot).  The
        request's ``trace`` rides every sub-lane: the causal tree shows
        the split, each sub-lane's micro-batch, and the recombine."""
        from ..ops.pcomp import split_history

        subs = split_history(entry.spec, h)
        if not subs:
            # empty history: vacuously linearizable (the gate already
            # refuses these, but a zero-lane join would never complete)
            self.cache.put(whole_key, int(Verdict.LINEARIZABLE))
            pending.resolve(i, int(Verdict.LINEARIZABLE))
            release_lane(i)
            return True
        with self._pcomp_lock:
            self.pcomp_split += 1
            self.pcomp_subs += len(subs)
        if self.devq is not None:
            # the pcomp seam (qsm_tpu/devq): the validated per-key
            # sub-lane group banks under the PROJECTED spec — the same
            # fingerprints the sub-lane cache rows use below, so a
            # window drain pre-answers this exact split next time
            from ..devq import bank_histories

            bank_histories(entry.proj, [subs[k] for k in sorted(subs)],
                           plane="pcomp", queue=self.devq)
        split_span = self.obs.event("pcomp.split", trace=trace,
                                    parent=parent, keys=len(subs),
                                    ops=len(h))

        def finish(worst: int, batch: Optional[dict]) -> None:
            banked = worst in (int(Verdict.VIOLATION),
                               int(Verdict.LINEARIZABLE))
            if banked:
                # the combined verdict banks under the WHOLE history's
                # key too: exact duplicates stay O(1) hits
                self.cache.put(whole_key, worst)
            self.obs.event("pcomp.recombine", trace=trace,
                           parent=split_span, subs=len(subs),
                           verdict=VERDICT_NAMES[worst], banked=banked)
            pending.resolve(i, worst, batch=batch)
            release_lane(i)

        join = _SubJoin(len(subs), finish)
        # the join owns the slot release from here on — including the
        # shed path, where aborted sub-lanes feed BUDGET_EXCEEDED
        pending.lane_submitted[i] = True
        dispatched = 0
        # sorted: deterministic sub-lane order (cache/bench replayability)
        for key in sorted(subs):
            sub_h = subs[key]
            skey = fingerprint_key(entry.proj, sub_h)
            sub_span = self.obs.event("sublane", trace=trace,
                                      parent=split_span, key=key,
                                      ops=len(sub_h))
            e = self.cache.get(skey)
            if e is not None:
                with self._pcomp_lock:
                    self.pcomp_sub_hits += 1
                self.obs.event("cache.hit", trace=trace,
                               parent=sub_span,
                               verdict=VERDICT_NAMES[e.verdict])
                dispatched += 1
                join.feed(e.verdict)
                continue
            lane = Lane(key=skey, history=sub_h, deadline=deadline,
                        resolve=join.resolver(), pcomp=True,
                        trace=trace, span=sub_span)
            if not self.batcher.submit(entry.proj_group_key, lane):
                join.abort(len(subs) - dispatched)
                return False
            dispatched += 1
        return True

    # -- monitor sessions (qsm_tpu/monitor, docs/MONITOR.md) -----------
    def _handle_session(self, conn: socket.socket, op: str,
                        req: dict) -> None:
        """The streaming verbs: ``session.open`` binds a spec and
        returns a session id (idempotent for a live id — failover
        replay and reconnects resume), ``session.append`` applies
        events and answers the CURRENT verdict — carrying the ``flip``
        payload (1-minimal shrunk repro + certificate) on the append
        that made a violation decidable — and ``session.close`` flushes,
        decides once more, optionally serves the whole-stream witness
        through the exact check-path machinery, and frees the session.
        Admission/SHED semantics match ``check``: a full queue or a
        session/event cap answers SHED, never a wrong or partial
        verdict; engine time is bounded by the frontier node budget and
        the request deadline."""
        from ..monitor import SessionError, SessionLimit

        t_req = time.perf_counter()
        trace = str(req.get("trace") or "") or new_trace_id()
        root = ""
        if self.obs.on:
            root = new_span_id()
            self.obs.tracer.emit("request", trace=trace, span=root,
                                 parent=str(req.get("parent") or ""),
                                 op=op, session=req.get("session"))
        self.requests += 1
        if op == "session.open":
            self._session_open(conn, req, trace, root, t_req)
            return
        sid = str(req.get("session") or "")
        s = self.monitor.get(sid)
        if s is None:
            # machine-readable: a router reads `unknown_session` as
            # "this node restarted and lost the live object" and
            # replays the journal (fleet/router.py _route_session) —
            # the banked decided prefixes make the replay cheap
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "session": sid, "trace": trace,
                              "unknown_session": True,
                              "error": f"unknown session {sid!r} "
                                       "(open one first; a restarted "
                                       "node resumes by re-open + "
                                       "replay)"})
            return
        if not self.admission.try_admit(1):
            self._respond(conn, {**self._shed(req, "queue full", trace,
                                              root), "session": sid},
                          trace, root, t_req, verb='session')
            return
        try:
            deadline = self.admission.deadline_for(req.get("deadline_s"))
            with s.lock:
                if op == "session.append":
                    doc = self._session_append(s, req, deadline, trace,
                                               root)
                else:
                    doc = self._session_close(s, req, deadline, trace,
                                              root)
        except SessionLimit as e:
            self.admission.release(1)
            self._respond(conn, {**self._shed(req, str(e), trace, root),
                                 "session": sid}, trace, root,
                          t_req, verb='session')
            return
        except Exception:
            self.admission.release(1)
            raise
        self.admission.release(1)
        doc["seconds"] = round(time.perf_counter() - t_req, 4)
        self._respond(conn, doc, trace, root, t_req, verb='session')

    def _session_open(self, conn, req: dict, trace: str, root: str,
                      t_req: float) -> None:
        from ..models.registry import MODELS
        from ..monitor import SessionLimit

        model = req.get("model")
        if model not in MODELS:
            self._send(conn, {"id": req.get("id"), "ok": False,
                              "trace": trace,
                              "error": f"unknown model {model!r}; one "
                                       f"of {sorted(MODELS)}"})
            return
        spec_kwargs = req.get("spec_kwargs") or {}
        # engine/projection validation BEFORE admission, like check
        entry = self._engine_for(model, spec_kwargs)
        if not self.admission.try_admit(1):
            self._respond(conn, self._shed(req, "queue full", trace,
                                           root), trace, root,
                          t_req, verb='session')
            return
        try:
            sid = req.get("session")
            try:
                s, resumed = self.monitor.open(
                    str(sid) if sid is not None else None, entry.spec,
                    entry.proj, trace=trace)
            except SessionLimit as e:
                self._respond(conn, self._shed(req, str(e), trace,
                                               root), trace, root,
                              t_req, verb='session')
                return
            with s.lock:
                s.model, s.spec_kwargs = model, spec_kwargs
                verdict = s.decide()
            self.obs.event("session.open", trace=trace, parent=root,
                           session=s.sid, model=model, resumed=resumed)
            self._respond(conn, {
                "id": req.get("id"), "ok": True, "session": s.sid,
                "model": model, "resumed": resumed, "seq": s.seq,
                "per_key": s.proj is not None,
                "verdict": VERDICT_NAMES[verdict], "trace": trace,
                "seconds": round(time.perf_counter() - t_req, 4),
            }, trace, root, t_req, verb='session')
        finally:
            self.admission.release(1)

    def _session_append(self, s: "MonitorSession", req: dict,
                        deadline: float,
                        trace: str, root: str) -> dict:
        events = req.get("events")
        if not isinstance(events, list) or not events:
            raise ValueError("session.append needs a non-empty "
                             "'events' array")
        applied = s.append(events, seq=req.get("seq"))
        already_pushed = s.flip_pushed
        verdict = s.decide()
        c = s.counters()
        self.obs.event("session.append", trace=trace, parent=root,
                       session=s.sid, events=applied,
                       verdict=VERDICT_NAMES[verdict])
        doc = {"id": req.get("id"), "ok": True, "session": s.sid,
               "seq": s.seq, "applied": applied,
               "verdict": VERDICT_NAMES[verdict], "trace": trace,
               "decided_prefix": c["committed_ops"],
               "window_ops": c["window_ops"]}
        if s.flipped and not already_pushed:
            # the flip: pushed on the append that made the violation
            # decidable (a verdict only changes when an event arrives,
            # so this response IS the earliest possible push), carrying
            # the shrink-plane-minimized repro + its certificate.  The
            # session RLock is already held by the dispatching caller;
            # re-acquiring keeps the guard visible at the write.
            with s.lock:
                s.flip_pushed = True
            self.monitor.note_flip()
            doc["flip"] = self._session_flip(s, deadline, trace, root)
        elif s.flipped:
            doc["flipped"] = True  # terminal; repro already delivered
        return doc

    def _session_flip(self, s: "MonitorSession", deadline: float,
                      trace: str,
                      root: str) -> dict:
        """Auto-minimize the violating stream through the PR 10 shrink
        plane (frontier candidates ride the shared micro-batcher and
        bank in the shared cache) and certify the result; the
        ``session.flip`` event is a flight-recorder dump trigger, so a
        production flip leaves an artifact naming the session's trace
        id even if no client ever reads the response."""
        from ..shrink.shrinker import Shrinker, minimality_certificate

        entry = self._engine_for(s.model, s.spec_kwargs)
        spec_key = self._spec_key(s.model, s.spec_kwargs)
        h = rows_to_history([list(r) for r in (s.flip_rows or s.rows)])

        def decide(hists):
            return self._decide_candidates(entry, spec_key, hists,
                                           deadline, trace=trace,
                                           parent=root)

        shrinker = Shrinker(entry.spec, decide, bank=self.cache,
                            bank_put=False, deadline=deadline)
        res = shrinker.run(h)
        flip = {"verdict": VERDICT_NAMES[int(res.verdict)],
                "initial_ops": res.initial_ops,
                "final_ops": res.final_ops,
                "rounds": res.rounds,
                "one_minimal": res.one_minimal,
                "complete": res.complete,
                "repro": history_to_rows(res.history),
                "why": res.why}
        if res.ok and res.complete:
            cert = minimality_certificate(entry.spec, res.history,
                                          deadline=deadline)
            if cert is not None:
                flip["certificate"] = cert
        with self._shrink_lock:
            self.shrink_rounds += res.rounds
            self.shrink_lanes += res.lanes_checked
            self.shrink_memo_hits += res.memo_hits
        self.obs.event("session.flip", trace=trace, parent=root,
                       session=s.sid, model=s.model,
                       ops=len(s.rows), final_ops=res.final_ops,
                       traces=[trace])
        return flip

    def _session_close(self, s: "MonitorSession", req: dict,
                       deadline: float,
                       trace: str, root: str) -> dict:
        verdict = s.close()
        doc = {"id": req.get("id"), "ok": True, "session": s.sid,
               "seq": s.seq, "verdict": VERDICT_NAMES[verdict],
               "trace": trace, "flipped": s.flipped,
               **{k: v for k, v in s.counters().items()
                  if k != "frontiers"}}
        if bool(req.get("witness")) and s.rows:
            # the whole-stream witness rides the EXACT check-path
            # machinery (cache row under the whole-history fingerprint,
            # decomposed stitching when the split pays), so a streamed
            # session's witness is bit-identical to `check --witness`
            # of the same history (tests/test_monitor.py parity pin)
            entry = self._engine_for(s.model, s.spec_kwargs)
            h = s.history()
            key = fingerprint_key(entry.spec, h)
            e = self.cache.get(key)
            if e is not None and not (e.witness is None and e.verdict
                                      == int(Verdict.LINEARIZABLE)):
                v, w = e.verdict, e.witness
            elif self._split_pays(entry, h):
                with self._pcomp_lock:
                    if entry.pcomp is None:
                        from ..ops.pcomp import PComp

                        entry.pcomp = PComp(entry.spec)
                v, w = entry.pcomp.check_witness(entry.spec, h)
                self.cache.put(key, int(v), w)
            else:
                v, w = entry.oracle.check_witness(entry.spec, h)
                self.cache.put(key, int(v), w)
            doc["verdict"] = VERDICT_NAMES[int(v)]
            doc["witness"] = ([list(p) for p in w]
                              if w is not None else None)
        self.obs.event("session.close", trace=trace, parent=root,
                       session=s.sid, events=s.seq,
                       verdict=doc["verdict"])
        self.monitor.close(s.sid)
        return doc

    # -- the shrink verb (qsm_tpu/shrink, docs/SHRINK.md) --------------
    def _handle_shrink(self, conn: socket.socket, req: dict) -> None:
        """Minimize one failing history.  Admission/deadline/SHED
        semantics are the check path's: unknown model / bad rows answer
        an error, a full queue answers SHED, a deadline that fires
        BEFORE the first frontier round answers SHED — but a deadline
        (or full batcher) that fires MID-shrink returns the best
        history found so far with ``complete: false`` and an honest
        ``why`` (a partial minimization is still a violation; throwing
        it away would waste every lane already paid for).  Frontier
        candidates ride the shared micro-batcher and bank in the
        verdict cache; the minimized result banks under the ORIGINAL
        history's fingerprint."""
        from ..models.registry import MODELS
        from ..shrink.shrinker import Shrinker, minimality_certificate

        t_req = time.perf_counter()
        model = req.get("model")
        if model not in MODELS:
            self._send(conn, {"id": req.get("id"), "ok": False,
                            "error": f"unknown model {model!r}; one of "
                                     f"{sorted(MODELS)}"})
            return
        rows = req.get("history")
        if rows is None and isinstance(req.get("histories"), list) \
                and len(req["histories"]) == 1:
            rows = req["histories"][0]
        if not isinstance(rows, list) or not rows:
            self._send(conn, {"id": req.get("id"), "ok": False,
                            "error": "shrink needs ONE non-empty "
                                     "'history' rows array"})
            return
        h = rows_to_history(rows)
        spec_kwargs = req.get("spec_kwargs") or {}
        want_cert = bool(req.get("certificate"))
        deadline = self.admission.deadline_for(req.get("deadline_s"))
        self.requests += 1
        # shrink requests are traced like check requests: one root,
        # one `shrink.round` event per greedy frontier round, batch
        # events for the candidate lanes parented under their round
        trace = str(req.get("trace") or "") or new_trace_id()
        root = ""
        if self.obs.on:
            root = new_span_id()
            self.obs.tracer.emit("request", trace=trace, span=root,
                                 parent=str(req.get("parent") or ""),
                                 model=model, op="shrink", ops=len(h))
        entry = self._engine_for(model, spec_kwargs)
        spec_key = self._spec_key(model, spec_kwargs)
        whole_key = fingerprint_key(entry.spec, h)
        with self._shrink_lock:
            self.shrink_requests += 1
            banked = self._shrink_bank.get(whole_key)
            if banked is not None:
                self._shrink_bank.move_to_end(whole_key)
        if banked is not None and not (want_cert
                                       and "certificate" not in banked):
            with self._shrink_lock:
                self.shrink_bank_hits += 1
            doc = {**banked, "id": req.get("id"), "cached": True,
                   "trace": trace,
                   "seconds": round(time.perf_counter() - t_req, 4)}
            if not want_cert:
                # a banked certificate (O(n²) witness payload) must not
                # inflate a duplicate answer that never asked for one
                doc.pop("certificate", None)
            self._respond(conn, doc, trace, root, t_req, verb='shrink')
            return
        if not self.admission.try_admit(1):
            self._respond(conn, self._shed(req, "queue full", trace,
                                           root), trace, root,
                          t_req, verb='shrink')
            return
        try:
            if time.monotonic() >= deadline:
                self.admission.shed_late()
                self._respond(conn, self._shed(req, "deadline", trace,
                                               root), trace, root,
                              t_req, verb='shrink')
                return
            self.obs.event("admission.admit", trace=trace, parent=root,
                           lanes=1)

            def decide(hists):
                rnd = self.obs.event("shrink.round", trace=trace,
                                     parent=root, lanes=len(hists))
                return self._decide_candidates(entry, spec_key, hists,
                                               deadline, trace=trace,
                                               parent=rnd)

            # bank = the verdict cache (candidates the check path — or
            # an earlier shrink — already decided are memo hits, and
            # the dispatch path banks every new verdict itself, so
            # bank_put stays off: no duplicate rows)
            shrinker = Shrinker(entry.spec, decide, bank=self.cache,
                                bank_put=False, deadline=deadline)
            res = shrinker.run(h)
            if res.ok and res.complete and want_cert:
                # a FRESH oracle per request: engines are stateful and
                # not thread-safe (_EngineEntry docstring), and this
                # witness loop runs on the connection thread while the
                # dispatcher may be driving entry.oracle — sharing it
                # here would race the memo and corrupt stats() counters
                res.certificate = minimality_certificate(
                    entry.spec, res.history, deadline=deadline)
            with self._shrink_lock:
                self.shrink_rounds += res.rounds
                self.shrink_lanes += res.lanes_checked
                self.shrink_memo_hits += res.memo_hits
            doc = {
                "id": req.get("id"), "ok": True, "model": model,
                "trace": trace,
                "verdict": VERDICT_NAMES[int(res.verdict)],
                "initial_ops": res.initial_ops,
                "final_ops": res.final_ops,
                "ratio": round(res.ratio, 3),
                "rounds": res.rounds,
                "engine_calls": res.engine_calls,
                "lanes": res.lanes_checked,
                "memo_hits": res.memo_hits,
                "complete": res.complete,
                "one_minimal": res.one_minimal,
                "undecided_neighbors": res.undecided_neighbors,
                "history": history_to_rows(res.history),
                "why": res.why,
                "plan_why": entry.plan_why,
            }
            if res.certificate is not None:
                doc["certificate"] = res.certificate
            if res.ok and res.complete:
                # minimized result banked under the ORIGINAL history's
                # fingerprint: the duplicate-shrink answer is O(1)
                with self._shrink_lock:
                    self._shrink_bank[whole_key] = dict(doc)
                    self._shrink_bank.move_to_end(whole_key)
                    while len(self._shrink_bank) > self.shrink_bank_entries:
                        self._shrink_bank.popitem(last=False)
            doc["seconds"] = round(time.perf_counter() - t_req, 4)
            self._respond(conn, doc, trace, root, t_req, verb='shrink')
        finally:
            self.admission.release(1)

    def _decide_candidates(self, entry: _EngineEntry, spec_key: str,
                           hists, deadline: float, trace: str = "",
                           parent: str = ""):
        """Decide shrink-frontier candidates through the SHARED lanes:
        each candidate is one micro-batch lane (split into per-key
        sub-lanes when that pays, exactly like paying check traffic),
        banked by the dispatch path.  ``None`` = shed (full batcher or
        deadline) — the shrinker stops with best-so-far.  Candidate
        lanes hold no admission slots: the shrink REQUEST holds one,
        and the batcher's bounded queue is the frontier's backstop."""

        def _noop(_i: int) -> None:
            return None

        pending = _PendingRequest(len(hists))
        for i, h in enumerate(hists):
            key = fingerprint_key(entry.spec, h)
            if self._split_pays(entry, h):
                if not self._submit_split(entry, h, key, pending, i,
                                          deadline, _noop, trace=trace,
                                          parent=parent):
                    pending.dead = True
                    return None
            else:
                # candidate lanes parent their batch events directly
                # under the frontier round (one span per candidate
                # would flood the log at 512 lanes/round)
                lane = Lane(key=key, history=h, deadline=deadline,
                            resolve=self._lane_resolver(pending, i,
                                                        _noop),
                            trace=trace, span=parent)
                pending.lane_submitted[i] = True
                if not self.batcher.submit(spec_key, lane):
                    pending.lane_submitted[i] = False
                    pending.dead = True
                    return None
        if not pending.wait(deadline - time.monotonic()):
            pending.dead = True
            return None
        return [int(v) for v in pending.verdicts]

    @staticmethod
    def _lane_resolver(pending: _PendingRequest, i: int, release_lane):
        def _resolve(verdict: int, batch: dict) -> None:
            pending.resolve(i, verdict, batch=batch)
            release_lane(i)

        return _resolve

    @staticmethod
    def _release_unsubmitted(pending: _PendingRequest,
                             release_lane) -> None:
        """Mid-request shed: slots of lanes the batcher does NOT own
        (submitted lanes release via their resolvers on dispatch)."""
        for j in range(len(pending.verdicts)):
            if not pending.lane_submitted[j]:
                release_lane(j)
                pending.resolve(j, int(Verdict.BUDGET_EXCEEDED))

    def _shed(self, req: dict, reason: str, trace: str = "",
              parent: str = "") -> dict:
        # the admission layer builds the payload so SHED responses gain
        # the pool-state block when a worker pool serves this plane —
        # plus the request's trace id and (when a dump fired) the
        # flight-recorder artifact path, so a shed client hands the
        # operator something actionable instead of a bare SHED
        self.obs.event("admission.shed", trace=trace, parent=parent,
                       reason=reason)
        # a SHED storm (many sheds in a short window) is itself a
        # flight-recorder trigger: "the server shed all night" becomes
        # one artifact, not a grep
        self.obs.note_shed()
        return self.admission.shed_doc(req.get("id"), reason,
                                       trace=trace or None,
                                       flight=self.obs.flight_path())

    # -- batch dispatch (the `serve` fault site / the worker pool) -----
    def _dispatch(self, spec_key: str, lanes: List[Lane],
                  why: dict) -> None:
        model, spec_kwargs = json.loads(spec_key)
        entry = self._engine_for(model, spec_kwargs)
        hists = [lane.history for lane in lanes]
        from ..core.history import bucket_for

        width = why["width"]
        why = {**why, "model": model,
               "bucket": bucket_for(max((len(h) for h in hists),
                                        default=1))}
        verdicts = None
        if self.pool is not None:
            traces = sorted({lane.trace for lane in lanes if lane.trace})
            verdicts, why = self._dispatch_pool(spec_key, model,
                                                spec_kwargs, hists,
                                                width, why, traces)
        if verdicts is None:
            # no pool, a quarantined spec, or a pool that lost every
            # healthy worker for this batch: the supervisor's own host
            # cpp→memo ladder is the last resort — exact, in-process
            verdicts, why = self._dispatch_host(entry, hists, width, why)
        # engine-relative BUDGET_EXCEEDED resolves via the witness
        # oracle (the property layer's rule) unless the engine IS that
        # ladder — re-running an identical search only repeats itself
        # (pool workers run the same auto ladder, so pooled verdicts
        # follow the same rule)
        todo = [i for i, v in enumerate(verdicts)
                if v == int(Verdict.BUDGET_EXCEEDED)]
        if todo and self.engine_kind != "auto":
            sub = entry.oracle.check_histories(
                entry.spec, [hists[i] for i in todo])
            for i, v in zip(todo, sub):
                verdicts[i] = int(v)
                self.budget_resolved += 1
        # one bank flush for the whole batch (put_many), then resolve —
        # banking is SUPERVISOR-ONLY by design: a SIGKILLed worker can
        # never leave a torn or wrong bank behind
        self.cache.put_many((lane.key, int(v), None)
                            for lane, v in zip(lanes, verdicts))
        if self.obs.on:
            # the batch lands in every member request's causal tree:
            # one `batch` event per traced lane (flush reason + worker
            # id — "which worker ran which micro-batch and why it
            # flushed"), one `cache.put` per banked verdict, and ONE
            # component-level `serve.dispatch` event carrying the
            # batch's compact SearchStats record (the span<->stats
            # bridge: the flight ring shows recent dispatches WITH
            # their cost records)
            worker = why.get("worker", "in-process")
            # counted LOCALLY, not as a global-counter delta: concurrent
            # dispatcher/connection threads emit through the same
            # tracer, and a delta would book their events to this batch
            n_emitted = 0
            for lane, v in zip(lanes, verdicts):
                if not lane.trace:
                    continue
                self.obs.event("batch", trace=lane.trace,
                               parent=lane.span, batch=why["batch"],
                               flush=why["flush"], lanes=why["lanes"],
                               width=width, worker=worker, model=model)
                n_emitted += 1
                if int(v) in (int(Verdict.VIOLATION),
                              int(Verdict.LINEARIZABLE)):
                    self.obs.event("cache.put", trace=lane.trace,
                                   parent=lane.span,
                                   verdict=VERDICT_NAMES[int(v)])
                    n_emitted += 1
            self.obs.event("serve.dispatch", batch=why["batch"],
                           flush=why["flush"], lanes=why["lanes"],
                           worker=worker, model=model,
                           search=why.get("search"))
            n_emitted += 1
            if why.get("search") is not None:
                # the other bridge direction: the batch's own cost
                # record says how many trace events it emitted
                # (SearchStats.obs_events, compact key "obe")
                why["search"]["obe"] = (why["search"].get("obe", 0)
                                        + n_emitted)
        for lane, v in zip(lanes, verdicts):
            lane.resolve(int(v), why)

    def _dispatch_pool(self, spec_key: str, model: str, spec_kwargs,
                       hists, width: int, why: dict, traces=None):
        """One micro-batch on the worker pool; ``(None, why)`` when the
        pool cannot decide it and the host path must.  ``traces`` (the
        batch's request trace ids) ride the worker frame and the pool's
        dispatch/shed events — a SIGKILLed worker's flight dump names
        the requests it took down."""
        from .protocol import history_to_rows

        pooled = self.pool.dispatch(
            spec_key, model, spec_kwargs,
            [history_to_rows(h) for h in hists], width, traces=traces)
        if pooled is None:
            return None, {**why, "pool": "in-process"}
        why = {**why, "worker": pooled.get("wid")}
        wf = int(pooled.get("batch_worker_faults", 0))
        if wf:
            why["worker_faults"] = wf
        search = pooled.get("search")
        if search is not None:
            # worker faults ride the batch's own cost record: a batch
            # that survived a worker loss must say so (SearchStats
            # worker_faults, compact key "wf")
            why["search"] = {**search, "wf": search.get("wf", 0) + wf}
        return np.asarray(pooled["verdicts"]), why

    def _dispatch_host(self, entry: _EngineEntry, hists, width: int,
                       why: dict):
        """The in-process dispatch (the `serve` fault site), serialized
        per entry — see _EngineEntry.dispatch_lock."""
        with entry.dispatch_lock:
            return self._dispatch_host_locked(entry, hists, width, why)

    def _dispatch_host_locked(self, entry: _EngineEntry, hists,
                              width: int, why: dict):
        padded = hists + [History([])] * (width - len(hists))
        st0 = collect_search_stats(entry.engine)

        def work():
            # the CPU-testable request-dispatch fault site
            # (resilience/faults.py): QSM_TPU_FAULTS=hang:serve wedges
            # here and the watchdog abandons it; raise:serve raises
            inject("serve")
            return entry.engine.check_histories(entry.spec, padded)

        try:
            verdicts = np.asarray(
                watchdog(work, self.policy.timeout_s,
                         label="serve.dispatch"))[:len(hists)]
        except device_error_types() as e:
            # server-level degradation: the warm engine (failover
            # ladder included) is gone for this batch — re-dispatch on
            # a dedicated emergency host ladder so the SERVER stays up
            # with exact verdicts, and count it
            self.serve_faults += 1
            self.obs.event("serve.degrade", error=type(e).__name__,
                           engine=getattr(entry.engine, "name",
                                          type(entry.engine).__name__))
            if entry.emergency is None:
                entry.emergency = host_fallback(entry.spec)
            verdicts = np.asarray(entry.emergency.check_histories(
                entry.spec, padded))[:len(hists)]
            why = {**why, "degraded": f"{type(e).__name__}"}
        st = stats_delta(collect_search_stats(entry.engine), st0)
        if st is not None:
            why = {**why, "search": st.to_compact()}
        return verdicts, why

    # -- observability -------------------------------------------------
    def _pcomp_snapshot(self) -> dict:
        with self._pcomp_lock:
            return {"enabled": self.pcomp_enabled,
                    "split": self.pcomp_split,
                    "sub_lanes": self.pcomp_subs,
                    "sub_cache_hits": self.pcomp_sub_hits}

    def _shrink_snapshot(self) -> dict:
        with self._shrink_lock:
            return {"requests": self.shrink_requests,
                    "rounds": self.shrink_rounds,
                    "lanes": self.shrink_lanes,
                    "memo_hits": self.shrink_memo_hits,
                    "bank_entries": len(self._shrink_bank),
                    "bank_hits": self.shrink_bank_hits}

    def stats(self) -> dict:
        """The aggregate the ``stats`` op (and ``qsm-tpu stats --serve``)
        returns: every counter a capacity decision needs, self-describing
        about batching, caching, shedding and degradation."""
        engines = {}
        for key, entry in list(self._engines.items()):
            st = collect_search_stats(entry.engine)
            engines[key] = {
                "engine": getattr(entry.engine, "name",
                                  type(entry.engine).__name__),
                "resilience": collect_resilience(entry.engine),
                "search": st.to_compact() if st is not None else None,
            }
        return {
            "address": self.address,
            "node": self.node_id,
            "uptime_s": round(time.monotonic() - self._t0, 1),
            "engine_kind": self.engine_kind,
            "mesh_devices": self.mesh_devices,
            "workers": self.n_workers,
            "requests": self.requests,
            "histories": self.histories,
            "serve_faults": self.serve_faults,
            # split-plane accounting: how much traffic decomposed, how
            # many sub-lanes it became, and how many of those the
            # per-sub-history cache rows answered without re-checking
            "pcomp": self._pcomp_snapshot(),
            # shrink-verb accounting: how many minimizations ran, what
            # their frontiers cost in shared lanes, and how much the
            # fingerprint memo + result bank saved (docs/SHRINK.md)
            "shrink": self._shrink_snapshot(),
            # monitor-session accounting (qsm_tpu/monitor): live
            # sessions, events streamed, frontier advances, prefix-bank
            # hits and flips pushed — the session block `qsm-tpu stats`
            # renders and the metrics collector reads (one source)
            "session": self.monitor.snapshot(),
            "worker_faults": (self.pool.worker_faults
                              if self.pool is not None else 0),
            "budget_resolved": self.budget_resolved,
            "admission": self.admission.snapshot(),
            "batcher": self.batcher.snapshot(),
            "cache": self.cache.stats(),
            # lease hosting (fleet/lease.py): transaction count of the
            # lease.* surface — None unless this node hosts the record
            "lease_host": ({"path": self.lease_store.describe(),
                            "ops": self.lease_ops}
                           if self.lease_store is not None else None),
            # node-to-node anti-entropy accounting (fleet/gossip.py):
            # None unless this node gossips
            "gossip": (self.gossip.snapshot()
                       if self.gossip is not None else None),
            # per-worker rows (dispatches, faults, deaths, respawns,
            # quarantines) — what `qsm-tpu stats --serve` aggregates
            "pool": self.pool.snapshot() if self.pool is not None else None,
            "engines": engines,
            # trace/flight accounting (qsm_tpu/obs): span events
            # emitted, flight-ring occupancy, dumps fired + last path
            "obs": self.obs.snapshot(),
            # the SLO plane (obs/slo.py): declared objectives + breach
            # count — None unless --slo configured objectives
            "slo": (self.slo.snapshot()
                    if self.slo is not None else None),
            # device-work queue (qsm_tpu/devq): banked/pending/evicted
            # counts plus the last drained window's headline — None
            # unless --devq-dir configured the queue
            "devq": ({**self.devq.snapshot(),
                      "last_window": (
                          {"window_id": self.devq_report.get(
                              "window_id"),
                           "drained": self.devq_report.get("drained"),
                           "window_utilization": self.devq_report.get(
                               "window_utilization")}
                          if self.devq_report is not None else None)}
                     if self.devq is not None else None),
            # fault-plane hits in THIS process (resilience/faults.py) —
            # zeros/empty unless someone is fault-drilling the server
            "faults": fired_snapshot(),
        }

    def _metric_samples(self):
        """Scrape-time collector (obs/metrics.py): the live-metrics
        surface derives from the SAME counters ``stats()`` reports, so
        the ``/metrics`` endpoint and `qsm-tpu stats` reconcile by
        construction (pinned in tests/test_obs.py)."""
        adm = self.admission.snapshot()
        bat = self.batcher.snapshot()
        cache = self.cache.stats()
        pc = self._pcomp_snapshot()
        sh = self._shrink_snapshot()
        sess = self.monitor.totals()
        c, g = "counter", "gauge"
        out = [
            ("qsm_serve_requests_total", c, "requests received", {},
             float(self.requests)),
            ("qsm_serve_histories_total", c, "history lanes received",
             {}, float(self.histories)),
            ("qsm_serve_faults_total", c, "serve-site degradations",
             {}, float(self.serve_faults)),
            ("qsm_serve_budget_resolved_total", c,
             "engine BUDGET_EXCEEDED resolved by the oracle", {},
             float(self.budget_resolved)),
            ("qsm_admission_queue_depth", g, "admission lane bound",
             {}, float(adm["queue_depth"])),
            ("qsm_admission_in_flight", g, "admitted lanes in flight",
             {}, float(adm["in_flight"])),
            ("qsm_admission_admitted_lanes_total", c, "lanes admitted",
             {}, float(adm["admitted_lanes"])),
            ("qsm_admission_shed_total", c, "requests shed",
             {"reason": "queue_full"}, float(adm["shed_queue"])),
            ("qsm_admission_shed_total", c, "requests shed",
             {"reason": "deadline"}, float(adm["shed_deadline"])),
            ("qsm_batcher_batches_total", c, "micro-batches dispatched",
             {}, float(bat["batches"])),
            ("qsm_batcher_lanes_total", c, "lanes dispatched", {},
             float(bat["lanes"])),
            ("qsm_batcher_occupancy", g, "mean batch occupancy", {},
             float(bat["mean_occupancy"])),
            ("qsm_cache_entries", g, "verdict-cache live entries", {},
             float(cache["entries"])),
            ("qsm_cache_hits_total", c, "verdict-cache hits", {},
             float(cache["hits"])),
            ("qsm_cache_misses_total", c, "verdict-cache misses", {},
             float(cache["misses"])),
            ("qsm_cache_hit_ratio", g, "verdict-cache hit ratio", {},
             float(cache["hit_rate"])),
            ("qsm_pcomp_split_total", c, "request histories decomposed",
             {}, float(pc["split"])),
            ("qsm_pcomp_sublanes_total", c, "per-key sub-lanes produced",
             {}, float(pc["sub_lanes"])),
            ("qsm_shrink_requests_total", c, "shrink requests", {},
             float(sh["requests"])),
            ("qsm_shrink_rounds_total", c, "shrink frontier rounds",
             {}, float(sh["rounds"])),
            ("qsm_session_live", g, "live monitor sessions", {},
             float(sess["sessions_live"])),
            ("qsm_session_events_total", c, "session events streamed",
             {}, float(sess["session_events"])),
            ("qsm_session_frontier_advances_total", c,
             "quiescent cuts committed", {},
             float(sess["frontier_advances"])),
            ("qsm_session_prefix_hits_total", c,
             "cuts resumed from the prefix bank", {},
             float(sess["prefix_hits"])),
            ("qsm_session_flips_pushed_total", c,
             "verdict flips pushed to clients", {},
             float(sess["flips_pushed"])),
            ("qsm_obs_span_events_total", c, "span events emitted", {},
             float(self.obs.tracer.events)),
        ]
        if self.pool is not None:
            pool = self.pool.snapshot()
            out += [
                ("qsm_pool_workers_live", g, "live pool workers", {},
                 float(pool["live"])),
                ("qsm_pool_dispatches_total", c, "pooled micro-batches",
                 {}, float(pool["dispatches"])),
                ("qsm_pool_worker_faults_total", c,
                 "workers shed (crash/wedge/kill)", {},
                 float(pool["worker_faults"])),
                ("qsm_pool_respawns_total", c, "worker respawns", {},
                 float(pool["respawns"])),
                ("qsm_pool_quarantines_total", c, "specs quarantined",
                 {}, float(pool["quarantines"])),
            ]
            out += [
                ("qsm_pool_worker_dispatches_total", c,
                 "per-worker dispatches", {"wid": str(w["wid"])},
                 float(w["dispatches"]))
                for w in pool["workers"]]
        out += [("qsm_fault_hits_total", c, "fault-plane rules fired",
                 {"site": site}, float(n))
                for site, n in sorted(fired_snapshot().items())]
        if self.obs.flight is not None:
            fl = self.obs.flight.snapshot()
            out += [
                ("qsm_flight_dumps_total", c, "flight-recorder dumps",
                 {}, float(fl["dumps"])),
                ("qsm_flight_events_recorded_total", c,
                 "events through the flight ring", {},
                 float(fl["recorded"])),
            ]
        return out
