"""``CheckClient`` — the serving plane's caller side.

One instance = one connection; requests on a connection are answered in
order.  Concurrency is per-connection (each concurrent caller opens its
own client — the micro-batcher coalesces ACROSS connections), which is
the shape tools/bench_serve.py drives.

Multi-address failover (ISSUE 13): ``address`` may be a comma-separated
list (``--addr a,b``) naming an HA router pair (or any set of
protocol-identical doors to the same fleet).  The client walks the list
with BOUNDED retries on three signals:

* connect failure / connection death mid-request — the socket layer's
  word that this door is gone;
* a ``SHED`` whose reason is ``router_standby`` / ``router_superseded``
  — the door is alive but not the active brain (the HA refusal
  contract, fleet/router.py), so the answer lives behind another one;
* the ``router`` fault site (``QSM_TPU_FAULTS=partition:router`` /
  ``raise:router`` / ``hang:router``) — the client→router exchange is
  chaos-drillable on the CPU platform like every other link in the
  stack.

Re-asking after a death mid-request is safe because every fleet op is
idempotent: check/shrink/stats are pure functions of the request and
verdicts bank by fingerprint (a duplicate lands on the same cache row).
Retries are bounded by ``len(addresses) + 1`` attempts — a fleet with
no answering door raises ``ConnectionError``, never spins.

Used by ``qsm-tpu submit`` / ``qsm-tpu stats --serve``, the bench tools
and tests/test_serve.py, tests/test_fleet_ha.py.
"""

from __future__ import annotations

import itertools
import json
import time
from typing import List, Optional, Sequence, Union

from ..core.history import History
from ..obs import new_trace_id
from ..resilience.faults import InjectedFault, inject
from .protocol import (LineChannel, connect, history_to_rows, send_doc)

_ids = itertools.count()

# ops whose retry ladder must ride ONE trace id: the client mints it
# up front (when the caller supplied none), so a request that bounces
# between addresses — a standby's HA shed, a death mid-request —
# reconstructs as ONE cross-door story in the collected span log
_TRACED_OPS = ("check", "shrink", "session.open", "session.append",
               "session.close")

# SHED reasons that mean "alive, but not the brain you want" — the
# client hops to the next address instead of surfacing the refusal
_FAILOVER_SHED_REASONS = ("router_standby", "router_superseded")

# pause between full cycles through a multi-address list: a takeover
# window (lease TTL + grace + one beat) lasts seconds, so burning the
# whole address list once per millisecond would exhaust any attempt
# budget long before the standby promotes.  The retry ladder is
# WALL-CLOCK bounded by the client's own timeout_s instead.
_CYCLE_PAUSE_S = 0.25


class CheckClient:
    """JSON-lines client for a running :class:`~qsm_tpu.serve.server.
    CheckServer` or :class:`~qsm_tpu.fleet.router.FleetRouter`
    (address: ``host:port`` or a UNIX socket path, or a comma list of
    either for multi-address failover — see module docstring)."""

    def __init__(self, address: str, timeout_s: float = 60.0):
        self.address = address
        self.addresses = [a.strip() for a in str(address).split(",")
                          if a.strip()]
        if not self.addresses:
            raise ValueError("CheckClient needs at least one address")
        self.timeout_s = timeout_s
        self.failovers = 0   # address hops taken (death or HA shed)
        self._addr_i = 0
        self._sock = None
        self._chan: Optional[LineChannel] = None
        self._connect_any()

    # ------------------------------------------------------------------
    def check(self, model: str,
              histories: Sequence[Union[History, Sequence[Sequence[int]]]],
              *, spec_kwargs: Optional[dict] = None, witness: bool = False,
              deadline_s: Optional[float] = None,
              req_id: Optional[str] = None,
              trace: Optional[str] = None) -> dict:
        """Submit one corpus; returns the response document (``ok`` with
        per-history verdict names, or ``shed``/``error``).  ``trace``
        propagates a caller-minted trace id (qsm_tpu/obs) — omitted,
        the server mints one and the response carries it either way."""
        rows: List[list] = [
            history_to_rows(h) if isinstance(h, History) else list(h)
            for h in histories]
        req = {"op": "check", "id": req_id or f"q{next(_ids)}",
               "model": model, "histories": rows}
        if spec_kwargs:
            req["spec_kwargs"] = spec_kwargs
        if witness:
            req["witness"] = True
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if trace:
            req["trace"] = trace
        return self._round_trip(req)

    def shrink(self, model: str,
               history: Union[History, Sequence[Sequence[int]]],
               *, spec_kwargs: Optional[dict] = None,
               certificate: bool = False,
               deadline_s: Optional[float] = None,
               req_id: Optional[str] = None,
               trace: Optional[str] = None) -> dict:
        """Minimize one failing history (the ``shrink`` verb,
        docs/SHRINK.md): the response carries the 1-minimal history's
        rows plus rounds/lanes/memo counters; ``certificate=True`` adds
        the per-neighbor ``verify_witness``-replayable proof."""
        rows = (history_to_rows(history) if isinstance(history, History)
                else list(history))
        req = {"op": "shrink", "id": req_id or f"q{next(_ids)}",
               "model": model, "history": rows}
        if spec_kwargs:
            req["spec_kwargs"] = spec_kwargs
        if certificate:
            req["certificate"] = True
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if trace:
            req["trace"] = trace
        return self._round_trip(req)

    # -- monitor sessions (qsm_tpu/monitor, docs/MONITOR.md) -----------
    def session_open(self, model: str, *,
                     spec_kwargs: Optional[dict] = None,
                     session: Optional[str] = None,
                     deadline_s: Optional[float] = None,
                     trace: Optional[str] = None) -> dict:
        """Open (or resume) a streaming monitor session; the response
        carries the server-assigned ``session`` id and current
        ``seq``.  :class:`SessionHandle` wraps the three verbs with
        the seq bookkeeping replays need."""
        req = {"op": "session.open", "id": f"q{next(_ids)}",
               "model": model}
        if spec_kwargs:
            req["spec_kwargs"] = spec_kwargs
        if session is not None:
            req["session"] = session
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if trace:
            req["trace"] = trace
        return self._round_trip(req)

    def session_append(self, session: str, events, *,
                       seq: Optional[int] = None,
                       deadline_s: Optional[float] = None,
                       trace: Optional[str] = None) -> dict:
        """Stream events into a session.  ``seq`` (the stream index of
        the first event) makes the append IDEMPOTENT: a re-send after
        a failover or reconnect applies only what the server has not
        seen — the same replay-safety contract every fleet op has.
        The response carries the current verdict, and the ``flip``
        payload (minimized repro + certificate) on the append that
        made a violation decidable."""
        req = {"op": "session.append", "id": f"q{next(_ids)}",
               "session": session, "events": list(events)}
        if seq is not None:
            req["seq"] = int(seq)
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if trace:
            req["trace"] = trace
        return self._round_trip(req)

    def session_close(self, session: str, *, witness: bool = False,
                      deadline_s: Optional[float] = None,
                      trace: Optional[str] = None) -> dict:
        req = {"op": "session.close", "id": f"q{next(_ids)}",
               "session": session}
        if witness:
            req["witness"] = True
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if trace:
            req["trace"] = trace
        return self._round_trip(req)

    def stats(self) -> dict:
        return self._round_trip({"op": "stats"})

    # -- elastic membership (ISSUE 18; fleet/router.py) ----------------
    def node_join(self, node: str, address: str) -> dict:
        """Add a node to a router's ring (idempotent): consistent
        hashing moves only the key ranges the newcomer's vnode points
        claim, and the router seeds its replog by anti-entropy before
        answering."""
        return self._round_trip({"op": "node.join",
                                 "id": f"q{next(_ids)}",
                                 "node": str(node),
                                 "address": str(address)})

    def node_leave(self, node: str) -> dict:
        """Retire a node from a router's ring (idempotent): its key
        ranges move to the next points clockwise and every session it
        owned migrates live — the journal replays onto the new owner
        on its next verb, exactly-once by seq."""
        return self._round_trip({"op": "node.leave",
                                 "id": f"q{next(_ids)}",
                                 "node": str(node)})

    # -- the device-work queue (qsm_tpu/devq, docs/WINDOWS.md) ---------
    def devq_put(self, items) -> dict:
        """Bank device-worthy work items (``devq.put``): any fleet node
        can feed the queue a window host later drains.  Idempotent —
        items dedupe by their fingerprint key."""
        return self._round_trip({"op": "devq.put",
                                 "id": f"q{next(_ids)}",
                                 "items": list(items)})

    def devq_digests(self) -> dict:
        """The queue's anti-entropy advertisement (``devq.digests``):
        segment digests of the devq log plus a queue snapshot."""
        return self._round_trip({"op": "devq.digests",
                                 "id": f"q{next(_ids)}"})

    def devq_pull(self, segments) -> dict:
        """Ship devq segments out (``devq.pull``) — fingerprint-
        verified by the adopting side, like ``replog.pull``."""
        return self._round_trip({"op": "devq.pull",
                                 "id": f"q{next(_ids)}",
                                 "segments": list(segments)})

    def devq_drain_report(self, report: Optional[dict] = None,
                          rows=None, done=None) -> dict:
        """Hand a drained window back (``devq.drain_report``): verdict
        rows bank under their originating fingerprints, drained keys
        tombstone as done, the report feeds the ``window_utilization``
        SLO.  With no arguments, reads the node's last report."""
        req = {"op": "devq.drain_report", "id": f"q{next(_ids)}"}
        if report is not None:
            req["report"] = report
        if rows:
            req["rows"] = [list(r) for r in rows]
        if done:
            req["done"] = list(done)
        return self._round_trip(req)

    # -- fleet observability (docs/OBSERVABILITY.md "Fleet") -----------
    def health(self) -> dict:
        """The ``health`` op: SLO status of the server/router (and,
        through a router, the folded per-node statuses)."""
        return self._round_trip({"op": "health"})

    def metrics(self) -> dict:
        """The ``obs.metrics`` op: the process's live metric samples,
        JSON-shaped (a router answers the federated set)."""
        return self._round_trip({"op": "obs.metrics"})

    def trace_events(self, trace_id: str) -> dict:
        """The ``obs.trace`` op: one trace's events (causal closure);
        a router answers from its collected fleet log merged with its
        own span log — the `qsm-tpu trace <id> --addr` transport."""
        return self._round_trip({"op": "obs.trace",
                                 "trace": str(trace_id)})

    def span_page(self, cursor: Optional[dict] = None,
                  max_events: Optional[int] = None) -> dict:
        """One ``obs.spans`` page of the peer's span log (cursor-paged
        and idempotent — obs/collect.py owns the semantics)."""
        req: dict = {"op": "obs.spans", "cursor": cursor}
        if max_events is not None:
            req["max_events"] = int(max_events)
        return self._round_trip(req)

    def shutdown(self) -> dict:
        # Deliberately single-attempt: ``shutdown`` is the one op the
        # contract excludes from IDEMPOTENT_OPS (serve/protocol.py) —
        # a failover re-send after a mid-flight drop could stop a
        # *different* process than the one that already acked.  A
        # dropped reply after the server acted is indistinguishable
        # from a dropped request, so the caller sees the error rather
        # than the client silently escalating it fleet-wide.
        # (QSM-PROTO-RETRY-IDEMPOTENT pins this shape.)
        return self._ask_once({"op": "shutdown"})

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._chan = None

    # ------------------------------------------------------------------
    @property
    def connected_address(self) -> str:
        """The address currently (or last) spoken to."""
        return self.addresses[self._addr_i % len(self.addresses)]

    def _connect_any(self, bound_s: Optional[float] = None) -> None:
        """Connect to the first answering address, starting from the
        current position (sticky: a client that failed over stays on
        the working door).  ``bound_s`` caps EACH connect attempt —
        the failover ladder passes its remaining budget so a
        SYN-dropping partition cannot stall one attempt for the whole
        ``timeout_s`` per address."""
        bound_s = self.timeout_s if bound_s is None else bound_s
        last: Optional[BaseException] = None
        for k in range(len(self.addresses)):
            i = (self._addr_i + k) % len(self.addresses)
            try:
                sock = connect(self.addresses[i],
                               timeout_s=max(0.1, bound_s))
            except OSError as e:
                last = e
                continue
            self._addr_i = i
            self._sock = sock
            self._chan = LineChannel(sock)
            return
        raise ConnectionError(
            f"no server answered at {self.addresses}: "
            f"{type(last).__name__}: {last}")

    def _advance(self) -> None:
        self.close()
        self._addr_i = (self._addr_i + 1) % len(self.addresses)
        self.failovers += 1

    def _round_trip(self, req: dict) -> dict:
        """One request under bounded multi-address failover (module
        docstring).  Single-address clients keep one bounded retry on
        a fresh connection — a server restart on the same address must
        not read as server death (the NodeLink lesson one level up).
        Multi-address clients cycle the list with a short pause
        between full cycles, wall-clock bounded by ``timeout_s``: a
        takeover window (the standby still shedding ``router_standby``
        while the lease runs out) lasts seconds, and a count bound
        would burn out in milliseconds against a dead door."""
        if req.get("op") in _TRACED_OPS and not req.get("trace"):
            # mint the trace CLIENT-side so every attempt of this
            # logical request — across doors and takeover windows —
            # shares one id (the server adopts it; _TRACED_OPS note)
            req["trace"] = new_trace_id()
        n = len(self.addresses)
        deadline = time.monotonic() + max(1.0, self.timeout_s)
        # bounded by construction: every attempt either pauses toward
        # the deadline or is one of the first `n + 1` free tries, AND
        # the deadline is re-checked per attempt (a SYN-dropping
        # partition burns connect budget, not just pause budget)
        max_attempts = (n + 1) + n * max(
            1, int(max(1.0, self.timeout_s) / _CYCLE_PAUSE_S) + 1)
        last: Optional[BaseException] = None
        for attempt in range(max_attempts):
            if attempt and time.monotonic() >= deadline:
                break
            try:
                doc = self._ask_once(req, deadline)
            except (OSError, ConnectionError, TimeoutError, ValueError,
                    InjectedFault) as e:
                last = e
                self._advance()
                if not self._pause_between_cycles(attempt, n, deadline):
                    break
                continue
            if (doc.get("shed")
                    and doc.get("reason") in _FAILOVER_SHED_REASONS
                    and n > 1):
                # alive but not the active brain: hop — the active is
                # behind one of the other doors (or about to be, after
                # its lease beat)
                last = None
                self._advance()
                if not self._pause_between_cycles(attempt, n, deadline):
                    return doc  # out of time: surface the honest SHED
                continue
            return doc
        if last is None:
            raise ConnectionError(
                f"no active router at {self.addresses} before the "
                f"{self.timeout_s:.1f}s client bound")
        raise ConnectionError(
            f"server at {self.address} closed the connection "
            f"({type(last).__name__}: {last})")

    def _pause_between_cycles(self, attempt: int, n: int,
                              deadline: float) -> bool:
        """After a full cycle through the address list, wait out a
        short pause (the takeover window is time, not attempts).
        False = the deadline is spent — stop retrying.  Single-address
        clients get their one free fresh-connection retry, then stop."""
        if n == 1:
            return attempt < 1
        if (attempt + 1) % n:
            return True  # mid-cycle: try the next address immediately
        remaining = deadline - time.monotonic()
        if remaining <= _CYCLE_PAUSE_S:
            return False
        time.sleep(_CYCLE_PAUSE_S)
        return True

    def _ask_once(self, req: dict,
                  deadline: Optional[float] = None) -> dict:
        bound = self.timeout_s
        if deadline is not None:
            bound = max(0.1, min(bound, deadline - time.monotonic()))
        if self._sock is None:
            self._connect_any(bound)
        act = inject("router")
        if act in ("partition", "wedge"):
            # the exchange's frames drop both directions: the request
            # never arrives, the answer never comes — the failover
            # loop treats it exactly like a dead door
            self.close()
            raise ConnectionError(
                "injected partition at fault site 'router'")
        send_doc(self._sock, req)
        line = self._chan.read_line(timeout_s=bound)
        if line is None:
            raise ConnectionError(
                f"server at {self.connected_address} closed the "
                "connection")
        return json.loads(line)

    def __enter__(self) -> "CheckClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class SessionHandle:
    """One live monitor session, seq-tracked (docs/MONITOR.md).

    Wraps the three ``session.*`` verbs so callers just ``append``
    events: every append carries ``seq``, so the client's bounded
    retry/failover ladder (the ``_round_trip`` machinery, multi-address
    included) can safely re-send — the server applies only what it has
    not seen, and a replay onto a restarted node resumes from the
    banked decided prefix.  ``flips`` collects every pushed flip
    payload (minimized repro + certificate)."""

    def __init__(self, client: CheckClient, model: str, *,
                 spec_kwargs: Optional[dict] = None,
                 session: Optional[str] = None,
                 deadline_s: Optional[float] = None):
        self.client = client
        self.model = model
        self.spec_kwargs = spec_kwargs
        self.deadline_s = deadline_s
        doc = client.session_open(model, spec_kwargs=spec_kwargs,
                                  session=session,
                                  deadline_s=deadline_s)
        if not doc.get("ok"):
            raise RuntimeError(f"session.open refused: {doc}")
        self.sid: str = doc["session"]
        self.seq: int = int(doc.get("seq", 0))
        self.verdict: str = doc.get("verdict", "LINEARIZABLE")
        self.trace: str = doc.get("trace", "")
        self.flips: List[dict] = []
        self.last: dict = doc

    def append(self, events) -> dict:
        """Stream events; returns the response (current verdict, and
        the flip payload on the deciding append)."""
        events = list(events)
        doc = self.client.session_append(
            self.sid, events, seq=self.seq,
            deadline_s=self.deadline_s,
            trace=self.trace or None)
        self.last = doc
        if doc.get("ok"):
            self.seq = int(doc.get("seq", self.seq))
            self.verdict = doc.get("verdict", self.verdict)
            if doc.get("flip"):
                self.flips.append(doc["flip"])
        return doc

    def close(self, witness: bool = False) -> dict:
        doc = self.client.session_close(self.sid, witness=witness,
                                        deadline_s=self.deadline_s,
                                        trace=self.trace or None)
        self.last = doc
        if doc.get("ok"):
            self.verdict = doc.get("verdict", self.verdict)
        return doc
