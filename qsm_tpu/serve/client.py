"""``CheckClient`` — the serving plane's caller side.

One instance = one connection; requests on a connection are answered in
order.  Concurrency is per-connection (each concurrent caller opens its
own client — the micro-batcher coalesces ACROSS connections), which is
the shape tools/bench_serve.py drives.

Used by ``qsm-tpu submit`` / ``qsm-tpu stats --serve``, the bench tool
and tests/test_serve.py.
"""

from __future__ import annotations

import itertools
import json
from typing import List, Optional, Sequence, Union

from ..core.history import History
from .protocol import (LineChannel, connect, history_to_rows, send_doc)

_ids = itertools.count()


class CheckClient:
    """JSON-lines client for a running :class:`~qsm_tpu.serve.server.
    CheckServer` (address: ``host:port`` or a UNIX socket path)."""

    def __init__(self, address: str, timeout_s: float = 60.0):
        self.address = address
        self.timeout_s = timeout_s
        self._sock = connect(address, timeout_s=timeout_s)
        self._chan = LineChannel(self._sock)

    # ------------------------------------------------------------------
    def check(self, model: str,
              histories: Sequence[Union[History, Sequence[Sequence[int]]]],
              *, spec_kwargs: Optional[dict] = None, witness: bool = False,
              deadline_s: Optional[float] = None,
              req_id: Optional[str] = None,
              trace: Optional[str] = None) -> dict:
        """Submit one corpus; returns the response document (``ok`` with
        per-history verdict names, or ``shed``/``error``).  ``trace``
        propagates a caller-minted trace id (qsm_tpu/obs) — omitted,
        the server mints one and the response carries it either way."""
        rows: List[list] = [
            history_to_rows(h) if isinstance(h, History) else list(h)
            for h in histories]
        req = {"op": "check", "id": req_id or f"q{next(_ids)}",
               "model": model, "histories": rows}
        if spec_kwargs:
            req["spec_kwargs"] = spec_kwargs
        if witness:
            req["witness"] = True
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if trace:
            req["trace"] = trace
        return self._round_trip(req)

    def shrink(self, model: str,
               history: Union[History, Sequence[Sequence[int]]],
               *, spec_kwargs: Optional[dict] = None,
               certificate: bool = False,
               deadline_s: Optional[float] = None,
               req_id: Optional[str] = None,
               trace: Optional[str] = None) -> dict:
        """Minimize one failing history (the ``shrink`` verb,
        docs/SHRINK.md): the response carries the 1-minimal history's
        rows plus rounds/lanes/memo counters; ``certificate=True`` adds
        the per-neighbor ``verify_witness``-replayable proof."""
        rows = (history_to_rows(history) if isinstance(history, History)
                else list(history))
        req = {"op": "shrink", "id": req_id or f"q{next(_ids)}",
               "model": model, "history": rows}
        if spec_kwargs:
            req["spec_kwargs"] = spec_kwargs
        if certificate:
            req["certificate"] = True
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if trace:
            req["trace"] = trace
        return self._round_trip(req)

    def stats(self) -> dict:
        return self._round_trip({"op": "stats"})

    def shutdown(self) -> dict:
        return self._round_trip({"op": "shutdown"})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------------
    def _round_trip(self, req: dict) -> dict:
        send_doc(self._sock, req)
        line = self._chan.read_line(timeout_s=self.timeout_s)
        if line is None:
            raise ConnectionError(
                f"server at {self.address} closed the connection")
        return json.loads(line)

    def __enter__(self) -> "CheckClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
