"""Pool worker process — one warm engine set, bank-free, expendable.

``serve/pool.py`` keeps the server's single admission → batcher → cache
plane and dispatches micro-batches to N of these processes.  The split
of responsibilities is the whole design:

* **The worker owns checking and nothing else.**  It builds the exact
  host cpp→memo ladder per spec — ``resilience.host_fallback`` wrapped
  in ``FailoverBackend``, the same engine the in-process server keeps
  warm — so pooled verdicts are bit-identical to the direct path by
  construction.  It never touches the verdict bank, the admission
  counters, or the socket plane: everything a crash could corrupt
  lives in the supervisor, which makes the worker *expendable* — the
  supervisor sheds a wedged or crashed worker exactly like a wedged
  chip and re-dispatches the undecided lanes.
* **The protocol is length-prefixed JSON frames over stdin/stdout**
  (``serve/frames.py``): 4-byte big-endian length + UTF-8 JSON —
  a half-written frame from a killed worker is detectable instead of
  silently mergeable.  fd 1 is claimed for the protocol before any
  engine code runs and fd 1 is repointed at stderr, so a stray
  ``print`` inside an engine can never corrupt the stream.
* **The ``worker`` fault site** (:func:`~qsm_tpu.resilience.faults.
  inject`) sits at the dispatch entry: ``QSM_TPU_FAULTS=kill:worker``
  SIGKILLs this process mid-batch, ``hang:worker`` wedges it past the
  supervisor's ``worker-dispatch`` watchdog, ``raise:worker`` answers
  a clean error — the three loss modes the supervisor's
  shed/re-dispatch/quarantine ladder must survive, all CPU-testable
  (tests/test_serve_pool.py).

Ops (all carry ``seq``; responses echo it)::

    {"op": "check", "seq": 3, "model": "cas", "spec_kwargs": {},
     "rows": [[...history rows...]], "width": 64}
      -> {"seq": 3, "ok": true, "verdicts": [1, 0, ...],
          "search": {...compact...}, "resilience": {...},
          "dispatches": 7, "seconds": 0.012}
    {"op": "ping", "seq": 4}   -> {"seq": 4, "ok": true, "pong": true, ...}
    {"op": "warm", "seq": 5, "model": "cas", "spec_kwargs": {}}
    {"op": "exit", "seq": 6}   -> {"seq": 6, "ok": true, "bye": true}

Run as ``python -m qsm_tpu.serve.worker --wid N`` (the pool does; the
module is import-light — the host ladder never pulls in jax, so a
respawn costs interpreter + package import, about a second).
"""

from __future__ import annotations

import json
import os
import time
from typing import BinaryIO, Dict, Optional, Tuple

from .frames import encode_frame, read_frame


class CheckWorker:
    """One worker process' state: warm engines keyed like the server's
    (``json.dumps([model, spec_kwargs], sort_keys=True)`` — per-spec
    affinity on the supervisor side keeps this map small and hot)."""

    def __init__(self, wid: int, proto_in: BinaryIO, proto_out: BinaryIO):
        self.wid = wid
        self._in = proto_in
        self._out = proto_out
        self._engines: Dict[str, Tuple[object, object]] = {}
        self._stop = False
        self._t0 = time.monotonic()
        self.dispatches = 0

    # ------------------------------------------------------------------
    def run(self) -> int:
        while not self._stop:
            doc = read_frame(self._in)
            if doc is None:
                break  # supervisor closed the pipe: exit, never linger
            resp = self._handle(doc)
            if resp is not None:
                self._out.write(encode_frame(resp))
                self._out.flush()
        return 0

    def _handle(self, doc: dict) -> Optional[dict]:
        op, seq = doc.get("op"), doc.get("seq")
        try:
            if op == "check":
                return self._check(doc)
            if op == "ping":
                return {"seq": seq, "ok": True, "pong": True,
                        "wid": self.wid, "dispatches": self.dispatches,
                        "uptime_s": round(time.monotonic() - self._t0, 1),
                        "specs": sorted(self._engines)}
            if op == "warm":
                from ..core.history import History

                spec, engine = self._engine_for(
                    doc.get("model"), doc.get("spec_kwargs") or {})
                # a warm DISPATCH, not just a build: the first real
                # check otherwise pays spec table compilation (~100s of
                # ms) inside a request's deadline
                engine.check_histories(spec, [History([])])
                return {"seq": seq, "ok": True, "warmed": True}
            if op == "exit":
                self._stop = True
                return {"seq": seq, "ok": True, "bye": True}
            return {"seq": seq, "ok": False,
                    "error": f"unknown worker op {op!r}"}
        except Exception as e:  # noqa: BLE001 — a failing dispatch must
            # answer an error frame (the supervisor re-dispatches the
            # lanes), not kill the worker loop; a KILLED worker is the
            # other tested path and SIGKILL never reaches here
            return {"seq": seq, "ok": False,
                    "error": f"{type(e).__name__}: {e}"[:300]}

    # ------------------------------------------------------------------
    def _engine_for(self, model: str, spec_kwargs: dict):
        from ..models.registry import make
        from ..resilience.failover import FailoverBackend, host_fallback

        key = json.dumps([model, spec_kwargs or {}], sort_keys=True)
        entry = self._engines.get(key)
        if entry is None:
            # the exact engine the in-process auto server keeps warm
            # (server.py _build_engine): verdict parity by construction
            spec, _ = make(model, "atomic", spec_kwargs or None)
            engine = FailoverBackend(spec, host_fallback(spec))
            entry = self._engines[key] = (spec, engine)
        return entry

    def _check(self, doc: dict) -> dict:
        from ..core.history import History
        from ..resilience.faults import inject
        from ..resilience.failover import collect_resilience
        from ..search.stats import collect_search_stats, stats_delta
        from .protocol import rows_to_history

        t0 = time.perf_counter()
        spec, engine = self._engine_for(doc.get("model"),
                                        doc.get("spec_kwargs") or {})
        hists = [rows_to_history(rows) for rows in doc["rows"]]
        # same fixed-width padding as the in-process dispatch (empty
        # histories are instantly-SUCCESS): only real lanes ride the pipe
        width = max(int(doc.get("width", len(hists))), len(hists))
        padded = hists + [History([])] * (width - len(hists))
        st0 = collect_search_stats(engine)
        # THE worker fault site: kill:worker SIGKILLs this process here
        # (mid-batch, mid-protocol — the supervisor sees pipe EOF),
        # hang:worker wedges it, raise:worker answers a clean error
        inject("worker")
        verdicts = engine.check_histories(spec, padded)[:len(hists)]
        self.dispatches += 1
        st = stats_delta(collect_search_stats(engine), st0)
        resp = {"seq": doc.get("seq"), "ok": True,
                "verdicts": [int(v) for v in verdicts],
                "search": st.to_compact() if st is not None else None,
                "resilience": collect_resilience(engine),
                "wid": self.wid, "dispatches": self.dispatches,
                "seconds": round(time.perf_counter() - t0, 4)}
        if "trace" in doc:
            # the trace plane's optional frame field (serve/frames.py):
            # echoed so a supervisor-side frame capture is attributable;
            # workers that predate it ignore the key entirely
            resp["trace"] = doc["trace"]
        return resp


def main(argv=None) -> int:
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        description="qsm_tpu check-pool worker (spawned by serve/pool.py)")
    ap.add_argument("--wid", type=int, default=0,
                    help="worker id (stats/affinity label)")
    args = ap.parse_args(argv)

    # claim the protocol stream BEFORE any engine code can print to it:
    # frames ride a private dup of fd 0/1, and fd 1 is repointed at
    # stderr so stray engine chatter cannot corrupt a frame
    proto_in = os.fdopen(os.dup(0), "rb")
    proto_out = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    # the supervisor owns lifecycle: a terminal Ctrl-C must stop the
    # SERVER (which tears the pool down deterministically), not race N
    # workers' own KeyboardInterrupts against the pipe protocol
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    return CheckWorker(args.wid, proto_in, proto_out).run()


if __name__ == "__main__":
    import sys

    sys.exit(main())
