"""JSON-lines wire protocol of the check server (docs/SERVING.md).

One request or response per line, UTF-8 JSON, over a local TCP or UNIX
socket.  Histories ride the repo's ONE external encoding — the
``[pid, cmd, arg, resp, invoke_time, response_time]`` rows that
regression files and the ``check`` CLI already use
(utils/report.py::history_from_rows is the shared decoder), so anything
that can feed ``qsm-tpu check`` can submit to the server unchanged.

Requests::

    {"op": "check", "id": "c0-3", "model": "cas", "histories": [[...]],
     "spec_kwargs": {}, "witness": false, "deadline_s": 30.0}
    {"op": "shrink", "id": "s1", "model": "kv", "history": [...],
     "spec_kwargs": {}, "certificate": false, "deadline_s": 300.0}
    {"op": "stats"}
    {"op": "shutdown"}

Responses (same order as requests on a connection)::

    {"id": "c0-3", "ok": true, "verdicts": ["LINEARIZABLE", ...],
     "cached": [true, false, ...], "witnesses": [...]?,
     "batches": [{...why stamp...}], "seconds": 0.012}
    {"id": "c0-3", "ok": false, "shed": true, "reason": "deadline"}

A ``shed`` response is the load-shedding contract (admission.py): the
server refuses work it cannot finish inside the request's deadline —
explicitly, never by silent latency collapse, and NEVER by a wrong or
partial verdict.

Trace plane (qsm_tpu/obs, docs/OBSERVABILITY.md): every ``check`` /
``shrink`` response — including SHED — carries a ``trace`` field, the
request-scoped trace id minted at admission (or adopted from an
optional client-supplied ``trace`` request field).  With the server
tracing to a span log, ``qsm-tpu trace <trace_id>`` reconstructs the
request's full causal tree.  SHED responses additionally carry
``flight`` — the most recent flight-recorder dump path — when one
fired, so a shed client can hand the operator the artifact.

The ``shrink`` verb (qsm_tpu/shrink, docs/SHRINK.md) answers with the
1-minimal history's rows plus rounds/lanes/memo counters::

    {"id": "s1", "ok": true, "verdict": "VIOLATION", "initial_ops": 64,
     "final_ops": 2, "rounds": 9, "history": [[...]], "one_minimal": true,
     "complete": true, "why": [...], "certificate": [...]?}

Its admission/SHED semantics match ``check``, with one documented
difference: a deadline firing MID-shrink returns the best-so-far
history with ``complete: false`` and an honest ``why`` instead of
discarding the rounds already paid for.

Monitor sessions (qsm_tpu/monitor, docs/MONITOR.md) grow the protocol
from request/response to STREAMS::

    {"op": "session.open", "id": "m0", "model": "kv", "spec_kwargs": {}}
    {"op": "session.append", "session": "s000001", "seq": 0, "events":
     [{"type": "invoke", "pid": 0, "cmd": 1, "arg": 5},
      {"type": "respond", "pid": 0, "resp": 0}]}
    {"op": "session.close", "session": "s000001", "witness": true}

Events are invoke/respond dicts (live streams; arrival order is time
order) or raw history 6-rows (recorded corpora, invoke-time order).
Every append answers the CURRENT verdict — exact at every step, equal
to the whole-history ``check`` of the same prefix — and the append
that makes a violation decidable carries ``flip``: the verdict, a
shrink-plane-minimized ``repro`` (1-minimal rows) and its
``certificate``.  ``seq`` (stream index of the append's first event)
makes appends idempotent: reconnects, router failover replay and
node restarts re-send safely, and a restarted node resumes from the
decided prefixes banked in the verdict cache under rolling prefix
fingerprints.  Session caps (sessions, events) answer SHED exactly
like admission pressure.  Routed through a ``FleetRouter``, a
session's ops route by its session key and a lost node is replayed
onto the next ring node (fleet/router.py).

Fleet tier (qsm_tpu/fleet, docs/SERVING.md "Fleet"): a server started
with a ``node_id`` stamps ``node`` on EVERY response (ok/SHED/error),
so router-merged answers say which node decided which lanes; a server
started with a ``replog_dir`` additionally answers the
``replog.digests`` / ``replog.pull`` / ``replog.push`` ops — the
segment-exchange surface anti-entropy reconciles replicated verdict
banks through — plus ``replog.covers`` / ``replog.subsumed`` (the
row-level subsumption legs: a segment whose rows the receiver already
holds is marked covered without its rows crossing the wire) and
``gossip.peers`` (configure node-to-node gossip at runtime,
fleet/gossip.py).  The ``FleetRouter`` itself speaks exactly this
protocol, so clients point at a router address unchanged; its SHED
responses carry the per-node health block (``fleet``) beside the
``pool`` block a single node would send.

Fleet observability (ISSUE 15, docs/OBSERVABILITY.md "Fleet"): every
server and router answers four more ops, none of them lease-gated —
the observability surface must stay up mid-incident:

* ``{"op": "obs.spans", "cursor": {...}|null, "max_events": N}`` —
  one cursor-paged, bounded, IDEMPOTENT page of this process's span
  log (obs/collect.py owns the cursor semantics: a re-scrape ships
  zero duplicate events, a lost rotation answers an honest ``gap``).
  The router's collection sweep scrapes node logs through this op
  into its fleet-wide collected log.
* ``{"op": "obs.trace", "trace": ID}`` — the trace's events under
  causal closure (ancestors included, so a ``router.takeover`` the
  request rode through appears in its tree).  A router answers from
  its COLLECTED log merged with its own spans — the transport behind
  ``qsm-tpu trace <id> --addr ROUTER``.
* ``{"op": "obs.metrics"}`` — the process's metric samples, JSON-
  shaped.  A router's answer is the FEDERATED set: every node's
  samples re-labeled with ``node``, plus per-node staleness gauges
  (a down node is a hole, never a hang).
* ``{"op": "health"}`` — the SLO evaluation (obs/slo.py; configured
  via ``--slo "check=250ms:p99,shed_rate<0.01"``): per-objective burn
  rates over a sliding window of the same histograms ``/metrics``
  serves, overall ``ok``/``degraded``/``breach``.  A router folds in
  every node's health.  ``qsm-tpu health`` maps the status to pinned
  exit codes (0/1/2; 3 unreachable).

Lease service (ISSUE 18, fleet/lease.py): a server started with a
``lease_path`` additionally answers the ``lease.acquire`` /
``lease.renew`` / ``lease.release`` / ``lease.read`` ops — the
transaction surface :class:`~qsm_tpu.fleet.lease.TcpLeaseStore` rides
so routers on DIFFERENT hosts can share one lease record.  Each op is
one request/response pair carrying ``holder`` (plus ``ttl_s`` /
``grace_s`` / ``term`` as the transaction needs); the server runs the
identical flock-excluded FileLeaseStore transaction and answers
``{"ok": true, "acquired"|"renewed"|"released": bool, "record":
{...}}``.  A refused transaction is an OK response with the flag
false — transport errors are the only None a TcpLeaseStore caller
sees, and both read as "lost this beat".

Elastic membership (ISSUE 18, fleet/membership.py): a router answers
``{"op": "node.join", "node": ID, "address": ADDR}`` and ``{"op":
"node.leave", "node": ID}`` — live ring membership changes.  Join
rebuilds the consistent-hash ring (only the arriving node's key
ranges move), starts replog handoff via the next anti-entropy sweep,
and invalidates the routed-session pins whose ring owner changed so
their next op replays the journal onto the new owner (exactly-once by
``seq``).  Leave is the inverse: the departing node's ranges scatter
to survivors and its pinned sessions migrate on their next op.  Both
are active-gated (a standby must not mutate the fleet view) and
idempotent (re-joining a present node / re-leaving an absent one is a
no-op).

Check/shrink/session requests may also carry ``parent`` — the span id
of the caller's dispatch edge.  A router stamps its ``node.dispatch``
span there, so the node's whole request subtree pins under the router
edge that caused it in the collected tree: cross-process causality by
edges, never by comparing wall clocks between hosts.

Router HA (fleet/lease.py): a router running under a lease stamps its
``term`` on every response; a NON-active router answers check/shrink
with ``{"shed": true, "reason": "router_standby" |
"router_superseded", "router": {role, term, active_term,
active_holder}}`` — never a verdict.  ``CheckClient`` accepts a comma
address list (``--addr a,b``) and fails over onto the next router on
connection death or an HA shed, wall-clock bounded by its own
``timeout_s`` (safe: every op here is idempotent and verdicts bank by
fingerprint).
"""

from __future__ import annotations

import json
import socket
import time
from typing import Callable, List, Optional, Sequence

from ..core.history import History

# index == Verdict value (ops/backend.py); the ONE rendering site —
# utils/cli.py imports this tuple for every subcommand's output
VERDICT_NAMES = ("VIOLATION", "LINEARIZABLE", "BUDGET_EXCEEDED")

# ---------------------------------------------------------------------
# The machine-readable wire contract (docs/PROTOCOL.md).  The protocol
# analyzer (analysis/protocol_model.py, lint family l) parses these
# tuples straight from this file's AST — keep them literal: no
# comprehensions, no computed elements.  Every op a client or router
# path sends and every op a ``_handle`` dispatches must appear in
# ``OPS``; the lint gate fails otherwise (QSM-PROTO-UNHANDLED).
OPS = (
    "check", "shrink", "stats", "shutdown",
    "session.open", "session.append", "session.close",
    "replog.digests", "replog.pull", "replog.push",
    "replog.covers", "replog.subsumed",
    "gossip.peers",
    "obs.spans", "obs.trace", "obs.metrics", "health",
    "lease.acquire", "lease.renew", "lease.release", "lease.read",
    "node.join", "node.leave",
    "devq.put", "devq.digests", "devq.pull", "devq.drain_report",
)

# Ops that MAY legally sit on a retrying call path (CheckClient
# failover, NodeLink fresh-socket retry, router re-dispatch).  Every
# entry's replay-safety argument, in one place:
#   check/shrink      — verdicts bank by history fingerprint; a replay
#                       answers from the bank (docs/SERVE.md)
#   session.*         — open resumes by session id; append carries
#                       ``seq`` so the server applies only unseen
#                       events; close is a no-op on a closed session
#   replog.*/gossip.* — anti-entropy reads + set-union writes
#   stats/obs.*/health— read-only snapshots (spans is cursor-paged)
#   lease.*           — the store transaction is term-gated: a replayed
#                       acquire of an own live record is a renew (same
#                       term), a replayed renew refreshes the same
#                       term, a replayed release re-tombstones the
#                       already-released record, read is read-only
#   node.join/leave   — membership set-union/difference: re-adding a
#                       present node or removing an absent one is a
#                       no-op rebuild of the same ring
#   devq.*            — put dedupes by item fingerprint (a replayed put
#                       of a pending/done key is a no-op), digests/pull
#                       are anti-entropy reads, drain_report banks
#                       fingerprint-keyed verdicts (set-union) + marks
#                       done tombstones (absorbing), so a replay
#                       re-banks identical rows
# ``shutdown`` is deliberately ABSENT: re-sending it after a mid-flight
# failover could stop a *different* process than the one addressed, so
# the client sends it on a single non-retrying attempt
# (QSM-PROTO-RETRY-IDEMPOTENT pins this).
IDEMPOTENT_OPS = (
    "check", "shrink", "stats",
    "session.open", "session.append", "session.close",
    "replog.digests", "replog.pull", "replog.push",
    "replog.covers", "replog.subsumed",
    "gossip.peers",
    "obs.spans", "obs.trace", "obs.metrics", "health",
    "lease.acquire", "lease.renew", "lease.release", "lease.read",
    "node.join", "node.leave",
    "devq.put", "devq.digests", "devq.pull", "devq.drain_report",
)

# Envelope keys: request keys any sender may attach / response keys
# any reply may carry, regardless of op.  ``node``/``term`` are
# stamped by the ONE ``_send`` egress (never by handlers); ``trace``/
# ``flight`` ride on admission and tracing.  The field-conformance
# pass (QSM-PROTO-FIELDS) exempts these from per-op sender/handler
# matching.
REQUEST_ENVELOPE = ("op", "id", "trace", "parent", "deadline_s")
RESPONSE_ENVELOPE = ("ok", "id", "error", "node", "term", "trace",
                     "flight", "shed", "reason", "router")

# recv granularity and the poll slice used while honoring deadlines /
# shutdown flags (a LineChannel read is bounded by BOTH)
_RECV_BYTES = 65536
_POLL_S = 0.5
# send bound: LineChannel leaves its short poll timeout on the socket,
# which sendall would otherwise inherit — a large witness response to a
# client that stalls >0.5 s mid-drain would abort the connection.  A
# send gets its own generous bound instead (a peer that cannot drain in
# this long is wedged, and a bounded drop beats a leaked thread).
SEND_TIMEOUT_S = 30.0


def history_to_rows(h: History) -> List[list]:
    """Inverse of utils/report.py::history_from_rows (pending ops keep
    their sentinel resp/response_time; the decoder canonicalizes)."""
    return [[o.pid, o.cmd, o.arg, o.resp, o.invoke_time, o.response_time]
            for o in h.ops]


def rows_to_history(rows: Sequence[Sequence[int]]) -> History:
    from ..utils.report import history_from_rows

    return history_from_rows(rows)


def send_doc(sock: socket.socket, doc: dict) -> None:
    sock.settimeout(SEND_TIMEOUT_S)
    sock.sendall((json.dumps(doc) + "\n").encode())


class LineChannel:
    """Buffered newline-framed reader over a socket.

    Every read is bounded: ``timeout_s`` is a wall-clock deadline and
    ``stop`` an optional shutdown predicate polled every ``_POLL_S`` —
    the discipline the QSM-SERVE-ACCEPT lint pass gates (an unbounded
    recv loop holds a server thread forever when a peer wedges).
    Returns ``None`` on EOF / closed socket; raises :class:`TimeoutError`
    past the deadline.
    """

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = b""

    def read_line(self, timeout_s: Optional[float] = None,
                  stop: Optional[Callable[[], bool]] = None
                  ) -> Optional[str]:
        t0 = time.monotonic()
        while b"\n" not in self._buf:
            if stop is not None and stop():
                return None
            slice_s = _POLL_S
            if timeout_s is not None:
                remaining = timeout_s - (time.monotonic() - t0)
                if remaining <= 0:
                    raise TimeoutError("read_line deadline exceeded")
                slice_s = min(slice_s, remaining)
            self.sock.settimeout(slice_s)
            try:
                chunk = self.sock.recv(_RECV_BYTES)
            except socket.timeout:
                continue
            except OSError:
                return None
            if not chunk:
                return None  # peer closed
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\n")
        return line.decode()


def parse_address(address: str):
    """``host:port`` → ``("tcp", (host, port))``; anything else is a
    UNIX socket path → ``("unix", path)``."""
    if ":" in address:
        host, _, port = address.rpartition(":")
        if port.isdigit():
            return "tcp", (host or "127.0.0.1", int(port))
    return "unix", address


def connect(address: str, timeout_s: float = 10.0) -> socket.socket:
    kind, target = parse_address(address)
    if kind == "tcp":
        return socket.create_connection(target, timeout=timeout_s)
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout_s)
    s.connect(target)
    return s
