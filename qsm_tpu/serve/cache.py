"""Verdict cache — duplicate submissions answered in O(1), survivably.

Online monitoring re-submits identical histories constantly (the same
trace window re-checked after a retry, N replicas reporting the same
interleaving), and a verdict is a pure function of (spec, history) —
the scheduler plane's fingerprint discipline (sched/systematic.py)
already treats a history's canonical identity as THE dedup key.  This
module banks (verdict, witness) under that identity:

* **Key** — :func:`fingerprint_key`: sha256 over the canonical JSON of
  ``(spec.name, spec_kwargs, history.fingerprint())``.  The history
  fingerprint is core/history.py's one canonical identity site, so an
  Op field added later changes cache keys together with every other
  identity comparison in the repo.
* **Memory** — bounded LRU (``max_entries``); hit moves to MRU.
* **Disk** — an APPEND-ONLY JSONL bank (header + one row per banked
  put; later rows for a key supersede earlier ones on load).  Each
  dispatch batch appends its rows with ONE fsync via :meth:`put_many`
  — O(batch), not O(entries): the worker-pool bench showed a
  full-bank rewrite per batch serializing the whole serving plane
  behind the cache lock (4022 → 988 h/s at 2 workers × 4 clients).
  When the log grows past twice the live set it is COMPACTED through
  ``resilience.checkpoint.atomic_write_text`` (header + live entries,
  atomic rename).  Crash-safety is per-row: a server killed mid-append
  tears at most the trailing line, which the loader drops — every
  earlier banked verdict (and witness) survives and a restart serves
  it without re-searching (tests/test_serve.py pins
  kill-restart-serve; tests/test_serve_pool.py the pooled twin).
* **Honesty** — only DECIDED verdicts (VIOLATION / LINEARIZABLE) are
  banked.  A BUDGET_EXCEEDED is an engine-relative statement, not a
  property of the history; banking it would freeze "undecided" past
  engine upgrades.
* **Fleet** — with ``store=`` a :class:`~qsm_tpu.fleet.replog.
  SegmentedLog` replaces the single file: same append/compact
  discipline, but the bank becomes content-fingerprinted SEGMENTS a
  fleet replicates via anti-entropy (docs/SERVING.md "Fleet");
  :meth:`VerdictCache.adopt_rows` folds replicated rows into the live
  set without re-banking them.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import List, Optional

from ..core.history import History

_ARTIFACT = "qsm_tpu_verdict_cache"
_VERSION = 1


def fingerprint_key(spec, history: History) -> str:
    """Canonical cache identity of (spec instance, observable history)."""
    doc = [spec.name, spec.spec_kwargs(), list(history.fingerprint())]
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True, default=list).encode()).hexdigest()


@dataclasses.dataclass
class CacheEntry:
    verdict: int
    witness: Optional[List[tuple]] = None  # [(op_index, resp), ...]
    hits: int = 0


class VerdictCache:
    """Bounded LRU with an atomic persistent JSONL bank (see module
    docstring).  Thread-safe: the server's connection threads and the
    batcher's dispatch thread share one instance."""

    def __init__(self, max_entries: int = 4096, path: Optional[str] = None,
                 persist_every: int = 1, store=None):
        self.max_entries = max_entries
        self.path = path
        # the fleet tier's segmented bank (fleet/replog.py SegmentedLog):
        # when set, persistence routes through the store's append/
        # compact/load contract instead of the single-file log — the
        # bank becomes replicable segment-by-segment while this class
        # keeps owning WHAT is banked (decided verdicts, post-merge
        # rows, later-row-wins)
        self.store = store
        self.persist_every = max(1, persist_every)
        self._od: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.compactions = 0
        self.bank_appends = 0  # append+fsync flushes (obs metrics feed)
        self.adopted = 0       # rows folded in from replicated segments
        self._puts_since_flush = 0
        self._dirty: List[str] = []   # banked rows awaiting one append
        self._file_rows = 0           # rows in the on-disk log
        self._file_exists = False
        if store is not None:
            self._load_store()
        elif path:
            self._load(path)

    # ------------------------------------------------------------------
    def get(self, key: str) -> Optional[CacheEntry]:
        with self._lock:
            e = self._od.get(key)
            if e is None:
                self.misses += 1
                return None
            self._od.move_to_end(key)
            e.hits += 1
            self.hits += 1
            return e

    def put(self, key: str, verdict: int,
            witness: Optional[List[tuple]] = None) -> None:
        with self._lock:
            if not self._put_locked(key, verdict, witness):
                return
            self._puts_since_flush += 1
            if (self._persistent
                    and self._puts_since_flush >= self.persist_every):
                self._flush_locked()

    def put_many(self, items) -> None:
        """Bank ``(key, verdict, witness)`` triples with ONE atomic
        flush for the whole batch: a flush is an O(entries) full-bank
        rewrite, so a 64-lane dispatch must pay it once, not 64 times
        (and every ``get`` on every connection thread blocks on the
        lock meanwhile)."""
        with self._lock:
            wrote = False
            for key, verdict, witness in items:
                wrote = self._put_locked(key, verdict, witness) or wrote
            if wrote and self._persistent:
                self._flush_locked()

    def _put_locked(self, key: str, verdict: int,
                    witness: Optional[List[tuple]]) -> bool:
        if verdict not in (0, 1):
            return False  # never bank BUDGET_EXCEEDED (module docstring)
        e = self._od.get(key)
        if e is not None:
            # keep a banked witness when the refresh has none (a
            # verdict-only re-check must not degrade the bank)
            if witness is not None:
                e.witness = list(witness)
            e.verdict = verdict
            self._od.move_to_end(key)
        else:
            e = self._od[key] = CacheEntry(
                verdict=verdict,
                witness=list(witness) if witness is not None else None)
            while len(self._od) > self.max_entries:
                self._od.popitem(last=False)
        if self._persistent:
            # serialize the POST-merge entry (not the put's arguments):
            # the last row for a key wins on load, so a verdict-only
            # refresh row must still carry the banked witness
            self._dirty.append(json.dumps(
                {"key": key, "verdict": e.verdict,
                 "witness": ([list(p) for p in e.witness]
                             if e.witness is not None else None)}))
        return True

    @property
    def _persistent(self) -> bool:
        return self.store is not None or bool(self.path)

    def flush(self) -> None:
        with self._lock:
            if self._persistent:
                self._flush_locked()

    def adopt_rows(self, rows) -> int:
        """Fold replicated rows (fleet/replog.py segment adoption) into
        the live set WITHOUT re-banking: the rows are already durable in
        the adopted segment, so marking them dirty would bank each
        verdict twice.  An existing entry only gains a witness it was
        missing — local rows stay authoritative (later-row-wins is a
        local ordering; a remote row for the same key can only agree on
        the verdict, verdicts being pure functions of (spec, history)).
        Returns rows actually folded in."""
        n = 0
        with self._lock:
            for row in rows:
                key, verdict = row.get("key"), row.get("verdict")
                if not key or verdict not in (0, 1):
                    continue
                w = row.get("witness")
                e = self._od.get(key)
                if e is not None:
                    if e.witness is None and w is not None:
                        e.witness = [tuple(p) for p in w]
                        n += 1
                    continue
                self._od[key] = CacheEntry(
                    verdict=verdict,
                    witness=[tuple(p) for p in w] if w is not None
                    else None)
                n += 1
                while len(self._od) > self.max_entries:
                    self._od.popitem(last=False)
            self.adopted += n
        return n

    def holds_all(self, keys) -> bool:
        """True iff EVERY key is in the live set — the row-level
        subsumption gate (fleet/gossip.py, the ``replog.subsumed``
        op): a segment whose keys are all held need not ship its rows.
        Pure containment: no hit/miss accounting, no LRU touch (a
        coverage probe must not keep cold entries artificially hot).
        An empty key list is NOT coverage — there is nothing to
        subsume, so the segment ships and the fingerprint check
        decides."""
        keys = list(keys)
        if not keys:
            return False
        with self._lock:
            return all(k in self._od for k in keys)

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            out = {"entries": len(self._od), "hits": self.hits,
                   "misses": self.misses,
                   "hit_rate": round(self.hits / total, 3) if total else 0.0,
                   "bank_rows": self._file_rows,
                   "bank_appends": self.bank_appends,
                   "compactions": self.compactions,
                   "adopted": self.adopted,
                   "path": self.path}
        if self.store is not None:
            out["replog"] = self.store.snapshot()
        return out

    # ------------------------------------------------------------------
    def _flush_locked(self) -> None:
        """Persist pending rows: ONE append+fsync per call (O(batch)).
        The log compacts to an atomic header+live-entries rewrite when
        it grows past twice the live set — appends must never turn the
        bank into an unbounded file."""
        if not self._dirty:
            self._puts_since_flush = 0
            return
        live = len(self._od)
        if self.store is not None:
            # segmented bank (fleet/replog.py): O(batch) append into
            # the active segment; when the fleet-wide row count
            # outgrows the live set, fold into ONE fresh segment (the
            # store remembers what it absorbed, so anti-entropy never
            # re-pulls the compacted-away segments)
            if (self.store.total_rows + len(self._dirty)
                    > max(2 * live, self.max_entries)):
                self.store.compact(self._live_lines())
                self.compactions += 1
            else:
                self.store.append(self._dirty)
                self.bank_appends += 1
            self._file_rows = self.store.total_rows
            self._dirty.clear()
            self._puts_since_flush = 0
            return
        if (not self._file_exists
                or self._file_rows + len(self._dirty)
                > max(2 * live, self.max_entries)):
            self._compact_locked()
        else:
            with open(self.path, "a") as f:
                f.write("\n".join(self._dirty) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._file_rows += len(self._dirty)
            self.bank_appends += 1
        self._dirty.clear()
        self._puts_since_flush = 0

    def _live_lines(self) -> List[str]:
        """The live set serialized in LRU order (oldest first — append
        order IS recency order on reload, like the single-file bank)."""
        return [json.dumps({"key": k, "verdict": e.verdict,
                            "witness": ([list(p) for p in e.witness]
                                        if e.witness is not None
                                        else None)})
                for k, e in self._od.items()]

    def _compact_locked(self) -> None:
        from ..resilience.checkpoint import atomic_write_text

        header = {"artifact": _ARTIFACT, "version": _VERSION,
                  "entries": len(self._od)}
        rows = self._live_lines()
        atomic_write_text(self.path,
                          "\n".join([json.dumps(header)] + rows) + "\n")
        self._file_rows = len(rows)
        self._file_exists = True
        self.compactions += 1

    def _load_store(self) -> None:
        """Adopt the segmented bank's merged row stream (fleet/replog.py
        handles torn tails and corrupt segments itself — what arrives
        here is clean).  Later rows supersede earlier ones, exactly
        like the single-file load."""
        for row in self.store.load():
            key, verdict = row.get("key"), row.get("verdict")
            if not key or verdict not in (0, 1):
                continue
            w = row.get("witness")
            self._od[key] = CacheEntry(
                verdict=verdict,
                witness=[tuple(p) for p in w] if w is not None else None)
            self._od.move_to_end(key)
        while len(self._od) > self.max_entries:
            self._od.popitem(last=False)
        self._file_rows = self.store.total_rows

    def _load(self, path: str) -> None:
        """Adopt a prior bank; CellJournal's tolerance rules — a garbled
        or truncated tail is dropped (those entries simply re-check), an
        alien header adopts nothing but is preserved aside.  The bank is
        an append log: a LATER row for a key supersedes earlier ones."""
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            return
        docs = []
        # torn = the file does not END at a clean line boundary: either
        # a garbled/unparsable line, or a final line that parses but
        # has no trailing newline (the kill landed after the payload
        # bytes, before the '\n' — still not appendable-after)
        torn = not text.endswith("\n")
        for ln in text.splitlines():
            if not ln.strip():
                continue
            try:
                docs.append(json.loads(ln))
            except ValueError:
                torn = True
                break  # truncated/garbled: trust nothing at or past it
        if not docs:
            return
        if docs[0].get("artifact") != _ARTIFACT:
            try:  # not ours: preserve, never adopt or clobber
                os.replace(path, f"{path}.pre-resume")
            except OSError:
                pass
            return
        # appending after a torn tail would weld the first new row onto
        # the partial line and poison every later load.  Leaving
        # _file_exists False forces the next flush to COMPACT (atomic
        # full rewrite), which re-establishes a clean line boundary.
        self._file_exists = not torn
        self._file_rows = len(docs) - 1
        for row in docs[1:]:
            key, verdict = row.get("key"), row.get("verdict")
            if not key or verdict not in (0, 1):
                continue
            w = row.get("witness")
            self._od[key] = CacheEntry(
                verdict=verdict,
                witness=[tuple(p) for p in w] if w is not None else None)
            self._od.move_to_end(key)  # append order IS recency order
        while len(self._od) > self.max_entries:
            self._od.popitem(last=False)
