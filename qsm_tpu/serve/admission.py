"""Admission control — bounded load, explicit shedding.

The serving plane's failure mode under overload must be a fast, honest
``SHED`` response, never silent latency collapse (a queue that grows
without bound converts overload into unbounded tail latency and then
into wrong-looking timeouts for EVERY client).  One controller instance
gates the whole server:

* **Bounded in-flight lanes** — ``try_admit(n)`` reserves ``n`` history
  lanes against ``queue_depth`` or refuses atomically (no partial
  admission: a request is whole or shed).  The QSM-SERVE-UNBOUNDED lint
  pass (analysis/serve_passes.py) gates the code-level twin of this
  rule — no unbounded queue constructions in the serve plane.
* **Per-request deadline** — defaulted from the ``serve``
  :data:`~qsm_tpu.resilience.policy.PRESETS` entry (ONE timeout table
  for the whole stack); a request past its deadline is answered
  ``SHED``, and its still-in-flight lanes complete into the verdict
  cache rather than being wasted.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..resilience.policy import RetryPolicy, preset


class AdmissionController:
    """Lane accounting + shed counters (one site the server and the
    ``stats`` op both read).

    ``pool_state`` (optional) is a zero-arg provider of the worker
    pool's compact health block (``serve/pool.py shed_state``).  When
    set, every SHED response carries it: "queue full" against a pool
    running 1-of-4 workers is a degradation story, not an overload
    story, and the client deciding whether to back off or fail over
    needs to tell them apart.

    ``fleet_state`` (optional) is the fleet tier's twin
    (``fleet/membership.py shed_state``): a router's SHED under
    cross-fleet backpressure carries the per-node health block — live/
    quarantined node counts and the shedding node's own id — so "the
    fleet is overloaded" and "the fleet is down to one node" read
    differently to the client and the operator.
    """

    def __init__(self, queue_depth: int = 1024,
                 policy: Optional[RetryPolicy] = None,
                 pool_state: Optional[Callable[[], dict]] = None,
                 fleet_state: Optional[Callable[[], dict]] = None):
        self.queue_depth = queue_depth
        self.policy = policy or preset("serve")
        self.pool_state = pool_state
        self.fleet_state = fleet_state
        self._lock = threading.Lock()
        self.in_flight = 0
        self.peak_in_flight = 0
        self.admitted_lanes = 0
        self.completed_lanes = 0
        self.shed_queue = 0     # requests refused at admission (full)
        self.shed_deadline = 0  # requests answered SHED past deadline

    # ------------------------------------------------------------------
    def deadline_for(self, deadline_s: Optional[float]) -> float:
        """Absolute monotonic deadline for a request; ``None`` takes the
        preset's default."""
        d = self.policy.deadline_s if deadline_s is None else deadline_s
        return time.monotonic() + max(0.0, float(d))

    def try_admit(self, n_lanes: int) -> bool:
        with self._lock:
            if self.in_flight + n_lanes > self.queue_depth:
                self.shed_queue += 1
                return False
            self.in_flight += n_lanes
            self.peak_in_flight = max(self.peak_in_flight, self.in_flight)
            self.admitted_lanes += n_lanes
            return True

    def release(self, n_lanes: int = 1) -> None:
        with self._lock:
            self.in_flight -= n_lanes
            self.completed_lanes += n_lanes

    def shed_late(self) -> None:
        """Count a deadline shed (the lanes release on completion)."""
        with self._lock:
            self.shed_deadline += 1

    def shed_doc(self, req_id, reason: str, trace: Optional[str] = None,
                 flight: Optional[str] = None) -> dict:
        """THE shed response payload (serve/protocol.py's refusal
        contract): explicit reason, plus the pool-state block when a
        worker pool serves this plane.  ``trace`` is the request's
        trace id and ``flight`` the most recent flight-recorder dump
        path (when one fired) — a shed client hands the operator
        something actionable, not a bare SHED."""
        doc = {"id": req_id, "ok": False, "shed": True, "reason": reason}
        if trace:
            doc["trace"] = trace
        if flight:
            doc["flight"] = flight
        if self.pool_state is not None:
            state = self.pool_state()
            if state:
                doc["pool"] = state
        if self.fleet_state is not None:
            state = self.fleet_state()
            if state:
                doc["fleet"] = state
        return doc

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            snap = {"queue_depth": self.queue_depth,
                    "in_flight": self.in_flight,
                    "peak_in_flight": self.peak_in_flight,
                    "admitted_lanes": self.admitted_lanes,
                    "completed_lanes": self.completed_lanes,
                    "shed_queue": self.shed_queue,
                    "shed_deadline": self.shed_deadline,
                    "policy": self.policy.name}
        if self.pool_state is not None:
            state = self.pool_state()
            if state:
                snap["pool"] = state
        if self.fleet_state is not None:
            state = self.fleet_state()
            if state:
                snap["fleet"] = state
        return snap
