"""Mesh/sharding for batch-parallel checking at scale (SURVEY.md §2b, §5)."""

from .mesh import batch_sharding, make_mesh, replicated_sharding
