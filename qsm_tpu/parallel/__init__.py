"""DEPRECATED — the mesh/sharding helpers moved to :mod:`qsm_tpu.mesh`.

This package was the dormant home of the mesh construction helpers before
ISSUE 19 promoted them into the full mesh-sharded dispatch substrate
(``qsm_tpu/mesh/``: topology + dispatch policy + the one-call
``sharded_backend``).  It remains ONLY as a thin re-export so existing
imports keep working; no mesh logic lives here.  New code imports from
``qsm_tpu.mesh``.  Pinned by tests/test_parallel.py; removal is fair game
once in-tree importers are gone.
"""

from ..mesh.topology import (batch_sharding, init_distributed, make_mesh,
                             make_mesh_2d, replicated_sharding)

__all__ = [
    "batch_sharding",
    "init_distributed",
    "make_mesh",
    "make_mesh_2d",
    "replicated_sharding",
]
