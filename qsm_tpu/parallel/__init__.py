"""Mesh/sharding for batch-parallel checking at scale (SURVEY.md §2b, §5)."""

from .mesh import (batch_sharding, init_distributed, make_mesh, make_mesh_2d,
                   replicated_sharding)
