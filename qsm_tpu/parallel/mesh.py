"""Device mesh + batch-axis sharding for the checker plane.

The reference's distribution story is actor messaging (distributed-process
over network-transport-*, SURVEY.md §5 comm backend); its checker is pure and
single-threaded.  Our checker plane instead scales the *batch axis* of the
linearisation kernel over a ``jax.sharding.Mesh``: trials and shrink
candidates are independent (SURVEY.md §2b "trial/batch parallelism"), so the
natural mapping is data parallelism — shard histories over devices, replicate
the (tiny) spec state, and let XLA place everything with zero collectives in
the hot loop (verdict gather rides the ICI at the end of the batch).

Single chip needs none of this; the helpers here exist so the SAME kernel
runs unchanged from v5e-1 to a full pod slice: ``pjit``-style sharding comes
entirely from ``NamedSharding`` annotations on the inputs.
"""

from __future__ import annotations

from typing import Optional, Sequence


def make_mesh(n_devices: Optional[int] = None, axis: str = "batch"):
    """A 1-D device mesh over the first ``n_devices`` devices (all by
    default).  The single axis is the history-batch axis."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.asarray(devs), (axis,))


def batch_sharding(mesh, axis: Optional[str] = None):
    """NamedSharding placing dim 0 (the batch) over the mesh axis."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.NamedSharding(mesh, P(axis or mesh.axis_names[0]))


def replicated_sharding(mesh):
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.NamedSharding(mesh, P())
