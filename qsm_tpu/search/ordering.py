"""Postcondition-aware candidate ordering.

Wing–Gong tries candidate ops in a canonical order (array index, both in
the oracle's ``for j in range(n)`` and the kernel's ``argmax`` over the
candidate mask).  That order is blind: a branch that linearises an
unconstrained op (a write — its postcondition holds in every state) ahead
of a constrained one (a read of a specific value) discovers the conflict
only deep in the subtree, after paying its whole expansion.  Ranking the
constrained ops FIRST makes branches that must fail their postcondition
die at depth 1: either the constrained op is linearisable now (taking it
prunes the state space most) or it is not, and the contradiction surfaces
before the subtree is paid for.

The rank is the op's **selectivity**: the fraction of model states in
which its ``step`` postcondition holds, computed from the same scalar
step tabulation the kernel's gather path uses
(``core.spec.compile_selectivity_table``, compiled alongside
``compile_step_table``).  For CAS: ``read(v)`` and a succeeding ``cas``
pass in 1/n_values states (rank ~0.2 — first), a failing ``cas`` in
(n-1)/n, a ``write`` always (rank 1.0 — last).  Vector specs rank
through their scalarized shadow when one exists; specs with no scalar
domain get no table and keep the canonical order.

Consumption is HOST-SIDE permutation: ops are reordered before encoding,
so the kernel's argmax and the oracle's index loop both try candidates in
rank order with zero per-iteration cost.  Linearizability is invariant
under op-array permutation (the precedence partial order rides the ops'
own timestamps), so verdicts cannot change — only iteration counts do;
tests/test_search.py pins both claims.  Witness indices are mapped back
through the permutation by the caller (ops/jax_kernel.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.history import History
from ..core.spec import Spec, compile_selectivity_table


class OrderingTable:
    """Per-spec selectivity ranks over (cmd, arg, resp)."""

    def __init__(self, sel: np.ndarray, spec_name: str):
        self.sel = sel  # float64[n_cmds, max_args, max_resps] in [0, 1]
        self.spec_name = spec_name

    def rank(self, cmd: int, arg: int, resp: int) -> float:
        """Selectivity of one op; lower = more constrained = tried first.

        Out-of-domain cmd/arg/resp (SUTs can return anything) rank 0.0:
        such an op's postcondition holds in no tabulated state, so it is
        maximally constrained — fronting it surfaces the contradiction
        immediately.
        """
        c, a, r = self.sel.shape
        if not (0 <= cmd < c and 0 <= arg < a):
            return 0.0
        if resp < 0:  # pending: completion may pick any response
            return float(self.sel[cmd, arg].mean())
        if resp >= r:
            return 0.0
        return float(self.sel[cmd, arg, resp])

    def permutation(self, history: History) -> np.ndarray:
        """Stable try-order permutation: ``permuted_ops[k] =
        ops[perm[k]]``.  Ties keep invocation order (the ops list's own
        order), so the permutation — and with it every downstream
        iteration count — is deterministic."""
        ops = history.ops
        order = sorted(range(len(ops)),
                       key=lambda j: (self.rank(ops[j].cmd, ops[j].arg,
                                               ops[j].resp), j))
        return np.asarray(order, np.intp)


def permute_history(history: History, perm: Sequence[int]) -> History:
    """Reorder a history's op array (timestamps — and therefore the
    precedence partial order — ride along untouched)."""
    return History([history.ops[j] for j in perm],
                   seed=history.seed, program_id=history.program_id)


def ordering_table(spec: Spec) -> Optional[OrderingTable]:
    """The spec's selectivity table, or None when it has no scalar domain
    to tabulate (ordering then stays off — the canonical order is kept).

    Vector specs with declared element bounds rank through their
    scalarized shadow (ops/scalarize.py): same CMDS, same step semantics,
    scalar domain.
    """
    target = spec
    if spec.STATE_DIM != 1:
        from ..ops.scalarize import scalar_shadow

        target = scalar_shadow(spec)
        if target is None:
            return None
    # 128 is the largest op bucket (core/history.py OP_BUCKETS); specs
    # whose bound grows with history length (ticket) are covered to there
    bound = target.scalar_state_bound(128)
    if bound is None or bound <= 0:
        return None
    sel = compile_selectivity_table(target, int(bound))
    return OrderingTable(sel, spec.name)


def order_indices(table: Optional[OrderingTable],
                  history: History) -> List[int]:
    """Try order for a host-side DFS over ``history.ops`` — identity when
    no table applies.  (The oracle consumes ranks this way; the kernel
    permutes the encoded arrays instead.)"""
    if table is None:
        return list(range(len(history.ops)))
    return [int(j) for j in table.permutation(history)]
