"""qsm_tpu.search — the search-efficiency plane.

The checker stack has two cost axes.  The throughput axis (ops/, the
kernels) decides how fast one lockstep ITERATION runs; this package owns
the other axis — how many iterations a verdict NEEDS.  The round-4/5
windows measured the gap: the banked device headline paid ~182k lockstep
iterations per history while the memoised host oracle decided the same
corpus exploring ~10²–10³ nodes.  That multiplier is search order,
memoisation coverage, and decomposition — hardware-independent, and
measurable on the CPU platform with the engines' existing counters.

Three modules:

* :mod:`~qsm_tpu.search.stats`    — ``SearchStats``, the first-class cost
  record every engine exposes (``search_stats()``) and every bench row
  carries;
* :mod:`~qsm_tpu.search.ordering` — postcondition-aware candidate
  ordering: per-spec selectivity tables (compiled next to the step
  tables, core/spec.py) ranking ops so branches that must fail their
  postcondition die at depth 1;
* :mod:`~qsm_tpu.search.planner`  — ``SearchPlan`` + ``plan_search``:
  chunk schedule, batch buckets, memo-slot policy, ordering and
  decomposition modes picked from corpus statistics and platform,
  replacing the hand-tuned tuples in ops/jax_kernel.py.

Verdict contract: nothing in this package may change a verdict — only
iteration/node counts.  tests/test_search.py pins bit-identical verdicts
across every engine with the plan on and off, and pins the ≥10×
iters-per-history win on the CAS-32 bench corpus.
"""

from .ordering import OrderingTable, ordering_table, permute_history
from .planner import (CorpusProfile, SearchPlan, build_backend,
                      build_host_backend, plan_search, profile_corpus)
from .stats import SearchStats, collect_search_stats

__all__ = [
    "CorpusProfile",
    "OrderingTable",
    "SearchPlan",
    "SearchStats",
    "build_backend",
    "build_host_backend",
    "collect_search_stats",
    "ordering_table",
    "permute_history",
    "plan_search",
    "profile_corpus",
]
