"""The search planner — corpus statistics in, ``SearchPlan`` out.

The chunked device driver (ops/jax_kernel.py) has been steered by
hand-tuned class constants since round 3: ``CHUNK_SCHEDULE``,
``MAX_SLOTS_FOR_BATCH``, the module-level batch buckets.  Those tuples
encode one trade (few compiles, wide lockstep batches) that the round-5
window priced precisely: ~182k lockstep iterations per history while the
host oracle explored ~10²–10³ nodes — cache starvation at 4096 lanes ×
32 memo slots plus lockstep spin in coarse chunks, not step throughput.

``plan_search`` replaces the hand tuning with a policy computed from
what is actually known:

* **platform** — the empirical (batch × cache-slots) safe region is a
  property of the axon TPU stack, NOT of the algorithm; on the CPU
  platform there is no crash region, so the plan grants every bucket the
  full-size memo table and fine-grained buckets down to single-lane
  (measured on the CAS-32 bench corpus: starved 32-slot tables cost
  17.9k iters/history where 4096-slot tables cost 0.8k — the whole
  starvation story reproduced off-chip).
* **corpus statistics** (``profile_corpus``) — mean quiescent-cut
  density decides decomposition (wrap the kernel in the segdc
  combinator: exhaustion cost is exponential in segment length, so
  histories that cut should never be searched whole); history length
  sets the first chunk (a shorter first chunk than the minimum depth of
  a success path can decide nothing).
* **spec** — ordering mode is on exactly when the spec has a scalar
  domain to rank against (search/ordering.py).

The early-compaction policy for the device platform is carried by the
schedule itself: the first chunk is SMALL (256), so the starved
widest-bucket stage ends within one chunk and survivors re-hash into the
large-cache buckets at the FIRST compaction — the round-5 window ran
(2048, 65536) and paid the 32-slot stage for 2048 iterations straight.

Verdict contract: a plan changes iteration counts only.  Budgets are not
part of the plan; the driver's honest BUDGET_EXCEEDED/oracle-resolution
semantics are untouched (tests/test_search.py pins verdict parity with
planning on and off across every engine).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

from ..core.history import History
from .ordering import ordering_table

# CPU platform: no crash region — fine buckets to single-lane (a straggler
# exhausting a violation tree pays bucket-width per iteration; at bucket 8
# the round-5 tail was 8× the work it needed) and full-size memo tables
# everywhere.  Wall-clock cost of the extra compiles is real but paid once
# per process; tests/bench warm explicitly.
_CPU_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
                16384, 65536, 262144)
_CPU_SLOTS = 4096
# ×2 geometric escalation from just past the 32-op success-path depth:
# measured on CAS-32 (128 histories, CPU platform) against the hand-tuned
# (256, 2048, 16384, 65536): 1839 → 440 iters/history kernel-only, 143
# with ordering + decomposition (tools/bench_search.py artifact).
_CPU_SCHEDULE = (48, 96, 192, 384, 768, 1536, 3072, 6144, 12288, 24576,
                 49152)

# Device platform: the verified safe region stands exactly as measured
# (ops/jax_kernel.py MAX_SLOTS_FOR_BATCH provenance); the plan's lever is
# the schedule — a small first chunk ends the starved wide stage early.
_TPU_BUCKETS = (8, 64, 256, 1024, 4096, 16384, 65536, 262144)
_TPU_SLOTS = {8: 8192, 64: 4096, 256: 512, 1024: 128, 4096: 32,
              16384: 8, 65536: 2, 262144: 0}
_TPU_SCHEDULE = (256, 2048, 16384, 65536)

# Decomposition gate: below this mean-segments-per-history the cut scan
# is overhead on a corpus that mostly cannot cut.  1.15 ≈ "at least one
# history in 7 cuts once"; the CAS-32 bench corpus profiles at ~1.69.
_DECOMPOSE_MEAN_SEGMENTS = 1.15
# The DECOMPOSED-corpus twin (ROADMAP item 3 leftover): with per-key
# decomposition on, the inner kernel only ever sees sub-histories, so
# the segdc gate must be measured on THEM, not on the whole corpus —
# whole-history segment density systematically understates the split
# shape (per-key sub-histories are sparser in time, so quiescent cuts
# are denser).  Measured on the r10 corpora: kv-16-keys × 16-pids
# sub-histories profile at 1.65 mean segments/sub at 64 ops and 4.26 at
# 256 ops (whole: 1.44 / 2.25); multireg-64 subs at 1.77.  The gate
# sits HIGHER than the whole-history one because a cut on an
# already-short sub-history buys less (exhaustion cost is exponential
# in segment length, and the split already shortened the segments):
# 1.35 ≈ "at least one sub-history in 3 cuts once", comfortably below
# every measured decomposed corpus and above non-cutting ones.
# Pinned by tests/test_shrink.py::test_planner_sub_segment_gate.
_DECOMPOSE_MEAN_SEGMENTS_SUB = 1.35


@dataclasses.dataclass(frozen=True)
class CorpusProfile:
    """What the planner is allowed to know about the workload."""

    n: int = 0
    max_ops: int = 0
    mean_ops: float = 0.0
    pending_fraction: float = 0.0
    cut_fraction: float = 0.0    # histories with ≥1 quiescent cut
    mean_segments: float = 1.0   # segments per history
    # per-key decomposition shape (only measured when profile_corpus is
    # given a spec whose projection VALIDATES — ops/pcomp.py): the
    # longest per-key sub-history across the corpus, and the mean number
    # of keys a history splits into.  sub_max_ops == 0 means "not
    # measured" — the decompose_keys gate then stays off.
    sub_max_ops: int = 0
    mean_partitions: float = 0.0
    # segment density OF THE SUB-HISTORIES (segments per per-key
    # sub-history): what the inner kernel actually sees when
    # decompose_keys is on — the segdc gate must be judged on this, not
    # on the whole-history density above (ROADMAP item 3 leftover).
    # 0.0 means "not measured" (no spec / invalid projection).
    sub_mean_segments: float = 0.0


def profile_corpus(histories: Sequence[History],
                   spec=None) -> CorpusProfile:
    """Corpus statistics; pass ``spec`` to also measure the per-key
    decomposition shape (sub-history lengths) when the spec declares a
    valid projection — the planner's ``decompose_keys`` gate needs it."""
    from ..ops.segdc import split_at_quiescent_cuts

    if not histories:
        return CorpusProfile()
    lens = [len(h) for h in histories]
    segs = [len(split_at_quiescent_cuts(h)) for h in histories]
    sub_max = 0
    mean_parts = 0.0
    sub_mean_segs = 0.0
    if spec is not None:
        from ..core.spec import projection_report
        from ..ops.pcomp import split_history

        if not projection_report(spec):
            # ONE split per history yields all three decomposition
            # statistics: the longest sub-history (compile-bucket
            # gate), the key count, and the decomposed corpus's own
            # segment profile — what segdc would see UNDER the per-key
            # split (the decompose gate's input when decompose_keys
            # fires)
            parts = []
            sub_segs = []
            for h in histories:
                subs = split_history(spec, h)
                parts.append(len(subs))
                for s in subs.values():
                    sub_max = max(sub_max, len(s))
                    sub_segs.append(len(split_at_quiescent_cuts(s)))
            mean_parts = sum(parts) / len(histories)
            if sub_segs:
                sub_mean_segs = sum(sub_segs) / len(sub_segs)
    return CorpusProfile(
        n=len(histories),
        max_ops=max(lens),
        mean_ops=sum(lens) / len(histories),
        pending_fraction=(sum(h.n_pending > 0 for h in histories)
                          / len(histories)),
        cut_fraction=sum(s > 1 for s in segs) / len(histories),
        mean_segments=sum(segs) / len(histories),
        sub_max_ops=sub_max,
        mean_partitions=mean_parts,
        sub_mean_segments=sub_mean_segs,
    )


@dataclasses.dataclass(frozen=True)
class SearchPlan:
    """Everything the driver used to hard-code, plus the two search
    modes, with provenance.  Consumed by ``JaxTPU(plan=…)`` and
    ``build_backend``."""

    name: str
    chunk_schedule: Tuple[int, ...]
    batch_buckets: Tuple[int, ...]
    slots_for_batch: Dict[int, int]
    ordering: bool          # host-side selectivity permutation
    decompose: bool         # wrap the kernel in quiescent-cut segdc
    unroll: Optional[int]   # None = the driver's platform auto
    # P-compositional per-key decomposition as a FIRST plan stage
    # (ops/pcomp.py): on iff the spec's declared projection validates
    # AND the corpus profile shows sub-histories landing in smaller
    # compile buckets than the whole histories.  Outermost in
    # build_backend — per-key sub-histories are sparser, so the
    # quiescent-cut stage under it cuts more often.
    decompose_keys: bool = False
    # Mesh shape this plan was sized for: bucket ladders are filtered to
    # widths divisible by it (qsm_tpu/mesh/dispatch.py) and it is part of
    # the plan's NAME — plan identity IS compile-bucket identity (the name
    # rides SearchStats.plan into artifacts), so a 1-chip plan can never
    # be mistaken for an 8-chip one downstream.
    mesh_devices: int = 1
    why: Tuple[str, ...] = ()

    def describe(self) -> Dict:
        return {
            "name": self.name,
            "chunk_schedule": list(self.chunk_schedule),
            "buckets": len(self.batch_buckets),
            "max_slots": max(self.slots_for_batch.values(), default=0),
            "ordering": self.ordering,
            "decompose": self.decompose,
            "decompose_keys": self.decompose_keys,
            "unroll": self.unroll,
            "mesh_devices": self.mesh_devices,
            "why": list(self.why),
        }


def _plan_decompose_keys(spec, profile: Optional[CorpusProfile]
                         ) -> Tuple[bool, str]:
    """The per-key decomposition gate with its ``why`` line: on iff the
    declared projection VALIDATES (an invalid one refuses loudly here —
    never a silent unsound split) and the profiled sub-histories land in
    strictly smaller compile buckets than the whole histories."""
    from ..core.spec import projection_report
    from ..ops.pcomp import bucket_or_none

    problems = projection_report(spec)
    if problems:
        # refusal with provenance: the plan SAYS why it would not split
        return False, ("decompose_keys=off (refused: "
                       f"{problems[0]})")
    if profile is None or not profile.n or not profile.sub_max_ops:
        return False, ("decompose_keys=off (projection valid but no "
                       "sub-history profile for this corpus)")
    whole = bucket_or_none(profile.max_ops)
    sub = bucket_or_none(profile.sub_max_ops)
    if sub is None:
        return False, (f"decompose_keys=off (sub-histories up to "
                       f"{profile.sub_max_ops} ops fit no op bucket)")
    if whole is not None and sub >= whole:
        return False, (f"decompose_keys=off (sub bucket {sub} >= whole "
                       f"bucket {whole}: the split only adds lanes)")
    return True, (
        f"decompose_keys=on (sub-histories <= {profile.sub_max_ops} ops "
        f"fit bucket {sub} vs whole "
        + (f"bucket {whole}" if whole is not None
           else f"max {profile.max_ops} ops past every bucket")
        + f"; mean {profile.mean_partitions:.1f} keys/history)")


def plan_search(spec, profile: Optional[CorpusProfile] = None,
                platform: Optional[str] = None,
                mesh_devices: int = 1) -> SearchPlan:
    """Pick the search plan for ``spec`` on ``platform`` ("cpu"/"tpu"; None
    = whatever jax's default backend reports) given optional corpus
    statistics.  Pure policy — constructs no backend and touches no
    device.  ``mesh_devices > 1`` sizes the plan for a mesh of that many
    devices: bucket ladders filter to mesh-divisible widths and the plan
    name gains an ``@meshN`` suffix (per-mesh-shape compile buckets —
    a 1-chip plan must never serve an 8-chip mesh)."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    on_device = platform not in ("cpu",)
    mesh_devices = max(1, int(mesh_devices))
    why = []

    orderable = ordering_table(spec) is not None
    why.append(f"ordering={'on' if orderable else 'off'} "
               f"({spec.name} {'has' if orderable else 'lacks'} a scalar "
               f"selectivity domain)")

    decompose_keys, dk_why = _plan_decompose_keys(spec, profile)

    decompose = False
    if profile is not None and profile.n:
        if decompose_keys and profile.sub_mean_segments:
            # with the per-key split on, the inner kernel only ever
            # sees sub-histories — the segdc gate is judged on THEIR
            # segment density, against the decomposed-corpus threshold
            # (whole-history density understates the split shape)
            decompose = (profile.sub_mean_segments
                         >= _DECOMPOSE_MEAN_SEGMENTS_SUB)
            why.append(
                f"decompose={'on' if decompose else 'off'} "
                f"(mean {profile.sub_mean_segments:.2f} segments/"
                f"sub-history under the per-key split over {profile.n} "
                f"histories; decomposed-corpus gate "
                f"{_DECOMPOSE_MEAN_SEGMENTS_SUB})")
        else:
            decompose = profile.mean_segments >= _DECOMPOSE_MEAN_SEGMENTS
            why.append(f"decompose={'on' if decompose else 'off'} "
                       f"(mean {profile.mean_segments:.2f} segments/history "
                       f"over {profile.n} histories)")
    else:
        why.append("decompose=off (no corpus profile)")
    why.append(dk_why)

    def _mesh_fit(name, buckets, slots):
        """Mesh-shape the plan: divisible buckets, matching slot table,
        ``@meshN`` name suffix (plan identity = compile-bucket identity)."""
        if mesh_devices == 1:
            return name, tuple(buckets), dict(slots)
        from ..mesh.dispatch import mesh_bucket_ladder, mesh_slots_table

        kept = mesh_bucket_ladder(buckets, mesh_devices)
        why.append(f"mesh_devices={mesh_devices}: bucket ladder filtered "
                   f"to mesh-divisible widths ({len(buckets)} -> "
                   f"{len(kept)} buckets)")
        return (f"{name}@mesh{mesh_devices}", kept,
                mesh_slots_table(slots, kept))

    if on_device:
        why.append("device platform: verified (batch × slots) safe region "
                   "kept; small first chunk ends the starved wide stage "
                   "at the first compaction")
        name, buckets, slots = _mesh_fit("tpu-safe-v1", _TPU_BUCKETS,
                                         _TPU_SLOTS)
        return SearchPlan(
            name=name,
            chunk_schedule=_TPU_SCHEDULE,
            batch_buckets=buckets,
            slots_for_batch=slots,
            ordering=orderable,
            decompose=decompose,
            decompose_keys=decompose_keys,
            unroll=8,
            mesh_devices=mesh_devices,
            why=tuple(why),
        )
    first = _CPU_SCHEDULE[0]
    sched = _CPU_SCHEDULE
    # with per-key decomposition on, the inner kernel only ever sees
    # sub-histories — sizing the schedule to the WHOLE corpus would
    # re-coarsen exactly what the split just bought
    eff_max = (profile.sub_max_ops if profile is not None and decompose_keys
               else profile.max_ops if profile is not None else 0)
    if eff_max > first:
        # a first chunk below the success-path depth decides nothing:
        # shift the whole geometric ladder up to cover the longest lane
        while first < eff_max:
            first *= 2
        sched = tuple(first * (1 << i) for i in range(len(_CPU_SCHEDULE)))
        why.append(f"first chunk {first} covers "
                   f"{'sub-history' if decompose_keys else ''} max_ops "
                   f"{eff_max}")
    why.append("cpu platform: no crash region — full-size memo tables, "
               "fine buckets to single-lane")
    name, buckets, slots = _mesh_fit(
        "cpu-fine-v1", _CPU_BUCKETS, {b: _CPU_SLOTS for b in _CPU_BUCKETS})
    return SearchPlan(
        name=name,
        chunk_schedule=sched,
        batch_buckets=buckets,
        slots_for_batch=slots,
        ordering=orderable,
        decompose=decompose,
        decompose_keys=decompose_keys,
        unroll=None,
        mesh_devices=mesh_devices,
        why=tuple(why),
    )


def build_backend(spec, plan: SearchPlan, budget: int = 2_000, **device_kw):
    """The planned checker: a ``JaxTPU`` honoring ``plan``, wrapped in the
    quiescent-cut segmentation combinator when the plan decomposes, and
    the whole ladder wrapped in the per-key decomposition combinator
    (``PComp``) when the plan splits per key — outermost, because per-key
    sub-histories are sparser and cut more often, so every inner stage
    benefits.  (Imports are local: the search plane must stay importable
    without jax for the pure-policy callers — lint, docs, profiling.)

    A mesh-sized plan (``plan.mesh_devices > 1``) implies a sharded
    engine: when the caller passes no explicit ``sharding=``, the lane
    sharding is derived here from a mesh of exactly that many devices, so
    plan and placement can never disagree (the plan's bucket ladder was
    filtered for that device count)."""
    from ..ops.jax_kernel import JaxTPU

    if plan.mesh_devices > 1 and device_kw.get("sharding") is None:
        from ..mesh.topology import batch_sharding, make_mesh

        device_kw["sharding"] = batch_sharding(
            make_mesh(plan.mesh_devices))
    if plan.mesh_devices > 1:
        # the planner seam (qsm_tpu/devq): a mesh-sized plan says the
        # device pays — bank a warmup item so the next seized window
        # pre-compiles this plan's @meshN bucket ladder.  No-op (and
        # no import cost beyond the cached module) without a queue.
        from ..devq.queue import note_device_plan

        note_device_plan(spec, plan)

    def make_core(s):
        if not plan.decompose:
            return JaxTPU(s, budget=budget, plan=plan, **device_kw)
        from ..ops.segdc import SegDC

        return SegDC(s, make_inner=lambda q: JaxTPU(q, budget=budget,
                                                    plan=plan, **device_kw))

    if plan.decompose_keys:
        from ..ops.pcomp import PComp

        return PComp(spec, make_inner=make_core)
    return make_core(spec)


def build_host_backend(spec, plan: SearchPlan):
    """The planned checker's HOST shape — the serving plane's ``auto``
    semantics as one construction site: ``PComp`` outermost over the
    exact cpp→memo host ladder when the plan splits per key, the ladder
    wrapped in ``FailoverBackend`` otherwise.  No device is touched and
    no compile bucket warmed; verdicts are bit-identical to the device
    path by the resolution contract.  Consumed by the shrink plane
    (qsm_tpu/shrink) and anything else that wants today's honest fast
    path driven by the same plan gates as :func:`build_backend`."""
    from ..resilience.failover import FailoverBackend, host_fallback

    if plan.decompose_keys:
        from ..ops.pcomp import PComp

        return PComp(spec, make_inner=host_fallback)
    return FailoverBackend(spec, host_fallback(spec))
