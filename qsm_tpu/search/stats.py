"""``SearchStats`` — search cost as a first-class artifact.

Every checker engine already keeps counters (the oracle's
``nodes_explored``, the device driver's ``lockstep_cost`` /
``compactions`` / ``rescued``, SegDC's segment tallies).  This module
gives them ONE record type and one collection entry point so the
property layer, bench.py, the CLI ``stats`` subcommand, and the round
artifacts all report the same fields — the iterations-per-history number
the north-star's ``vs_best_host`` gap decomposes into is never again
reconstructible only by hand from BENCH extras.

Semantics of the two headline fields:

* ``lockstep_iters`` — Σ (while-loop trips × padded batch width) over
  every device chunk call: what every lane PAYS under lockstep, not what
  it needed.  Host engines report 0.
* ``nodes_explored`` — host-side search nodes: oracle step evaluations
  plus SegDC middle-segment enumeration nodes.  Device engines report 0
  here; a hybrid/segdc composition reports both, side by side, which is
  exactly the honest form (device iterations saved by moving work to the
  host are not savings unless the host nodes are shown too).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional


@dataclasses.dataclass
class SearchStats:
    """Cumulative search-cost counters of one engine (or composition)."""

    engine: str = ""
    histories: int = 0          # histories this engine decided work for
    lockstep_iters: int = 0     # device lockstep cost (trips × width)
    nodes_explored: int = 0     # host search nodes (oracle + middles)
    memo_prunes: int = 0        # memo hits: subtrees skipped
    memo_inserts: int = 0       # configurations proven non-linearizable
    compactions: int = 0        # batch-shrink / cache-growth events
    chunk_rounds: int = 0       # device chunk calls
    rescued: int = 0            # lanes decided past the base budget
    deferred: int = 0           # histories deferred to the oracle
    tail_histories: int = 0     # hybrid: lanes the host tail decided
    segments_split: int = 0     # segdc: histories that actually cut
    segments_total: int = 0     # segdc: segments across them
    # P-compositionality (ops/pcomp.py): the per-key decomposition's own
    # cost/shape record — how many histories split, into how many per-key
    # sub-histories, how long the worst sub-history stayed (the number
    # that decides whether the split bought smaller compile buckets), and
    # what recombining verdicts + stitching witnesses cost host-side
    pcomp_split: int = 0        # histories decomposed per key
    pcomp_subs: int = 0         # per-key sub-histories produced
    pcomp_max_sub: int = 0      # longest sub-history (ops) — max-merged
    pcomp_recombine_ms: int = 0  # verdict recombine + witness stitch
    # Shrink plane (qsm_tpu/shrink): frontier-at-once counterexample
    # minimization — how many greedy rounds ran, how many candidate
    # lanes the frontier dispatches carried, how many candidates the
    # fingerprint memo answered without re-checking, and how small the
    # minimized history ended up relative to the input (percent of the
    # initial op count; min-merged — the record's best shrink).  A
    # shrink run's cost record must say it shrank, and to what.
    shrink_rounds: int = 0      # greedy frontier rounds
    shrink_lanes: int = 0       # candidate lanes dispatched
    shrink_memo_hits: int = 0   # candidates answered from the memo
    shrink_ratio_pct: int = 0   # 100 * final_ops / initial_ops (0 = none)
    ordering: bool = False      # postcondition-aware ordering active
    plan: str = ""              # planner provenance ("" = hand-tuned)
    # resilience plane (qsm_tpu/resilience): device-loss accounting —
    # cost records from a degraded run must SAY they degraded, or a
    # host-fallback rate silently masquerades as a device rate
    degradations: int = 0       # device-loss events absorbed
    retries: int = 0            # extra dispatch attempts before degrading
    fallback_engine: str = ""   # host engine degraded onto ("" = none)
    worker_faults: int = 0      # pool workers shed (crash/wedge/kill)
    # while deciding these lanes — serve/pool.py stamps it so a batch
    # that survived a worker loss says so in its own cost record
    node_faults: int = 0        # fleet nodes lost (death/wedge/partition)
    # while deciding these lanes — fleet/router.py stamps it so a batch
    # that survived a node loss (re-dispatched to a surviving node or
    # the router's own ladder) says so in its own cost record
    lease_faults: int = 0       # lease-store beats lost (fault/transport)
    # while this work ran — the HA plane's cost record: a soak that
    # rode out lease-store partitions must say how many beats the
    # arbitration lost (fleet/lease.py `lease` fault site)
    # span<->stats bridge (qsm_tpu/obs): trace events emitted while
    # deciding these lanes.  The serve dispatch path stamps it into the
    # batch's compact record and the batch's `serve.dispatch` span
    # event carries the compact record back — observability cost is
    # accounted like any other search cost, in both directions.
    obs_events: int = 0
    # Monitor plane (qsm_tpu/monitor): the streaming-session cost/shape
    # record — events streamed through sessions, quiescent cuts the
    # frontiers committed, cuts resumed from the prefix bank with ZERO
    # engine work, and verdict flips pushed to clients.  A monitoring
    # run's record must say how much of its deciding was incremental.
    session_events: int = 0      # events applied to live sessions
    frontier_advances: int = 0   # quiescent cuts committed
    flips_pushed: int = 0        # violation flips handed to clients
    prefix_hits: int = 0         # cuts resumed from the decided-prefix bank
    # Generation plane (qsm_tpu/gen): the workload-fuzzer's cost/shape
    # record — command sequences generated, profile/seed mutations the
    # steering loop applied, verdict flips (violations) its corpora
    # induced, and feedback rounds scored.  A fuzz campaign's record
    # must say how much adversarial workload it manufactured — and the
    # oracle's own counters above stay untouched: generation never
    # contributes to a verdict (docs/GENERATION.md soundness note).
    gen_seqs: int = 0            # command sequences (histories) generated
    gen_mutations: int = 0       # profile/seed mutations applied
    gen_flips: int = 0           # violations induced by generated corpora
    gen_feedback_rounds: int = 0  # steering rounds scored

    # -- derived -----------------------------------------------------------
    @property
    def iters_per_history(self) -> float:
        return self.lockstep_iters / self.histories if self.histories else 0.0

    @property
    def nodes_per_history(self) -> float:
        return self.nodes_explored / self.histories if self.histories else 0.0

    # -- composition -------------------------------------------------------
    def absorb(self, other: Optional["SearchStats"],
               count_histories: bool = False) -> "SearchStats":
        """Fold a sub-engine's counters into this record (hybrid tails,
        segdc inners).  ``count_histories`` is off by default: a wrapper
        usually counts each input history once itself, and the inner's
        per-lane count (expansions, frontier states) would double-book."""
        if other is None:
            return self
        for f in ("lockstep_iters", "nodes_explored", "memo_prunes",
                  "memo_inserts", "compactions", "chunk_rounds", "rescued",
                  "deferred", "tail_histories", "segments_split",
                  "segments_total", "degradations", "retries",
                  "worker_faults", "node_faults", "lease_faults",
                  "pcomp_split",
                  "pcomp_subs", "pcomp_recombine_ms", "shrink_rounds",
                  "shrink_lanes", "shrink_memo_hits", "obs_events",
                  "session_events", "frontier_advances", "flips_pushed",
                  "prefix_hits", "gen_seqs", "gen_mutations", "gen_flips",
                  "gen_feedback_rounds"):
            setattr(self, f, getattr(self, f) + getattr(other, f))
        # a maximum, not a tally: the composed record's worst sub-history
        # is the worst either side saw
        self.pcomp_max_sub = max(self.pcomp_max_sub, other.pcomp_max_sub)
        # a ratio, not a tally: the composed record keeps the BEST
        # (smallest) shrink either side achieved; 0 means "never shrank"
        if other.shrink_ratio_pct:
            self.shrink_ratio_pct = (
                other.shrink_ratio_pct if not self.shrink_ratio_pct
                else min(self.shrink_ratio_pct, other.shrink_ratio_pct))
        if count_histories:
            self.histories += other.histories
        self.ordering = self.ordering or other.ordering
        if not self.plan:
            self.plan = other.plan
        if not self.fallback_engine:
            self.fallback_engine = other.fallback_engine
        return self

    # -- rendering ---------------------------------------------------------
    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["iters_per_history"] = round(self.iters_per_history, 1)
        d["nodes_per_history"] = round(self.nodes_per_history, 1)
        return d

    def to_compact(self) -> Dict:
        """The small form bench lines embed (MAX_LINE discipline): the
        two per-history headline numbers plus the counters that explain
        them; everything else stays in the full ``stats`` CLI output."""
        return {
            "iph": round(self.iters_per_history, 1),
            "nph": round(self.nodes_per_history, 1),
            "prunes": self.memo_prunes,
            "rescued": self.rescued,
            "segs": self.segments_split,
            "ord": int(self.ordering),
            "plan": self.plan,
            # resilience counters ride every compact record so bench
            # rows are self-describing about fault handling (a degraded
            # rate must never read as a clean device rate)
            "deg": self.degradations,
            "fb": self.fallback_engine,
            "wf": self.worker_faults,
            "ndf": self.node_faults,
            "lsf": self.lease_faults,
            # P-compositionality counters ride every compact record too:
            # a bench row from a decomposed run must say it decomposed
            # (and into what) or its rate reads as a whole-history rate
            "pcs": self.pcomp_split,
            "pcn": self.pcomp_subs,
            "pcm": self.pcomp_max_sub,
            # shrink counters ride every compact record the same way: a
            # bench row produced during minimization must say how many
            # rounds/lanes the shrink plane spent and what it bought
            "shr": self.shrink_rounds,
            "shl": self.shrink_lanes,
            "shm": self.shrink_memo_hits,
            "sho": self.shrink_ratio_pct,
            # span<->stats bridge: trace events this record's work
            # emitted (qsm_tpu/obs) — a traced batch's cost record
            # says what the tracing itself cost
            "obe": self.obs_events,
            # monitor-session counters (qsm_tpu/monitor): a monitoring
            # bench row must say how many events streamed, how many
            # cuts committed, how many resumed from the bank for free,
            # and how many flips the run pushed
            "sev": self.session_events,
            "fad": self.frontier_advances,
            "flp": self.flips_pushed,
            "pfh": self.prefix_hits,
            # generation-plane counters (qsm_tpu/gen): a bench row from
            # a fuzz campaign must say how many sequences it generated,
            # how many mutations steering applied, how many verdict
            # flips the corpora induced, and how many feedback rounds
            # were scored
            "gsq": self.gen_seqs,
            "gmu": self.gen_mutations,
            "gfl": self.gen_flips,
            "gfr": self.gen_feedback_rounds,
        }

    def to_timings(self) -> Dict[str, float]:
        """Numeric projection for ``PropertyResult.timings`` (a flat
        str → float mapping by contract).  Resilience counters appear
        only when nonzero: the property layer keeps its OWN
        ``resilience_*`` entries for degradations it performed itself
        (core/property.py), and the two sources merge additively there —
        emitting zeros here would clobber that accounting."""
        out = {
            "search_iters_per_history": round(self.iters_per_history, 1),
            "search_nodes_per_history": round(self.nodes_per_history, 1),
            "search_memo_prunes": float(self.memo_prunes),
            "search_rescued": float(self.rescued),
            "search_histories": float(self.histories),
        }
        if self.degradations:
            out["resilience_degradations"] = float(self.degradations)
        if self.retries:
            out["resilience_retries"] = float(self.retries)
        if self.worker_faults:
            out["resilience_worker_faults"] = float(self.worker_faults)
        if self.node_faults:
            out["resilience_node_faults"] = float(self.node_faults)
        if self.lease_faults:
            out["resilience_lease_faults"] = float(self.lease_faults)
        # pcomp accounting only when decomposition actually happened —
        # zeros would claim "pcomp ran, split nothing" on every
        # whole-history run
        if self.pcomp_subs:
            out["pcomp_split"] = float(self.pcomp_split)
            out["pcomp_subs"] = float(self.pcomp_subs)
            out["pcomp_max_sub"] = float(self.pcomp_max_sub)
            out["pcomp_recombine_ms"] = float(self.pcomp_recombine_ms)
        # shrink accounting only when minimization actually ran — zeros
        # would claim "shrank to nothing" on every plain check run
        if self.shrink_rounds:
            out["shrink_rounds"] = float(self.shrink_rounds)
            out["shrink_lanes"] = float(self.shrink_lanes)
            out["shrink_memo_hits"] = float(self.shrink_memo_hits)
            out["shrink_ratio"] = round(self.shrink_ratio_pct / 100.0, 3)
        # span-bridge accounting only when tracing actually emitted —
        # zeros would claim "traced, emitted nothing" on every
        # tracing-off run
        if self.obs_events:
            out["obs_events"] = float(self.obs_events)
        # session accounting only when events actually streamed — zeros
        # would claim "monitored, saw nothing" on every batch-check run
        if self.session_events:
            out["session_events"] = float(self.session_events)
            out["frontier_advances"] = float(self.frontier_advances)
            out["flips_pushed"] = float(self.flips_pushed)
            out["prefix_hits"] = float(self.prefix_hits)
        # generation accounting only when the fuzzer actually generated
        # — zeros would claim "fuzzed, produced nothing" on every plain
        # check run
        if self.gen_seqs:
            out["gen_seqs"] = float(self.gen_seqs)
            out["gen_mutations"] = float(self.gen_mutations)
            out["gen_flips"] = float(self.gen_flips)
            out["gen_feedback_rounds"] = float(self.gen_feedback_rounds)
        return out


_COUNTER_FIELDS = ("histories", "lockstep_iters", "nodes_explored",
                   "memo_prunes", "memo_inserts", "compactions",
                   "chunk_rounds", "rescued", "deferred", "tail_histories",
                   "segments_split", "segments_total", "degradations",
                   "retries", "worker_faults", "node_faults",
                   "lease_faults",
                   "pcomp_split", "pcomp_subs", "pcomp_recombine_ms",
                   "shrink_rounds", "shrink_lanes", "shrink_memo_hits",
                   "obs_events", "session_events", "frontier_advances",
                   "flips_pushed", "prefix_hits", "gen_seqs",
                   "gen_mutations", "gen_flips", "gen_feedback_rounds")
# pcomp_max_sub and shrink_ratio_pct are deliberately NOT delta fields:
# a maximum/ratio has no meaningful "per-run difference", so stats_delta
# keeps `after`'s value.


def stats_delta(after: Optional[SearchStats],
                before: Optional[SearchStats]) -> Optional[SearchStats]:
    """``after - before`` over the counter fields: the cost of ONE run on
    an engine whose instance counters are lifetime-cumulative.  The
    property layer uses this so ``PropertyResult.timings`` stays per-run
    like every other entry in that dict, even when the caller reuses a
    backend object across property runs."""
    if after is None:
        return None
    if before is None:
        return after
    d = dataclasses.replace(after)
    for f in _COUNTER_FIELDS:
        setattr(d, f, getattr(after, f) - getattr(before, f))
    return d


def collect_search_stats(backend) -> Optional[SearchStats]:
    """``SearchStats`` for any backend, or None when it exposes none.

    Engines own their accounting (``search_stats()``); this helper only
    adds the generic fallback so callers (property layer, bench, CLI)
    never need per-engine knowledge.  Unknown combinators are probed for
    the conventional wrapper attributes (``inner`` / ``device`` /
    ``plain``) so e.g. the per-history router still reports its kernels'
    counters.
    """
    fn = getattr(backend, "search_stats", None)
    if callable(fn):
        return fn()
    for attr in ("inner", "device", "plain"):
        sub = getattr(backend, attr, None)
        if sub is not None and callable(getattr(sub, "search_stats", None)):
            st = sub.search_stats()
            st.engine = f"{type(backend).__name__.lower()}({st.engine})"
            return st
    return None
