"""qsm_tpu — TPU-native state-machine property testing & linearizability
checking, with the capability surface of
``advancedtelematic/quickcheck-state-machine-distributed`` (see SURVEY.md).

Layers (mirroring SURVEY.md §1, redesigned TPU-first):

* ``qsm_tpu.core``     — spec protocol, history encoding, generation/shrinking,
  sequential runner (reference L3/L6 pure parts)
* ``qsm_tpu.sched``    — deterministic PULSE-style scheduler, in-memory actor
  transport, concurrent runner, fault injection (reference L0–L2, L4)
* ``qsm_tpu.ops``      — linearisers: ``WingGongCPU`` oracle and the batched
  ``JaxTPU`` branch-and-bound kernel (reference L5)
* ``qsm_tpu.models``   — the five milestone specs + correct/racy SUT pairs
  (reference L7)
* ``qsm_tpu.mesh``     — the mesh-sharded dispatch substrate: ONE
  NamedSharding lane axis under every check plane (plain batches, pcomp
  sub-lanes, shrink frontiers, monitor re-checks, serve fan-out), with
  mesh-divisible compile buckets and bit-identical verdicts at any mesh
  shape (docs/MESH.md; ``qsm_tpu.parallel`` is its deprecated re-export)
* ``qsm_tpu.analysis`` — ``qsmlint``: static spec/kernel/determinism
  analysis that catches window-burning defects before any TPU window
  opens (docs/ANALYSIS.md)
* ``qsm_tpu.serve``    — the serving plane: long-lived check server
  with warm engines, cross-request micro-batching, a persistent
  verdict cache and bounded admission (docs/SERVING.md)
* ``qsm_tpu.shrink``   — the batched shrink plane: frontier-at-once
  counterexample minimization to 1-minimal histories with
  verify_witness-replayable certificates, served as the ``shrink``
  verb (docs/SHRINK.md)
* ``qsm_tpu.obs``      — the observability plane: request-scoped
  spans with trace-id propagation through the whole serving stack,
  a live metrics registry (Prometheus ``/metrics``, ``stats
  --watch``), and a crash flight recorder (docs/OBSERVABILITY.md)
* ``qsm_tpu.fleet``    — the multi-node serving tier: a
  protocol-identical router over N check-server nodes with
  consistent-hash routing by the verdict-cache identity, node
  quarantine/re-admission, bounded node-loss re-dispatch, and a
  segmented replicated verdict log with anti-entropy catch-up —
  de-SPOF'd end to end: router HA behind a filesystem lease
  (split-brain-safe term takeover), node-to-node gossip replication,
  and row-level segment subsumption (docs/SERVING.md "Fleet" /
  "Router HA")
* ``qsm_tpu.monitor``  — the streaming monitor plane: per-session
  incremental quiescent-cut frontiers deciding a live event stream
  the moment each prefix is decidable, decided prefixes banked in
  the verdict cache under rolling prefix fingerprints (restarts
  resume from the bank), flips pushed with shrink-plane-minimized
  repros (docs/MONITOR.md)
* ``qsm_tpu.ingest``   — foreign trace ingest: Jepsen/Knossos- and
  porcupine-style event logs as first-class corpora (byte-stable
  round trips) plus the live log tailer behind ``qsm-tpu monitor``
* ``qsm_tpu.utils``    — config, structured logging, CLI
"""

from .core.spec import CmdSig, Spec, compile_step_table
from .core.history import (EncodedBatch, History, Op, encode_batch,
                           overlapping_history, sequential_history)
from .core.generator import Program, ProgOp, generate_program, shrink_candidates
from .core.sequential import (ModelSUT, prop_sequential,
                              run_sequential)
from .core.property import (Counterexample, PropertyConfig, PropertyResult,
                            prop_concurrent, replay, trial_seed)
from .ops.backend import (LineariseBackend, Verdict, check_one,
                          verify_witness)
from .ops.wing_gong_cpu import WingGongCPU
from .sched.scheduler import FaultPlan, Monitor, Recv, Scheduler, Send
from .sched.runner import ConcurrentSUT, run_concurrent

__version__ = "0.1.0"
