"""Systematic schedule exploration — bounded-exhaustive model checking.

The property layer samples k SEEDED schedules per program (core/property.py,
the reference's QuickCheck approach).  This module replaces sampling with
ENUMERATION for small programs: every delivery-order decision the scheduler
can make is explored depth-first, every distinct history is collected, and
the whole set is decided in ONE batched checker call — turning "k random
schedules found nothing" into "all N interleavings explored, none violate",
a certainty the reference family cannot produce.

How it composes with the scheduler: delivery choice is the scheduler's only
nondeterminism (process step order is fixed — sched/scheduler.py), and
``Scheduler(choices=...)`` replays a scripted prefix then defaults to
choice 0, logging the branching factor at every delivery.  Determinism
makes tree search stateless: running prefix ``p`` reveals the branching
factors along ``p``'s leftmost completion, and lexicographic backtracking
over the logged factors enumerates the full tree without ever storing it.

Fault injection is refused here: fault decisions draw from the seeded RNG,
which scripted replay deliberately bypasses — sampling (prop_concurrent
with a FaultPlan) remains the way to explore faulty executions.

The batching story is the TPU story: enumeration yields hundreds-to-
thousands of small histories per program, exactly the shape the device
kernel's vmap batch wants (SURVEY.md §2b trial/batch parallelism).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.history import History
from ..ops.backend import LineariseBackend, Verdict
from .runner import prepare_run
from .scheduler import FaultPlan


@dataclasses.dataclass
class ExploreResult:
    """Outcome of exploring one program's interleaving tree."""

    schedules_run: int
    distinct_histories: int
    exhausted: bool         # True: the WHOLE tree fit under max_schedules
    violations: int
    undecided: int
    seconds: float
    violating: Optional[History] = None  # first violating history, if any

    @property
    def ok(self) -> bool:
        return self.violations == 0

    @property
    def verified(self) -> bool:
        """Every interleaving explored AND decided, none violating — the
        certainty claim.  False whenever the tree was truncated or any
        history came back undecided."""
        return self.exhausted and self.violations == 0 and self.undecided == 0


def schedule_key(choices: Sequence[int]) -> str:
    """Stamp a delivery-choice script into a history seed string.  The
    ONE encode site; :func:`parse_schedule_key` is its inverse (replay
    regressions store this string, so the pair must never drift)."""
    return "explore:" + ",".join(map(str, choices))


def parse_schedule_key(seed_key) -> Optional[List[int]]:
    """The choice script from a :func:`schedule_key` stamp, or None when
    ``seed_key`` is not an exploration stamp (an ordinary seeded run)."""
    if not (isinstance(seed_key, str) and seed_key.startswith("explore:")):
        return None
    body = seed_key[len("explore:"):]
    return [int(x) for x in body.split(",") if x != ""]


def _next_prefix(choices: List[int], factors: List[int]
                 ) -> Optional[List[int]]:
    """Lexicographic successor: the deepest position that still has an
    untried sibling, bumped; None when the tree is exhausted."""
    for i in range(len(factors) - 1, -1, -1):
        c = choices[i] if i < len(choices) else 0
        if c + 1 < factors[i]:
            return (choices[:i] if i < len(choices)
                    else choices + [0] * (i - len(choices))) + [c + 1]
    return None


def _summarize(hists: List[History], verdicts
               ) -> Tuple[int, int, Optional[History]]:
    """(violations, undecided, first violating history) — the ONE verdict
    accounting site for explore_program and explore_many."""
    violations = int((verdicts == int(Verdict.VIOLATION)).sum())
    undecided = int((verdicts == int(Verdict.BUDGET_EXCEEDED)).sum())
    violating = None
    for h, v in zip(hists, verdicts):
        if int(v) == int(Verdict.VIOLATION):
            violating = h
            break
    return violations, undecided, violating


def _enumerate(sut_factory, program, max_schedules: int, max_steps: int
               ) -> Tuple[List[History], int, bool]:
    """Walk one program's delivery-choice tree depth-first: (distinct
    histories, schedules run, whole tree fit under max_schedules)."""
    histories: Dict[Tuple, History] = {}
    prefix: Optional[List[int]] = []
    schedules = 0
    exhausted = True
    while prefix is not None:
        if schedules >= max_schedules:
            exhausted = False
            break
        sched, rec = prepare_run(sut_factory(), program, seed=0,
                                 max_steps=max_steps, choices=prefix)
        sched.run()
        schedules += 1
        h = rec.history(seed=schedule_key(prefix))
        histories.setdefault(h.fingerprint(), h)
        prefix = _next_prefix(prefix, sched.choice_log)
    return list(histories.values()), schedules, exhausted


def explore_program(
    sut_factory: Callable[[], object],
    program,
    spec,
    backend: Optional[LineariseBackend] = None,
    max_schedules: int = 10_000,
    max_steps: int = 100_000,
    faults: Optional[FaultPlan] = None,
    check: bool = True,
) -> ExploreResult:
    """Enumerate every delivery schedule of ``program`` (up to
    ``max_schedules``), then decide all distinct histories in one batched
    checker call.

    ``backend`` picks the checker (default: the framework's fastest host
    oracle via ``core.property._default_oracle``); a fresh SUT is built
    per schedule from ``sut_factory`` (state must not leak between
    runs — same contract as the property layer's executions).

    ``check=False`` enumerates only (for coverage ground truth): every
    history reports as undecided, so ``verified`` can never be claimed
    from an unchecked run.
    """
    if faults is not None:
        raise ValueError(
            "systematic exploration is incompatible with fault injection "
            "(fault decisions are seeded draws, which scripted replay "
            "bypasses); use prop_concurrent sampling for faulty runs")
    t0 = time.perf_counter()
    hists, schedules, exhausted = _enumerate(sut_factory, program,
                                             max_schedules, max_steps)
    if not check:
        return ExploreResult(
            schedules_run=schedules, distinct_histories=len(hists),
            exhausted=exhausted, violations=0, undecided=len(hists),
            seconds=round(time.perf_counter() - t0, 3))
    if backend is None:
        from ..core.property import _default_oracle

        backend = _default_oracle(spec)
    verdicts = (backend.check_histories(spec, hists) if hists
                else np.empty(0, np.int8))
    violations, undecided, violating = _summarize(hists, verdicts)
    return ExploreResult(
        schedules_run=schedules, distinct_histories=len(hists),
        exhausted=exhausted, violations=violations, undecided=undecided,
        seconds=round(time.perf_counter() - t0, 3), violating=violating)


def explore_many(
    sut_factory: Callable[[], object],
    programs: Sequence,
    spec,
    backend: Optional[LineariseBackend] = None,
    max_schedules: int = 10_000,
    max_steps: int = 100_000,
) -> List[ExploreResult]:
    """Explore MANY programs, deciding the union of all their distinct
    histories in ONE batched checker call — the vmap-shaped workload the
    device kernel exists for (BASELINE.json:9: ≥1024 histories per
    batch): N small interleaving trees enumerate host-side, the
    exponential decisions all ride one dispatch.  Returns one
    :class:`ExploreResult` per program.

    Enumeration is identical to :func:`explore_program` per program
    (same default ``max_schedules``); DECIDED verdicts are identical
    too, but a budget-bounded device backend may defer differently —
    its memo-cache size depends on the batch bucket (JaxTPU
    ``MAX_SLOTS_FOR_BATCH``), so a history decided in a small
    per-program batch can come back BUDGET_EXCEEDED in the larger
    union batch (never the reverse direction of a wrong verdict; the
    per-program ``undecided`` count reports it).
    """
    if backend is None:
        from ..core.property import _default_oracle

        backend = _default_oracle(spec)
    per_prog = []
    flat: List[History] = []
    for prog in programs:
        t0 = time.perf_counter()
        hists, schedules, exhausted = _enumerate(sut_factory, prog,
                                                 max_schedules, max_steps)
        per_prog.append((slice(len(flat), len(flat) + len(hists)),
                         schedules, exhausted,
                         time.perf_counter() - t0))
        flat.extend(hists)
    t0 = time.perf_counter()
    verdicts = (backend.check_histories(spec, flat) if flat
                else np.empty(0, np.int8))
    check_dt = time.perf_counter() - t0
    out = []
    for sl, schedules, exhausted, enum_dt in per_prog:
        hs = flat[sl]
        violations, undecided, violating = _summarize(hs, verdicts[sl])
        # per-program seconds like explore_program's: own enumeration
        # time plus this program's share of the one batched check call
        # (apportioned by history count — the batch cost driver)
        share = check_dt * (len(hs) / len(flat)) if flat else 0.0
        out.append(ExploreResult(
            schedules_run=schedules, distinct_histories=len(hs),
            exhausted=exhausted, violations=violations,
            undecided=undecided, seconds=round(enum_dt + share, 3),
            violating=violating))
    return out


def shrink_explored(
    sut_factory: Callable[[], object],
    program,
    spec,
    backend: Optional[LineariseBackend] = None,
    max_schedules: int = 2_000,
    max_rounds: int = 50,
    initial: Optional[ExploreResult] = None,
):
    """Minimize a program whose exploration found a violation.

    QuickCheck-style greedy shrink, but the predicate is EXPLORATION:
    a candidate program survives iff exhaustively exploring it (bounded
    per candidate by ``max_schedules``) still finds a violating
    interleaving.  The result is therefore stronger than the property
    layer's shrink — the minimal program is violating under SOME
    schedule, found by search rather than by replaying one seed's
    schedule, so shrinking cannot lose the race by schedule drift.

    Returns ``(program, ExploreResult, shrink_steps)`` for the smallest
    still-violating program (the input's own result if nothing smaller
    violates).  Pass the program's already-computed result as
    ``initial`` to skip re-exploring it (exploration is deterministic,
    so the caller's result is exactly what a fresh run would produce).
    """
    from ..core.generator import dedupe, shrink_candidates

    best_prog = program
    best_res = (initial if initial is not None
                else explore_program(sut_factory, program, spec,
                                     backend=backend,
                                     max_schedules=max_schedules))
    if best_res.violations == 0:
        return best_prog, best_res, 0
    steps = 0
    for _ in range(max_rounds):
        improved = False
        for cand in dedupe(shrink_candidates(spec, best_prog), limit=256):
            if len(cand) >= len(best_prog):
                continue
            res = explore_program(sut_factory, cand, spec, backend=backend,
                                  max_schedules=max_schedules)
            if res.violations > 0:
                best_prog, best_res = cand, res
                steps += 1
                improved = True
                break  # greedy: restart candidate stream from the smaller
        if not improved:
            return best_prog, best_res, steps
    return best_prog, best_res, steps
